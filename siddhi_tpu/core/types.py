"""Attribute type system for the TPU-native CEP engine.

Mirrors the reference's attribute types (reference:
modules/siddhi-query-api/.../definition/Attribute.java — STRING, INT, LONG,
FLOAT, DOUBLE, BOOL, OBJECT) but maps them to device dtypes:

- INT    -> int32   (Java int, wrapping arithmetic)
- LONG   -> int64   (Java long)
- FLOAT  -> float32
- DOUBLE -> float64 (jax x64 enabled at import of siddhi_tpu)
- BOOL   -> bool
- STRING -> int32 dictionary codes (host-side interning; see StringTable)
- OBJECT -> host-only (cannot cross to device; gated at plan time)

Java-style binary numeric promotion (JLS 5.6.2) is used for arithmetic and
comparisons, matching the typed executor selection in the reference's
ExpressionParser (modules/siddhi-core/.../util/parser/ExpressionParser.java:206).
"""
from __future__ import annotations

import enum
import threading

import numpy as np


class AttrType(enum.Enum):
    STRING = "string"
    INT = "int"
    LONG = "long"
    FLOAT = "float"
    DOUBLE = "double"
    BOOL = "bool"
    OBJECT = "object"

    @classmethod
    def from_name(cls, name: str) -> "AttrType":
        return cls(name.lower())


NUMERIC_TYPES = (AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE)

_NP_DTYPES = {
    AttrType.STRING: np.int32,   # dictionary code
    AttrType.INT: np.int32,
    AttrType.LONG: np.int64,
    AttrType.FLOAT: np.float32,
    AttrType.DOUBLE: np.float64,
    AttrType.BOOL: np.bool_,
}


def np_dtype(t: AttrType):
    if t is AttrType.OBJECT:
        raise TypeError("OBJECT attributes cannot be placed on device")
    return _NP_DTYPES[t]


# Shared promotion lattice (exported: ops/expr.py applies it at compile
# time, analysis/typecheck.py mirrors it statically — one table, not two)
PROMOTION_ORDER = {
    AttrType.INT: 0,
    AttrType.LONG: 1,
    AttrType.FLOAT: 2,
    AttrType.DOUBLE: 3,
}
_PROMOTION_ORDER = PROMOTION_ORDER  # backward-compat alias


def promote(a: AttrType, b: AttrType) -> AttrType:
    """Java binary numeric promotion: the wider of the two operand types."""
    if a not in PROMOTION_ORDER or b not in PROMOTION_ORDER:
        raise TypeError(f"cannot apply numeric promotion to {a} and {b}")
    order = max(PROMOTION_ORDER[a], PROMOTION_ORDER[b])
    for t, o in PROMOTION_ORDER.items():
        if o == order:
            return t
    raise AssertionError


def can_coerce(src: AttrType, dst: AttrType) -> bool:
    """Whether a value of `src` widens losslessly-enough into a `dst`
    column under the promotion lattice (int->long->float->double).
    Equal types always coerce; non-numeric types only to themselves."""
    if src is dst:
        return True
    if src in PROMOTION_ORDER and dst in PROMOTION_ORDER:
        return PROMOTION_ORDER[src] <= PROMOTION_ORDER[dst]
    return False


def comparable(a: AttrType, b: AttrType) -> bool:
    """Whether `a <op> b` has device compare semantics: numeric pairs
    promote; STRING/BOOL compare only against themselves (STRING travels
    as int32 dictionary codes — comparing a code against a number is
    meaningless, so STRING vs numeric is rejected, never coerced)."""
    if a in NUMERIC_TYPES and b in NUMERIC_TYPES:
        return True
    return a is b and a in (AttrType.STRING, AttrType.BOOL)


# interned marker object for uuid() sentinel codes (identity-compared)
UUID_MARKER = "\x00uuid\x00"
# per-process namespace: uuid() values are unique across processes and
# stable across repeated decodes of the same row within one process
import uuid as _uuid_mod  # noqa: E402

_UUID_SALT = _uuid_mod.uuid4()


class StringTable:
    """Global host-side string interning: string <-> int32 dictionary code.

    The reference manipulates java.lang.String values directly inside the
    per-event executor trees; on TPU, strings travel as dictionary codes and
    only equality / group-by / join-key semantics are preserved on device
    (which is all the reference's hot paths use them for). Decoding happens in
    host callbacks.

    Code 0 is reserved for null.
    """

    NULL_CODE = 0

    def __init__(self):
        self._lock = threading.Lock()
        self._to_code: dict[str, int] = {}
        self._to_str: list = [None]  # code 0 -> null

    def encode(self, s) -> int:
        if s is None:
            return self.NULL_CODE
        s = str(s)
        code = self._to_code.get(s)
        if code is None:
            with self._lock:
                code = self._to_code.get(s)
                if code is None:
                    code = len(self._to_str)
                    self._to_str.append(s)
                    self._to_code[s] = code
        return code

    def decode(self, code: int, uuid_key=None):
        s = self._to_str[int(code)]
        if s == UUID_MARKER:
            # uuid() columns carry a sentinel code on device; the host
            # boundary materializes the UUID (UUIDFunctionExecutor.java
            # generates per-event UUIDs). With a uuid_key (timestamp/row/
            # column coordinates) the value is a salted deterministic
            # uuid5 so REPEATED decodes of the same emitted/stored row
            # agree across delivery paths; without one it is random.
            import uuid as _uuid
            if uuid_key is None:
                return str(_uuid.uuid4())
            return str(_uuid.uuid5(_UUID_SALT, repr(uuid_key)))
        return s

    def __len__(self):
        return len(self._to_str)


# Single process-wide table: codes are stable across apps/runtimes, which
# makes snapshots and cross-app streams trivially consistent.
GLOBAL_STRINGS = StringTable()


# ---------------------------------------------------------------------------
# SET values (createSet/unionSet/sizeOfSet): a set is a fixed-width int64
# vector [1 + SET_LANES] — lane 0 a type tag, lanes 1.. the encoded
# elements, empty lanes SET_EMPTY. Columns of AttrType.OBJECT carrying
# sets are 2D [rows, 1 + SET_LANES] on device and decode to frozensets.
# ---------------------------------------------------------------------------
SET_LANES = 32
SET_EMPTY = -(2 ** 62)
_SET_TAGS = {}
_SET_TAG_OF = {}


def set_tag_of(t: AttrType) -> int:
    order = [AttrType.INT, AttrType.LONG, AttrType.FLOAT, AttrType.DOUBLE,
             AttrType.BOOL, AttrType.STRING]
    if t not in order:
        raise ValueError(f"createSet() not supported for type {t}")
    return order.index(t) + 1


def decode_set(arr) -> frozenset:
    """Host boundary: [1 + SET_LANES] int64 -> frozenset."""
    import struct

    tag = int(arr[0])
    out = []
    for v in arr[1:]:
        v = int(v)
        if v == SET_EMPTY:
            continue
        if tag in (3, 4):        # FLOAT / DOUBLE bit patterns
            out.append(struct.unpack("<d", struct.pack("<q", v))[0])
        elif tag == 5:
            out.append(bool(v))
        elif tag == 6:
            out.append(GLOBAL_STRINGS.decode(v))
        else:
            out.append(v)
    return frozenset(out)


def col_zeros(t: AttrType, cap: int):
    """Zero column of device shape for one attribute: [cap] for
    primitives, [cap, 1 + SET_LANES] int64 for SET-carrying OBJECT."""
    import jax.numpy as jnp
    if t is AttrType.OBJECT:
        return jnp.full((cap, 1 + SET_LANES), jnp.int64(SET_EMPTY))
    return jnp.zeros((cap,), dtype=np_dtype(t))


def null_value(t: AttrType):
    """The in-band placeholder stored in the data column where null; the
    actual null signal is the per-column null mask."""
    if t is AttrType.STRING:
        return StringTable.NULL_CODE
    if t is AttrType.BOOL:
        return False
    return np_dtype(t)(0)
