"""Packed columnar ingest: the high-throughput host->device path.

The reference's ingest hot path is InputHandler.send -> Disruptor ring
buffer (stream/StreamJunction.java:255-313). The TPU equivalent is bound by
the host->device link, so the wire format matters:

- every 64-bit column (LONG/DOUBLE and the timestamp lane) is split into
  two 1-D 32-bit lanes host-side and recombined on device: the TPU runtime
  transfers 1-D 32-bit arrays several times faster than int64 (which takes
  a slow conversion path) or 2-D arrays (layout tiling);
- timestamps are delta-encoded against the chunk's first timestamp (int32
  offsets + one int64 base scalar): monotonic ms deltas are tiny and
  compress to almost nothing on compressing transports;
- the hi lanes of small-valued LONG columns are constant zero and likewise
  compress away;
- chunks are zero-padded to the bucket capacity (zero tails are free);
- the validity mask / kind lane / null masks are NOT transferred at all —
  they are reconstructed on device from the row count.

The jitted query step fuses unpacking with the operator chain, so ingest
costs one device_put per chunk and zero per-batch host round-trips.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .event import EventBatch, StreamSchema
from .types import AttrType

# lanes per attribute type in the packed wire format
_WIDE = (AttrType.LONG, AttrType.DOUBLE)


def lanes_of(t: AttrType) -> int:
    return 2 if t in _WIDE else 1


def _split64(a: np.ndarray, capacity: int):
    """64-bit numpy column -> (lo, hi) uint32 lanes, zero-padded."""
    n = a.shape[0]
    v = a.view(np.uint32).reshape(-1, 2)
    lo = np.zeros((capacity,), np.uint32)
    hi = np.zeros((capacity,), np.uint32)
    lo[:n] = v[:, 0]
    hi[:n] = v[:, 1]
    return lo, hi


def pack_columns(schema: StreamSchema, ts: np.ndarray, cols: Sequence,
                 capacity: int):
    """Host side: (ts, data columns) -> (parts tuple, base_ts, n).

    Returns None if the chunk cannot be delta-encoded (timestamp span
    exceeding int32 ms range ~ 24 days) — callers fall back to the
    EventBatch path.
    """
    ts = np.asarray(ts, dtype=np.int64)
    n = ts.shape[0]
    assert n <= capacity, (n, capacity)
    base = int(ts[0]) if n else 0
    span_ok = n == 0 or (int(ts[-1]) - base < 2 ** 31 and
                         int(ts.min()) >= base - 2 ** 31)
    if not span_ok:
        return None
    off = np.zeros((capacity,), np.int32)
    off[:n] = ts - base
    parts = [off]
    for t, c in zip(schema.types, cols):
        c = np.asarray(c)
        if t in _WIDE:
            want = np.int64 if t is AttrType.LONG else np.float64
            if c.dtype != want:
                c = c.astype(want)
            parts.extend(_split64(c, capacity))
        elif t is AttrType.FLOAT:
            buf = np.zeros((capacity,), np.float32)
            buf[:n] = c
            parts.append(buf)
        elif t is AttrType.BOOL:
            buf = np.zeros((capacity,), np.bool_)
            buf[:n] = c
            parts.append(buf)
        else:  # INT, STRING dictionary codes
            buf = np.zeros((capacity,), np.int32)
            buf[:n] = c
            parts.append(buf)
    return tuple(parts), base, n


def _join64(lo, hi):
    return (lo.astype(jnp.uint64) |
            (hi.astype(jnp.uint64) << jnp.uint64(32)))


def unpack_parts(schema: StreamSchema, parts, base_ts, n) -> EventBatch:
    """Device side (inside jit): packed lanes -> EventBatch.

    Rows >= n are padding; nulls are all-false (the packed path carries no
    nulls — null-bearing sends use the row path)."""
    capacity = parts[0].shape[0]
    ts = base_ts.astype(jnp.int64) + parts[0].astype(jnp.int64)
    cols = []
    i = 1
    for t in schema.types:
        if t is AttrType.LONG:
            cols.append(_join64(parts[i], parts[i + 1]).astype(jnp.int64))
            i += 2
        elif t is AttrType.DOUBLE:
            u = _join64(parts[i], parts[i + 1])
            cols.append(jax.lax.bitcast_convert_type(u, jnp.float64))
            i += 2
        else:
            cols.append(parts[i])
            i += 1
    valid = jnp.arange(capacity, dtype=jnp.int32) < n
    # padding rows get ts 0 would disturb nothing (valid=False), but keep
    # them at base_ts so monotonic-time invariants hold under lax ops
    return EventBatch(
        ts=jnp.where(valid, ts, base_ts.astype(jnp.int64)),
        cols=tuple(cols),
        nulls=tuple(jnp.zeros((capacity,), jnp.bool_) for _ in cols),
        kind=jnp.zeros((capacity,), jnp.int32),
        valid=valid,
    )


class PackedChunk:
    """One device-resident packed chunk, shared by every subscriber of a
    junction (transferred once)."""

    __slots__ = ("parts", "base_ts", "n", "last_ts")

    def __init__(self, parts, base_ts: int, n: int, last_ts: int):
        self.parts = parts          # tuple of device arrays
        self.base_ts = base_ts      # host int
        self.n = n                  # host int (rows used)
        self.last_ts = last_ts

    @classmethod
    def build(cls, schema: StreamSchema, ts, cols, capacity: int):
        packed = pack_columns(schema, ts, cols, capacity)
        if packed is None:
            return None
        parts, base, n = packed
        return cls(jax.device_put(parts), base, n, int(ts[-1]))
