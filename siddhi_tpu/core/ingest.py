"""Packed columnar ingest: the high-throughput host->device path.

The reference's ingest hot path is InputHandler.send -> Disruptor ring
buffer (stream/StreamJunction.java:255-313). The TPU equivalent is bound by
the host->device link (potentially a slow tunnel: ~10 MB/s with ~70 ms
round-trip latency was measured on this image), so the wire format matters
more than anything else on the ingest side:

- EVERYTHING for a chunk travels in ONE 1-D uint8 buffer = one transfer =
  one RTT (a tuple of per-column arrays pays the round-trip per array);
- every dynamic scalar (row count, base timestamp, processing time, per-
  column bases) is embedded in the buffer header, so the jitted step takes
  no separate scalar arguments at all;
- each column is adaptively narrowed per chunk: constant columns ship zero
  bytes (base in the header), integer/string/long columns ship min-offset
  deltas in the narrowest of u8/u16/u32, timestamps detect arithmetic
  progressions ('aff': zero bytes + stride in the header), bools bit-pack
  to 1 bit/row, floats ship raw bits;
- encodings are STICKY per stream (they only ever widen), because the
  encoding tuple is part of the jit cache key — flapping between widths
  would trigger recompiles;
- chunks are zero-padded to the bucket capacity (zero tails compress to
  nothing on compressing transports and cost little raw);
- the validity mask / kind lane / null masks are NOT transferred at all —
  they are reconstructed on device from the header row count.

The jitted query step fuses unpacking with the operator chain, so ingest
costs one device_put per chunk and zero per-batch host round-trips.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .event import EventBatch, StreamSchema
from .types import AttrType

_INT_FAMILY = (AttrType.INT, AttrType.STRING, AttrType.LONG)

# lane byte-width per row for each encoding code
_CODE_BYTES = {"c": 0, "aff": 0, "d8": 1, "d16": 2, "d32": 4,
               "f32": 4, "f64": 8, "raw64": 8}
# widening order within each family (sticky codes only move right)
_ORDER = ("c", "aff", "b1", "f32", "f64", "d8", "d16", "d32", "raw64")
_RANK = {c: i for i, c in enumerate(_ORDER)}


def _pad8(x: int) -> int:
    return (x + 7) & ~7


def _lane_bytes(code: str, capacity: int) -> int:
    if code == "b1":
        return capacity // 8
    return _CODE_BYTES[code] * capacity


def layout(n_cols: int, enc: tuple, capacity: int):
    """(header bytes, per-lane byte offsets, total buffer bytes).

    enc = (ts_code, col_code...). Header int64 slots:
    [0]=n, [1]=base_ts, [2]=now, [3]=ts_stride, [4+i]=col i base."""
    H = (4 + n_cols) * 8
    offs = []
    o = H
    for code in enc:
        offs.append(o)
        o += _pad8(_lane_bytes(code, capacity))
    return H, offs, o


def initial_encoding(schema: StreamSchema) -> tuple:
    """The sticky encoding a fresh PackedEncoder starts from (affine
    timestamps, every column constant). This is the encoding tuple the
    FIRST chunk of a stream compiles against unless the data forces a
    widening — the AOT compile service (core/compile.py) precompiles
    packed steps for it so cold starts hit a ready program."""
    return ("aff",) + ("c",) * len(schema.types)


def encoding_for_sample(schema: StreamSchema, ts, cols,
                        now: int = 0) -> tuple:
    """The sticky encoding a traffic sample settles on: run a throwaway
    encoder over the sample and return its (widened) tuple. Lets
    warmup() precompile the packed step real traffic will dispatch."""
    enc = PackedEncoder(schema)
    ts = np.ascontiguousarray(ts, dtype=np.int64)
    _, tup, _ = enc.encode(ts, cols, _sample_capacity(len(ts)), now)
    return tup


def _sample_capacity(n: int) -> int:
    cap = 8
    while cap < n:
        cap *= 2
    return cap


def zero_packed_buffer(schema: StreamSchema, enc: tuple, capacity: int):
    """A device-resident all-zero packed buffer for (enc, capacity) —
    the abstract argument the compile service warms packed steps with
    (header decodes as n=0: every row is padding)."""
    _, _, total = layout(len(schema.types), enc, capacity)
    return jax.device_put(np.zeros((total,), np.uint8))


def _int_code(span: int) -> str:
    if span < 2 ** 8:
        return "d8"
    if span < 2 ** 16:
        return "d16"
    if span < 2 ** 32:
        return "d32"
    return "raw64"


class PackedEncoder:
    """Per-stream sticky encoding chooser: codes only widen across chunks
    (each distinct encoding tuple is a separate XLA compile)."""

    def __init__(self, schema: StreamSchema):
        self.schema = schema
        self._ts_code = "aff"
        self._col_codes = ["c"] * len(schema.types)

    def _widen(self, cur: str, cand: str) -> str:
        return cand if _RANK[cand] > _RANK[cur] else cur

    def encode(self, ts: np.ndarray, cols: Sequence, capacity: int,
               now: int):
        """-> (buf np.uint8[total], enc tuple, n)."""
        assert capacity % 8 == 0, capacity
        ts = np.ascontiguousarray(ts, dtype=np.int64)
        n = int(ts.shape[0])
        types = self.schema.types

        # --- choose codes -------------------------------------------------
        if n >= 2:
            stride = int(ts[1]) - int(ts[0])
            is_aff = bool(np.all(np.diff(ts) == stride))
        else:
            stride, is_aff = 0, True
        tmin = int(ts.min()) if n else 0
        base_ts = int(ts[0]) if is_aff and n else tmin
        span_code = _int_code(int(ts.max()) - tmin) if n else "d8"
        ts_cand = "aff" if is_aff else span_code
        self._ts_code = self._widen(self._ts_code, ts_cand)
        if self._ts_code != "aff":
            # once on a delta code, the width must cover THIS chunk's span
            # even when the chunk itself is affine (offsets would wrap)
            self._ts_code = self._widen(self._ts_code, span_code)
        ts_code = self._ts_code
        if ts_code != "aff":
            base_ts = tmin  # offsets must be non-negative

        ncols = []
        bases = []
        for i, t in enumerate(types):
            c = np.ascontiguousarray(np.asarray(cols[i]))
            if t in _INT_FAMILY:
                want = np.int64 if t is AttrType.LONG else np.int32
                if c.dtype != want:
                    c = c.astype(want)
                lo = int(c.min()) if n else 0
                hi = int(c.max()) if n else 0
                cand = "c" if lo == hi else _int_code(hi - lo)
                base = lo
            elif t is AttrType.FLOAT:
                c = c.astype(np.float32) if c.dtype != np.float32 else c
                u = c.view(np.uint32)
                cand = "c" if (n and (u == u[0]).all()) or n == 0 else "f32"
                base = int(np.int64(np.float64(c[0]).view(np.int64))) \
                    if (cand == "c" and n) else 0
            elif t is AttrType.DOUBLE:
                c = c.astype(np.float64) if c.dtype != np.float64 else c
                u = c.view(np.uint64)
                cand = "c" if (n and (u == u[0]).all()) or n == 0 else "f64"
                base = int(c[:1].view(np.int64)[0]) if (cand == "c" and n) \
                    else 0
            elif t is AttrType.BOOL:
                c = c.astype(np.bool_) if c.dtype != np.bool_ else c
                if n and (c == c[0]).all():
                    cand, base = "c", int(c[0])
                elif n == 0:
                    cand, base = "c", 0
                else:
                    cand, base = "b1", 0
            else:
                raise TypeError(f"cannot pack column type {t}")
            code = self._widen(self._col_codes[i], cand)
            self._col_codes[i] = code
            if code != "c" and t in _INT_FAMILY:
                base = lo  # delta base even when chunk is constant
            ncols.append((code, c))
            bases.append(base)

        enc = (ts_code,) + tuple(code for code, _ in ncols)

        # --- assemble the single buffer ----------------------------------
        H, offs, total = layout(len(types), enc, capacity)
        buf = np.zeros((total,), np.uint8)
        hdr = buf[:H].view(np.int64)
        hdr[0] = n
        hdr[1] = base_ts
        hdr[2] = now
        hdr[3] = stride
        for i, b in enumerate(bases):
            hdr[4 + i] = b

        def put(o: int, arr: np.ndarray):
            raw = arr.view(np.uint8)
            buf[o:o + raw.nbytes] = raw

        # ts lane
        if ts_code == "d8":
            put(offs[0], (ts - base_ts).astype(np.uint8))
        elif ts_code == "d16":
            put(offs[0], (ts - base_ts).astype(np.uint16))
        elif ts_code == "d32":
            put(offs[0], (ts - base_ts).astype(np.uint32))
        elif ts_code == "raw64":
            put(offs[0], ts)

        for i, ((code, c), base) in enumerate(zip(ncols, bases)):
            o = offs[1 + i]
            if code == "c":
                continue
            if code == "b1":
                bits = np.zeros((capacity,), np.bool_)
                bits[:n] = c
                put(o, np.packbits(bits, bitorder="little"))
            elif code == "f32":
                put(o, c)
            elif code == "f64":
                put(o, c)
            elif code == "raw64":
                put(o, c.astype(np.int64))
            else:  # d8/d16/d32 deltas
                dt = {"d8": np.uint8, "d16": np.uint16,
                      "d32": np.uint32}[code]
                put(o, (c.astype(np.int64) - base).astype(dt))
        return buf, enc, n


def _bitcast_lane(buf, offset: int, capacity: int, width: int, dtype):
    raw = jax.lax.dynamic_slice(buf, (offset,), (capacity * width,))
    if width == 1:
        return raw.astype(dtype) if dtype != jnp.uint8 else raw
    return jax.lax.bitcast_convert_type(raw.reshape(capacity, width), dtype)


def unpack_buffer(schema: StreamSchema, enc: tuple, capacity: int, buf):
    """Device side (inside jit): single packed buffer -> (EventBatch, now).

    Rows >= n are padding; nulls are all-false (the packed path carries no
    nulls — null-bearing sends use the row path)."""
    types = schema.types
    C = len(types)
    H, offs, total = layout(C, enc, capacity)
    hdr = jax.lax.bitcast_convert_type(buf[:H].reshape(4 + C, 8), jnp.int64)
    n, base_ts, now, stride = hdr[0], hdr[1], hdr[2], hdr[3]
    rows = jnp.arange(capacity, dtype=jnp.int64)
    valid = rows < n

    ts_code = enc[0]
    if ts_code == "aff":
        ts = base_ts + stride * rows
    elif ts_code == "raw64":
        ts = _bitcast_lane(buf, offs[0], capacity, 8, jnp.int64)
    else:
        w = {"d8": 1, "d16": 2, "d32": 4}[ts_code]
        dt = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[w]
        ts = base_ts + _bitcast_lane(buf, offs[0], capacity, w,
                                     dt).astype(jnp.int64)
    ts = jnp.where(valid, ts, base_ts)

    cols = []
    for i, t in enumerate(types):
        code = enc[1 + i]
        o = offs[1 + i]
        base = hdr[4 + i]
        if t in _INT_FAMILY:
            out_dt = jnp.int64 if t is AttrType.LONG else jnp.int32
            if code == "c":
                col = jnp.full((capacity,), base).astype(out_dt)
            elif code == "raw64":
                col = _bitcast_lane(buf, o, capacity, 8, jnp.int64)
                col = col.astype(out_dt)
            else:
                w = {"d8": 1, "d16": 2, "d32": 4}[code]
                dt = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[w]
                col = (base + _bitcast_lane(buf, o, capacity, w,
                                            dt).astype(jnp.int64))
                col = col.astype(out_dt)
        elif t is AttrType.FLOAT:
            if code == "c":
                f = jax.lax.bitcast_convert_type(base, jnp.float64)
                col = jnp.full((capacity,), f.astype(jnp.float32))
            else:
                col = _bitcast_lane(buf, o, capacity, 4, jnp.float32)
        elif t is AttrType.DOUBLE:
            if code == "c":
                col = jnp.full(
                    (capacity,),
                    jax.lax.bitcast_convert_type(base, jnp.float64))
            else:
                col = _bitcast_lane(buf, o, capacity, 8, jnp.float64)
        else:  # BOOL
            if code == "c":
                col = jnp.full((capacity,), base != 0)
            else:
                bytes_ = buf[o:o + capacity // 8]
                idx = jnp.arange(capacity)
                col = ((bytes_[idx >> 3] >> (idx & 7).astype(jnp.uint8))
                       & 1).astype(jnp.bool_)
        cols.append(col)

    batch = EventBatch(
        ts=ts,
        cols=tuple(cols),
        nulls=tuple(jnp.zeros((capacity,), jnp.bool_) for _ in cols),
        kind=jnp.zeros((capacity,), jnp.int32),
        valid=valid,
    )
    return batch, now


class PackedChunk:
    """One device-resident packed chunk, shared by every subscriber of a
    junction (transferred once)."""

    __slots__ = ("buf", "enc", "capacity", "n", "last_ts", "ts_min")

    def __init__(self, buf, enc: tuple, capacity: int, n: int,
                 last_ts: int, ts_min=None):
        self.buf = buf              # ONE device uint8 array
        self.enc = enc              # static encoding tuple (jit cache key)
        self.capacity = capacity
        self.n = n
        self.last_ts = last_ts
        self.ts_min = ts_min        # host-known earliest ts (timer bounds)

    @classmethod
    def build(cls, encoder: PackedEncoder, ts, cols, capacity: int,
              now: int):
        buf, enc, n = encoder.encode(ts, cols, capacity, now)
        return cls(jax.device_put(buf), enc, capacity, n, int(ts[-1]),
                   ts_min=int(ts.min()) if len(ts) else None)
