"""Packed columnar ingest: the high-throughput host->device path.

The reference's ingest hot path is InputHandler.send -> Disruptor ring
buffer (stream/StreamJunction.java:255-313). The TPU equivalent is bound by
the host->device link (potentially a slow tunnel: ~10 MB/s with ~70 ms
round-trip latency was measured on this image), so the wire format matters
more than anything else on the ingest side:

- EVERYTHING for a chunk travels in ONE 1-D uint8 buffer = one transfer =
  one RTT (a tuple of per-column arrays pays the round-trip per array);
- every dynamic scalar (row count, base timestamp, processing time, per-
  column bases) is embedded in the buffer header, so the jitted step takes
  no separate scalar arguments at all;
- each column is adaptively narrowed per chunk: constant columns ship zero
  bytes (base in the header), integer/string/long columns ship min-offset
  deltas in the narrowest of u8/u16/u32, timestamps detect arithmetic
  progressions ('aff': zero bytes + stride in the header), bools bit-pack
  to 1 bit/row, floats ship raw bits;
- encodings are STICKY per stream (they only ever widen), because the
  encoding tuple is part of the jit cache key — flapping between widths
  would trigger recompiles;
- chunks are zero-padded to the bucket capacity (zero tails compress to
  nothing on compressing transports and cost little raw);
- the validity mask / kind lane / null masks are NOT transferred at all —
  they are reconstructed on device from the header row count.

The jitted query step fuses unpacking with the operator chain, so ingest
costs one device_put per chunk and zero per-batch host round-trips.
"""
from __future__ import annotations

import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .event import EventBatch, StreamSchema
from .types import AttrType

_INT_FAMILY = (AttrType.INT, AttrType.STRING, AttrType.LONG)

# lane byte-width per row for each encoding code
_CODE_BYTES = {"c": 0, "aff": 0, "d8": 1, "d16": 2, "d32": 4,
               "f32": 4, "f64": 8, "raw64": 8}
# widening order within each family (sticky codes only move right)
_ORDER = ("c", "aff", "b1", "f32", "f64", "d8", "d16", "d32", "raw64")
_RANK = {c: i for i, c in enumerate(_ORDER)}


def _pad8(x: int) -> int:
    return (x + 7) & ~7


def _lane_bytes(code: str, capacity: int) -> int:
    if code == "b1":
        return capacity // 8
    return _CODE_BYTES[code] * capacity


def layout(n_cols: int, enc: tuple, capacity: int):
    """(header bytes, per-lane byte offsets, total buffer bytes).

    enc = (ts_code, col_code...). Header int64 slots:
    [0]=n, [1]=base_ts, [2]=now, [3]=ts_stride, [4+i]=col i base."""
    H = (4 + n_cols) * 8
    offs = []
    o = H
    for code in enc:
        offs.append(o)
        o += _pad8(_lane_bytes(code, capacity))
    return H, offs, o


def initial_encoding(schema: StreamSchema) -> tuple:
    """The sticky encoding a fresh PackedEncoder starts from (affine
    timestamps, every column constant). This is the encoding tuple the
    FIRST chunk of a stream compiles against unless the data forces a
    widening — the AOT compile service (core/compile.py) precompiles
    packed steps for it so cold starts hit a ready program."""
    return ("aff",) + ("c",) * len(schema.types)


def encoding_for_sample(schema: StreamSchema, ts, cols,
                        now: int = 0) -> tuple:
    """The sticky encoding a traffic sample settles on: run a throwaway
    encoder over the sample and return its (widened) tuple. Lets
    warmup() precompile the packed step real traffic will dispatch."""
    enc = PackedEncoder(schema)
    ts = np.ascontiguousarray(ts, dtype=np.int64)
    _, tup, _ = enc.encode(ts, cols, _sample_capacity(len(ts)), now)
    return tup


def _sample_capacity(n: int) -> int:
    cap = 8
    while cap < n:
        cap *= 2
    return cap


def zero_packed_buffer(schema: StreamSchema, enc: tuple, capacity: int):
    """A device-resident all-zero packed buffer for (enc, capacity) —
    the abstract argument the compile service warms packed steps with
    (header decodes as n=0: every row is padding)."""
    _, _, total = layout(len(schema.types), enc, capacity)
    return jax.device_put(np.zeros((total,), np.uint8))


def _int_code(span: int) -> str:
    if span < 2 ** 8:
        return "d8"
    if span < 2 ** 16:
        return "d16"
    if span < 2 ** 32:
        return "d32"
    return "raw64"


class PackedEncoder:
    """Per-stream sticky encoding chooser: codes only widen across chunks
    (each distinct encoding tuple is a separate XLA compile).

    The encode path is zero-copy where the wire format allows it: a
    caller column that already matches the lane dtype and C layout is
    bitcast-viewed straight into the packed buffer (no ``np.asarray``
    round trip, no defensive copy); coercions and per-lane copies are
    counted in ``stats`` and surface in ``statistics()['ingest']`` so
    regressions are visible. Host staging buffers rotate (up to three
    per layout size) instead of reallocating per chunk — except on the
    CPU backend, where ``jax.device_put`` may zero-copy alias the
    numpy buffer for the device array's lifetime and rewriting it
    would corrupt a live array."""

    def __init__(self, schema: StreamSchema):
        self.schema = schema
        self._ts_code = "aff"
        self._col_codes = ["c"] * len(schema.types)
        self.stats = {"chunks": 0, "rows": 0, "coerced_arrays": 0,
                      "view_lanes": 0, "copied_lanes": 0,
                      "staging_reuse": 0}
        self._staging: dict = {}
        self._reuse = jax.default_backend() != "cpu"

    def _widen(self, cur: str, cand: str) -> str:
        return cand if _RANK[cand] > _RANK[cur] else cur

    def _conform(self, arr, want) -> np.ndarray:
        """Zero-copy fast path: an already-conformant numpy column
        (dtype + C-contiguity match) passes through untouched; anything
        else pays one counted coercion copy."""
        if isinstance(arr, np.ndarray) and arr.dtype == want and \
                arr.flags.c_contiguous:
            return arr
        self.stats["coerced_arrays"] += 1
        return np.ascontiguousarray(arr, dtype=want)

    def _buffer(self, total: int):
        """-> (host staging buffer, fresh). Fresh buffers are all-zero
        (calloc); pooled buffers are reused only once their previous
        device transfer reports ready, so a rewrite can never race an
        in-flight H2D copy (double-buffered dispatch keeps at most two
        transfers outstanding; the pool holds three buffers)."""
        if self._reuse:
            pool = self._staging.setdefault(total, [])
            for ent in pool:
                dev = ent[1]
                if dev is None or getattr(dev, "is_ready",
                                          lambda: False)():
                    ent[1] = None
                    self.stats["staging_reuse"] += 1
                    return ent[0], False
            if len(pool) < 3:
                buf = np.zeros((total,), np.uint8)
                pool.append([buf, None])
                return buf, True
        return np.zeros((total,), np.uint8), True

    def note_transfer(self, buf: np.ndarray, dev) -> None:
        """Record the device array a pooled staging buffer fed — the
        reuse gate in _buffer waits on it."""
        if not self._reuse:
            return
        for ent in self._staging.get(buf.nbytes, ()):
            if ent[0] is buf:
                ent[1] = dev
                return

    def _choose_codes(self, ts: np.ndarray, cols: Sequence):
        """Sticky code-choosing pass over one chunk: widens ``_ts_code``
        / ``_col_codes`` and returns the conformed columns (so callers
        never conform twice). Returns (n, conformed cols, ts span code).
        The span code is returned rather than folded immediately so a
        ROUND-wide widen (``widen_round``) can fold every chunk's span
        only once the round's final ts code is known."""
        n = int(ts.shape[0])
        types = self.schema.types
        if n >= 2:
            stride = int(ts[1]) - int(ts[0])
            is_aff = bool(np.all(np.diff(ts) == stride))
        else:
            is_aff = True
        tmin = int(ts.min()) if n else 0
        span_code = _int_code(int(ts.max()) - tmin) if n else "d8"
        ts_cand = "aff" if is_aff else span_code
        self._ts_code = self._widen(self._ts_code, ts_cand)
        conf = []
        for i, t in enumerate(types):
            if t in _INT_FAMILY:
                want = np.int64 if t is AttrType.LONG else np.int32
                c = self._conform(cols[i], want)
                lo = int(c.min()) if n else 0
                hi = int(c.max()) if n else 0
                cand = "c" if lo == hi else _int_code(hi - lo)
            elif t is AttrType.FLOAT:
                c = self._conform(cols[i], np.float32)
                u = c.view(np.uint32)
                cand = "c" if (n and (u == u[0]).all()) or n == 0 else "f32"
            elif t is AttrType.DOUBLE:
                c = self._conform(cols[i], np.float64)
                u = c.view(np.uint64)
                cand = "c" if (n and (u == u[0]).all()) or n == 0 else "f64"
            elif t is AttrType.BOOL:
                c = self._conform(cols[i], np.bool_)
                cand = "c" if (n == 0 or (c == c[0]).all()) else "b1"
            else:
                raise TypeError(f"cannot pack column type {t}")
            self._col_codes[i] = self._widen(self._col_codes[i], cand)
            conf.append(c)
        return n, conf, span_code

    @property
    def encoding(self) -> tuple:
        """The current sticky encoding tuple (the jit cache key the next
        assembled chunk will dispatch under)."""
        return (self._ts_code,) + tuple(self._col_codes)

    def widen_round(self, chunks: Sequence) -> tuple:
        """Pool-round pre-pass: sticky-widen the shared codes over EVERY
        slot's (ts, cols) chunk BEFORE any buffer is assembled, so all
        rows of one packed (slots, total) round buffer share ONE
        encoding tuple (= one jit cache key, zero recompiles on tenant
        churn). Folds every chunk's ts span once the round's final ts
        code is known — chunk A (affine) widened before chunk B flips
        the code off 'aff' must still ship deltas wide enough for A's
        span. Returns the settled encoding tuple."""
        spans = []
        for ts, cols in chunks:
            ts = self._conform(np.asarray(ts, np.int64), np.int64)
            _n, _c, span = self._choose_codes(ts, cols)
            spans.append(span)
        if self._ts_code != "aff":
            for span in spans:
                self._ts_code = self._widen(self._ts_code, span)
        return self.encoding

    def encode(self, ts: np.ndarray, cols: Sequence, capacity: int,
               now: int):
        """-> (buf np.uint8[total], enc tuple, n)."""
        assert capacity % 8 == 0, capacity
        ts = self._conform(ts, np.int64)
        n, conf, span_code = self._choose_codes(ts, cols)
        if self._ts_code != "aff":
            # once on a delta code, the width must cover THIS chunk's span
            # even when the chunk itself is affine (offsets would wrap)
            self._ts_code = self._widen(self._ts_code, span_code)
        enc = self.encoding
        _H, _offs, total = layout(len(self.schema.types), enc, capacity)
        buf, fresh = self._buffer(total)
        self._assemble(ts, conf, capacity, now, buf, fresh)
        return buf, enc, n

    def encode_into(self, ts: np.ndarray, cols: Sequence, capacity: int,
                    now: int, out: np.ndarray):
        """Assemble one chunk into a CALLER-OWNED pre-zeroed buffer (one
        row of a pool round's (slots, total) stacked buffer) under the
        CURRENT sticky codes — the caller must have run ``widen_round``
        over the whole round first, so this never widens. Returns n."""
        assert capacity % 8 == 0, capacity
        ts = self._conform(ts, np.int64)
        return self._assemble(ts, cols, capacity, now, out, fresh=True)

    def _assemble(self, ts: np.ndarray, cols: Sequence, capacity: int,
                  now: int, buf: np.ndarray, fresh: bool) -> int:
        """Write header + lanes for one chunk under the CURRENT sticky
        codes (already wide enough for this chunk's spans). ``cols`` may
        be raw caller arrays; they are conformed here if needed."""
        n = int(ts.shape[0])
        types = self.schema.types
        self.stats["chunks"] += 1
        self.stats["rows"] += n

        ts_code = self._ts_code
        if n >= 2:
            stride = int(ts[1]) - int(ts[0])
        else:
            stride = 0
        tmin = int(ts.min()) if n else 0
        base_ts = (int(ts[0]) if n else 0) if ts_code == "aff" else tmin

        ncols = []
        bases = []
        for i, t in enumerate(types):
            code = self._col_codes[i]
            if t in _INT_FAMILY:
                want = np.int64 if t is AttrType.LONG else np.int32
                c = self._conform(cols[i], want)
                lo = int(c.min()) if n else 0
                base = lo   # constant value when code == "c", else delta
            elif t is AttrType.FLOAT:
                c = self._conform(cols[i], np.float32)
                base = int(np.int64(np.float64(c[0]).view(np.int64))) \
                    if (code == "c" and n) else 0
            elif t is AttrType.DOUBLE:
                c = self._conform(cols[i], np.float64)
                base = int(c[:1].view(np.int64)[0]) if (code == "c" and n) \
                    else 0
            else:  # BOOL
                c = self._conform(cols[i], np.bool_)
                base = int(c[0]) if (code == "c" and n) else 0
            ncols.append((code, c))
            bases.append(base)

        enc = (ts_code,) + tuple(code for code, _ in ncols)
        H, offs, total = layout(len(types), enc, capacity)
        assert buf.nbytes == total, (buf.nbytes, total)
        hdr = buf[:H].view(np.int64)
        hdr[0] = n
        hdr[1] = base_ts
        hdr[2] = now
        hdr[3] = stride
        for i, b in enumerate(bases):
            hdr[4 + i] = b

        stats = self.stats

        def put(o: int, arr: np.ndarray, lane: int, view: bool):
            """Write one lane; ``view`` marks a direct bitcast view of
            the (conformed) caller array — no intermediate temp."""
            raw = arr.view(np.uint8)
            end = o + raw.nbytes
            buf[o:end] = raw
            if not fresh:
                # pooled buffer: pad rows must decode exactly like a
                # fresh zeroed buffer
                buf[end:o + lane] = 0
            stats["view_lanes" if view else "copied_lanes"] += 1

        # ts lane
        ts_lane = _pad8(_lane_bytes(ts_code, capacity))
        if ts_code == "raw64":
            put(offs[0], ts, ts_lane, view=True)
        elif ts_code != "aff":
            dt = {"d8": np.uint8, "d16": np.uint16,
                  "d32": np.uint32}[ts_code]
            put(offs[0], (ts - base_ts).astype(dt), ts_lane, view=False)

        for i, ((code, c), base) in enumerate(zip(ncols, bases)):
            o = offs[1 + i]
            if code == "c":
                continue
            lane = _pad8(_lane_bytes(code, capacity))
            if code == "b1":
                bits = np.zeros((capacity,), np.bool_)
                bits[:n] = c
                put(o, np.packbits(bits, bitorder="little"), lane,
                    view=False)
            elif code in ("f32", "f64"):
                put(o, c, lane, view=True)
            elif code == "raw64":
                if c.dtype == np.int64:
                    put(o, c, lane, view=True)
                else:
                    put(o, c.astype(np.int64), lane, view=False)
            else:  # d8/d16/d32 deltas
                dt = {"d8": np.uint8, "d16": np.uint16,
                      "d32": np.uint32}[code]
                if _CODE_BYTES[code] < c.dtype.itemsize:
                    # the span fits the column's native dtype (e.g. d16
                    # from int32): subtract without the int64 temp
                    put(o, (c - c.dtype.type(base)).astype(dt), lane,
                        view=False)
                else:
                    put(o, (c.astype(np.int64) - base).astype(dt), lane,
                        view=False)
        return n


def _bitcast_lane(buf, offset: int, capacity: int, width: int, dtype):
    raw = jax.lax.dynamic_slice(buf, (offset,), (capacity * width,))
    if width == 1:
        return raw.astype(dtype) if dtype != jnp.uint8 else raw
    return jax.lax.bitcast_convert_type(raw.reshape(capacity, width), dtype)


def unpack_buffer(schema: StreamSchema, enc: tuple, capacity: int, buf):
    """Device side (inside jit): single packed buffer -> (EventBatch, now).

    Rows >= n are padding; nulls are all-false (the packed path carries no
    nulls — null-bearing sends use the row path)."""
    types = schema.types
    C = len(types)
    H, offs, total = layout(C, enc, capacity)
    hdr = jax.lax.bitcast_convert_type(buf[:H].reshape(4 + C, 8), jnp.int64)
    n, base_ts, now, stride = hdr[0], hdr[1], hdr[2], hdr[3]
    rows = jnp.arange(capacity, dtype=jnp.int64)
    valid = rows < n

    ts_code = enc[0]
    if ts_code == "aff":
        ts = base_ts + stride * rows
    elif ts_code == "raw64":
        ts = _bitcast_lane(buf, offs[0], capacity, 8, jnp.int64)
    else:
        w = {"d8": 1, "d16": 2, "d32": 4}[ts_code]
        dt = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[w]
        ts = base_ts + _bitcast_lane(buf, offs[0], capacity, w,
                                     dt).astype(jnp.int64)
    ts = jnp.where(valid, ts, base_ts)

    cols = []
    for i, t in enumerate(types):
        code = enc[1 + i]
        o = offs[1 + i]
        base = hdr[4 + i]
        if t in _INT_FAMILY:
            out_dt = jnp.int64 if t is AttrType.LONG else jnp.int32
            if code == "c":
                col = jnp.full((capacity,), base).astype(out_dt)
            elif code == "raw64":
                col = _bitcast_lane(buf, o, capacity, 8, jnp.int64)
                col = col.astype(out_dt)
            else:
                w = {"d8": 1, "d16": 2, "d32": 4}[code]
                dt = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}[w]
                col = (base + _bitcast_lane(buf, o, capacity, w,
                                            dt).astype(jnp.int64))
                col = col.astype(out_dt)
        elif t is AttrType.FLOAT:
            if code == "c":
                f = jax.lax.bitcast_convert_type(base, jnp.float64)
                col = jnp.full((capacity,), f.astype(jnp.float32))
            else:
                col = _bitcast_lane(buf, o, capacity, 4, jnp.float32)
        elif t is AttrType.DOUBLE:
            if code == "c":
                col = jnp.full(
                    (capacity,),
                    jax.lax.bitcast_convert_type(base, jnp.float64))
            else:
                col = _bitcast_lane(buf, o, capacity, 8, jnp.float64)
        else:  # BOOL
            if code == "c":
                col = jnp.full((capacity,), base != 0)
            else:
                bytes_ = buf[o:o + capacity // 8]
                idx = jnp.arange(capacity)
                col = ((bytes_[idx >> 3] >> (idx & 7).astype(jnp.uint8))
                       & 1).astype(jnp.bool_)
        cols.append(col)

    batch = EventBatch(
        ts=ts,
        cols=tuple(cols),
        nulls=tuple(jnp.zeros((capacity,), jnp.bool_) for _ in cols),
        kind=jnp.zeros((capacity,), jnp.int32),
        valid=valid,
    )
    return batch, now


class PackedChunk:
    """One device-resident packed chunk, shared by every subscriber of a
    junction (transferred once)."""

    __slots__ = ("buf", "enc", "capacity", "n", "last_ts", "ts_min")

    def __init__(self, buf, enc: tuple, capacity: int, n: int,
                 last_ts: int, ts_min=None):
        self.buf = buf              # ONE device uint8 array
        self.enc = enc              # static encoding tuple (jit cache key)
        self.capacity = capacity
        self.n = n
        self.last_ts = last_ts
        self.ts_min = ts_min        # host-known earliest ts (timer bounds)

    @classmethod
    def build(cls, encoder: PackedEncoder, ts, cols, capacity: int,
              now: int):
        buf, enc, n = encoder.encode(ts, cols, capacity, now)
        dev = jax.device_put(buf)
        encoder.note_transfer(buf, dev)
        return cls(dev, enc, capacity, n, int(ts[-1]),
                   ts_min=int(ts.min()) if len(ts) else None)


# -- double-buffered ingest pipeline -----------------------------------------

PIPELINE_SPLIT_DEFAULT = 262144


def pipeline_enabled() -> bool:
    """``SIDDHI_TPU_INGEST_PIPELINE=0`` kill switch (default on) for
    the double-buffered encode/dispatch overlap."""
    return os.environ.get("SIDDHI_TPU_INGEST_PIPELINE", "1").lower() \
        not in ("0", "off", "false")


def pipeline_split_cap() -> int:
    """Sub-chunk size the pipeline cuts oversized sends into
    (``SIDDHI_TPU_INGEST_PIPELINE_CHUNK`` overrides; must be a bucket
    from BATCH_BUCKETS to keep jit caches warm)."""
    raw = os.environ.get("SIDDHI_TPU_INGEST_PIPELINE_CHUNK", "")
    try:
        v = int(raw)
    except ValueError:
        v = 0
    return v if v > 0 else PIPELINE_SPLIT_DEFAULT


def pipeline_chunk_cap(n: int, max_cap: int) -> int:
    """Effective per-chunk cap under the pipeline: a send larger than
    the split cap is cut into sub-chunks so encode of chunk N+1 can
    overlap device work of chunk N even for one huge send_arrays call.
    The compile service mirrors this (core/compile.py specs) so warmed
    programs match what dispatch produces."""
    sub = pipeline_split_cap()
    return min(max_cap, sub) if n > sub else max_cap


class IngestPipeline:
    """Double-buffered ingest for one input handler: a single worker
    thread encodes chunk N+1 (pure numpy — the heavy ufuncs drop the
    GIL) while the caller thread dispatches chunk N, whose H2D copy and
    compute ride JAX async dispatch. The bounded futures window is the
    backpressure: the producer blocks in ``result()`` until the oldest
    encode lands, so at most DEPTH chunks are in flight and nothing
    queues beyond the encoder's rotating staging buffers —
    admission/429 decisions stay upstream (serving/qos.py).

    Donation-safe by construction: packed steps donate their state
    buffers (argnums 0-2) but never the packed chunk argument, so a
    chunk whose transfer is still in flight cannot be invalidated by
    the step consuming its predecessor."""

    DEPTH = 2

    def __init__(self, stream_id: str):
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"ingest-{stream_id}")
        self.stats = {"sends": 0, "chunks": 0, "encode_s": 0.0,
                      "dispatch_s": 0.0, "wall_s": 0.0, "overlap_s": 0.0}

    def run(self, n_chunks: int, encode, dispatch) -> None:
        """``encode(i) -> chunk`` on the worker thread; ``dispatch(i,
        chunk)`` on the caller thread, overlapped one chunk ahead."""
        from collections import deque
        from time import perf_counter
        t0 = perf_counter()
        enc_s = disp_s = 0.0

        def timed_encode(i):
            e0 = perf_counter()
            return encode(i), perf_counter() - e0

        futs = deque([self._pool.submit(timed_encode, 0)])
        try:
            for i in range(n_chunks):
                if i + 1 < n_chunks:
                    futs.append(self._pool.submit(timed_encode, i + 1))
                chunk, dt = futs.popleft().result()
                enc_s += dt
                d0 = perf_counter()
                dispatch(i, chunk)
                disp_s += perf_counter() - d0
        finally:
            while futs:  # dispatch failed: drain the lookahead encode
                f = futs.popleft()
                if not f.cancel():
                    try:
                        f.result(timeout=60)
                    except Exception:  # noqa: BLE001 — the dispatch
                        pass           # error already propagates
            wall = perf_counter() - t0
            st = self.stats
            st["sends"] += 1
            st["chunks"] += n_chunks
            st["encode_s"] += enc_s
            st["dispatch_s"] += disp_s
            st["wall_s"] += wall
            st["overlap_s"] += max(0.0, enc_s + disp_s - wall)

    def close(self) -> None:
        self._pool.shutdown(wait=True)
