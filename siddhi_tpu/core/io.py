"""I/O connectors: Source/Sink SPI, mappers, the in-memory transport,
and connection retry.

Reference mapping:
- stream/input/source/Source.java:155 (connectWithRetry + backoff)
- stream/output/sink/Sink.java:174-243 (publish with retry / @OnError)
- util/transport/InMemoryBroker.java:29 + InMemorySource/InMemorySink
- stream/input/source/SourceMapper / stream/output/sink/SinkMapper SPIs
- util/transport/BackoffRetryCounter.java

Host-side by design: connectors bridge external systems to the
InputHandler / StreamCallback boundary; the device pipeline starts after
ingestion. Custom transports register through the extension SPI as
`source:<type>` / `sink:<type>` classes.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable, Optional

from ..obs.tracing import maybe_span
from .stream import Event, StreamCallback

log = logging.getLogger("siddhi_tpu.io")


class ConnectionUnavailableException(Exception):
    """Transport temporarily unreachable; triggers retry with backoff."""


# on-error actions a connector can declare via `on.error=` (the static
# plan validator rejects anything else at parse time; constructors also
# reject so programmatic wiring fails fast)
SINK_ON_ERROR_ACTIONS = ("RETRY", "WAIT", "STORE", "LOG", "STREAM")
SOURCE_ON_ERROR_ACTIONS = ("RETRY", "WAIT")


def _on_error_opts(options: dict, valid: tuple, default_attempts: int,
                   what: str) -> tuple[str, int, int, int]:
    """Parse the shared on.error option family: (action, max attempts,
    backoff base ms, backoff cap ms)."""
    action = str(options.get("on.error") or "RETRY").upper()
    if action not in valid:
        raise ValueError(
            f"{what}: unknown on.error action '{action}' "
            f"(expected one of {', '.join(valid)})")
    attempts = int(options.get("on.error.max.attempts")
                   or default_attempts)
    if attempts < 1:
        raise ValueError(f"{what}: on.error.max.attempts must be >= 1")
    base = int(options.get("on.error.backoff.ms") or 5)
    cap = int(options.get("on.error.backoff.cap.ms") or 1000)
    return action, attempts, base, cap


_backoff_rng_lock = threading.Lock()
_backoff_rng = random.Random()


def set_backoff_rng(rng) -> "random.Random":
    """Install the RNG the backoff jitter draws from; returns the
    previous one. FaultInjector seeds this on entry (and restores it on
    exit) so chaos runs reproduce their exact retry schedule from the
    seed; outside a chaos harness the default unseeded Random gives
    every process its own jitter stream."""
    global _backoff_rng
    with _backoff_rng_lock:
        prev = _backoff_rng
        _backoff_rng = rng if rng is not None else random.Random()
        return prev


class BackoffRetryCounter:
    """Exponential backoff with FULL JITTER: each wait is uniform in
    (0, min(base * 2^n, cap)] instead of the deterministic ceiling
    (the reference steps fixed seconds; scaled down so tests run fast).

    The jitter is the point, not a nicety: when a shared transport dies,
    every sink/source hits its backoff schedule at the same instant — a
    deterministic schedule re-synchronizes ALL of them into one retry
    storm at each boundary, while full jitter spreads the reconnects
    uniformly across the window (tests/test_resilience.py asserts the
    spread). Deterministic under FaultInjector via ``set_backoff_rng``.
    """

    def __init__(self, base_ms: int = 5, cap_ms: int = 1000):
        self.base_ms = base_ms
        self.cap_ms = cap_ms
        self._n = 0

    def next_wait_s(self) -> float:
        ceiling = min(self.base_ms * (2 ** self._n), self.cap_ms)
        self._n += 1
        with _backoff_rng_lock:
            u = _backoff_rng.random()
        # (0, ceiling]: never a zero sleep — a 0 wait would busy-spin
        # the reconnect loop against a dead transport
        return ceiling * (1.0 - u) / 1000.0

    def reset(self) -> None:
        self._n = 0


class InMemoryBroker:
    """Process-wide topic pub/sub (util/transport/InMemoryBroker.java:29).

    Thread-safe by construction: every subscriber-list mutation happens
    under the class lock, and publish iterates a snapshot taken under
    the lock — a sink publishing while a source disconnects can at worst
    deliver one message to a just-unsubscribed callback, never observe a
    list mutating mid-iteration."""

    _topics: dict = {}
    _lock = threading.Lock()

    @classmethod
    def subscribe(cls, topic: str, fn: Callable[[Any], None]) -> Callable:
        with cls._lock:
            cls._topics.setdefault(topic, []).append(fn)
        return fn

    @classmethod
    def unsubscribe(cls, topic: str, fn: Callable) -> None:
        with cls._lock:
            subs = cls._topics.get(topic, [])
            if fn in subs:
                subs.remove(fn)

    @classmethod
    def publish(cls, topic: str, message: Any) -> None:
        with cls._lock:
            subs = list(cls._topics.get(topic, []))
        for fn in subs:
            fn(message)


# ---------------------------------------------------------------------------
# mappers
# ---------------------------------------------------------------------------


class SourceMapper:
    """Transport payload -> event data tuple(s)."""

    def __init__(self, schema):
        self.schema = schema

    def map(self, payload) -> list[tuple]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    def map(self, payload):
        if isinstance(payload, Event):
            return [tuple(payload.data)]
        if isinstance(payload, (list, tuple)) and payload and \
                isinstance(payload[0], (list, tuple)):
            return [tuple(p) for p in payload]
        return [tuple(payload)]


class JsonSourceMapper(SourceMapper):
    """JSON object (or list of objects) keyed by attribute name
    (the out-of-tree siddhi-map-json default mapping)."""

    def map(self, payload):
        import json
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) \
            else payload
        objs = obj if isinstance(obj, list) else [obj]
        names = [a.name for a in self.schema.attributes]
        return [tuple(o.get(n) for n in names) for o in objs]


class SinkMapper:
    def __init__(self, schema):
        self.schema = schema

    def map(self, event: Event):
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, event: Event):
        return event


class JsonSinkMapper(SinkMapper):
    def map(self, event: Event):
        import json
        return json.dumps({a.name: v for a, v in
                           zip(self.schema.attributes, event.data)})


SOURCE_MAPPERS = {"passthrough": PassThroughSourceMapper,
                  "json": JsonSourceMapper}
SINK_MAPPERS = {"passthrough": PassThroughSinkMapper,
                "json": JsonSinkMapper}


# ---------------------------------------------------------------------------
# sources / sinks
# ---------------------------------------------------------------------------


class Source:
    """Receives external payloads and feeds an InputHandler
    (stream/input/source/Source.java SPI). Subclasses implement
    connect/disconnect; payloads go through self.on_payload."""

    def __init__(self, options: dict, mapper: SourceMapper, handler):
        self.options = options
        self.mapper = mapper
        self.handler = handler
        self.connected = False
        # on.error='RETRY' (bounded attempts) | 'WAIT' (block until the
        # transport comes back), with configurable attempt/backoff knobs
        (self.on_error, self.max_attempts, self._backoff_base_ms,
         self._backoff_cap_ms) = _on_error_opts(
            options, SOURCE_ON_ERROR_ACTIONS, 12,
            f"source {type(self).__name__}")
        self._paused = threading.Event()
        self._paused.set()  # not paused

    # -- lifecycle --------------------------------------------------------
    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def connect_with_retry(self, max_tries: Optional[int] = None) -> None:
        """Source.connectWithRetry (Source.java:155): exponential backoff
        until the transport accepts the connection. on.error='WAIT'
        blocks (keeps retrying at the backoff cap) until it does; RETRY
        raises immediately after the final failed attempt — no trailing
        backoff sleep nobody is waiting on."""
        if max_tries is None:
            max_tries = self.max_attempts
        backoff = BackoffRetryCounter(self._backoff_base_ms,
                                      self._backoff_cap_ms)
        attempt = 0
        while True:
            attempt += 1
            try:
                self.connect()
                self.connected = True
                return
            except ConnectionUnavailableException:
                if self.on_error != "WAIT" and attempt >= max_tries:
                    raise ConnectionUnavailableException(
                        f"source {type(self).__name__} failed to connect "
                        f"after {attempt} attempts")
                time.sleep(backoff.next_wait_s())

    def pause(self) -> None:
        self._paused.clear()

    def resume(self) -> None:
        self._paused.set()

    def on_payload(self, payload) -> None:
        self._paused.wait()
        rows = self.mapper.map(payload)
        if rows:
            self.handler.send(rows if len(rows) > 1 else rows[0])


class InMemorySource(Source):
    """@source(type='inMemory', topic='x')
    (stream/input/source/InMemorySource.java)."""

    def connect(self) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory source needs a topic option")
        self._sub = InMemoryBroker.subscribe(topic, self.on_payload)

    def disconnect(self) -> None:
        topic = self.options.get("topic")
        if topic is not None and getattr(self, "_sub", None) is not None:
            InMemoryBroker.unsubscribe(topic, self._sub)


class Sink(StreamCallback):
    """Publishes stream events to an external system
    (stream/output/sink/Sink.java SPI); publish failures retry with
    backoff, then follow the per-sink `on.error` action
    (Sink.java:174-243):

    - RETRY (default) / LOG: bounded attempts, then log + count the drop
    - WAIT: block, retrying at the backoff cap, until the transport
      recovers (or the sink is disconnected)
    - STORE: bounded attempts, then capture the event in the app's
      error store for replay (at-least-once)
    - STREAM: bounded attempts, then emit a fault event on the origin
      stream's `!stream` junction

    The policy resolves PER EVENT: one event exhausting its retries must
    not abort the rest of the batch (events after it are still
    attempted, not lost to a raised exception)."""

    def __init__(self, options: dict, mapper: SinkMapper):
        super().__init__()
        self.options = options
        self.mapper = mapper
        (self.on_error, self.max_attempts, self._backoff_base_ms,
         self._backoff_cap_ms) = _on_error_opts(
            options, SINK_ON_ERROR_ACTIONS, 4,
            f"sink {type(self).__name__}")
        # wired by build_io: origin stream id + its junction (fault
        # routing, error-store resolution, per-stream error counters)
        self.stream_id: Optional[str] = None
        self.junction = None
        self._closed = False

    def connect(self) -> None:
        self._closed = False

    def disconnect(self) -> None:
        self._closed = True

    def publish(self, payload) -> None:
        raise NotImplementedError

    def receive(self, events: list[Event]) -> None:
        app = getattr(self.junction, "app", None)
        with maybe_span(app, "sink",
                        self.stream_id or type(self).__name__,
                        events=len(events)):
            for e in events:
                payload = self.mapper.map(e)
                try:
                    self._publish_with_retry(payload)
                except ConnectionUnavailableException as exc:
                    self._on_publish_failure(e, exc)

    def _publish_with_retry(self, payload) -> None:
        backoff = BackoffRetryCounter(self._backoff_base_ms,
                                      self._backoff_cap_ms)
        attempt = 0
        while True:
            attempt += 1
            try:
                self.publish(payload)
                return
            except ConnectionUnavailableException:
                if self.on_error == "WAIT":
                    if self._closed:
                        raise   # disconnected mid-wait: stop blocking
                    time.sleep(backoff.next_wait_s())
                    continue
                if attempt >= self.max_attempts:
                    raise   # terminal — no trailing backoff sleep
                time.sleep(backoff.next_wait_s())

    def _on_publish_failure(self, event: Event, exc: Exception) -> None:
        """Terminal per-event on-error resolution; never raises, so the
        remainder of the batch is still attempted."""
        sid = self.stream_id or type(self).__name__
        if self.junction is not None:
            self.junction.count_error()
        if self.on_error == "STORE" and self.junction is not None and \
                self.junction.store_error([event], exc,
                                          attempts=self.max_attempts):
            log.warning("sink on stream '%s': event routed to the error "
                        "store after %d attempts (%s)", sid,
                        self.max_attempts, exc)
            return
        if self.on_error == "STREAM" and self.junction is not None and \
                self.junction.publish_fault([event], exc):
            return
        log.error("sink on stream '%s': dropped event after %d "
                  "attempt(s) (action=%s)", sid, self.max_attempts,
                  self.on_error, exc_info=exc)


class InMemorySink(Sink):
    """@sink(type='inMemory', topic='x')
    (stream/output/sink/InMemorySink.java)."""

    def publish(self, payload) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory sink needs a topic option")
        InMemoryBroker.publish(topic, payload)


def _java_string_hash(s: str) -> int:
    """Java String.hashCode — the reference's partitioned strategy keys
    destinations by partitionKeyValue.hashCode() % destinationCount
    (PartitionedDistributionStrategy.java:100-110)."""
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    if h >= 0x80000000:
        h -= 0x100000000
    return h


class DistributionStrategy:
    """Destination chooser SPI (stream/output/sink/distributed/
    DistributionStrategy.java): returns the destination ids an event
    is published to."""

    def init(self, schema, dist_opts: dict, dest_opts: list[dict]) -> None:
        self.n = len(dest_opts)

    def destinations(self, event: Event) -> list[int]:
        raise NotImplementedError


class RoundRobinDistributionStrategy(DistributionStrategy):
    """RoundRobinDistributionStrategy.java:49 — cycle destinations per
    published event."""

    def init(self, schema, dist_opts, dest_opts):
        super().init(schema, dist_opts, dest_opts)
        self._i = 0

    def destinations(self, event):
        d = self._i % self.n
        self._i += 1
        return [d]


class PartitionedDistributionStrategy(DistributionStrategy):
    """PartitionedDistributionStrategy.java:52 — hash of the partitionKey
    attribute value picks the destination."""

    def init(self, schema, dist_opts, dest_opts):
        super().init(schema, dist_opts, dest_opts)
        key = dist_opts.get("partitionkey")
        if not key:
            raise ValueError(
                "PartitionKey is required for partitioned distribution "
                "strategy.")
        try:
            self._pos = schema.index_of(key)
        except KeyError:
            raise ValueError(
                f"Could not find partition key attribute '{key}'")

    def destinations(self, event):
        v = event.data[self._pos]
        return [abs(_java_string_hash(str(v))) % self.n]


class BroadcastDistributionStrategy(DistributionStrategy):
    """BroadcastDistributionStrategy.java — every destination."""

    def destinations(self, event):
        return list(range(self.n))


DISTRIBUTION_STRATEGIES = {
    "roundrobin": RoundRobinDistributionStrategy,
    "partitioned": PartitionedDistributionStrategy,
    "broadcast": BroadcastDistributionStrategy,
}


class DistributedSink(StreamCallback):
    """@sink(..., @distribution(strategy=..., @destination(...), ...)):
    one child sink per @destination, events routed by the strategy
    (DistributedTransport.java:47 + MultiClientDistributedSink — each
    destination holds its own client/connection)."""

    def __init__(self, children: list[Sink],
                 strategy: DistributionStrategy):
        super().__init__()
        self.children = children
        self.strategy = strategy

    def connect(self) -> None:
        for c in self.children:
            c.connect()

    def disconnect(self) -> None:
        for c in self.children:
            c.disconnect()

    def receive(self, events: list[Event]) -> None:
        for e in events:
            for d in self.strategy.destinations(e):
                self.children[d].receive([e])


SOURCE_TYPES = {"inmemory": InMemorySource}
SINK_TYPES = {"inmemory": InMemorySink}


def build_io(app, exts: dict) -> None:
    """Planner pass: wire @source/@sink annotations on stream definitions
    (reference: SiddhiAppRuntimeBuilder source/sink attachment).
    exts: the planner's lowercased extension registry."""
    from ..ops.expr import CompileError
    for sid, sd in app.ast.stream_definitions.items():
        for ann in sd.annotations:
            kind = ann.name.lower()
            if kind not in ("source", "sink"):
                continue
            opts = {k.lower(): v for k, v in ann.elements.items()}
            typ = (opts.pop("type", "") or "").lower()
            mname = (opts.pop("map", "passthrough") or "").lower()
            schema = app.schemas[sid]
            if kind == "source":
                cls = SOURCE_TYPES.get(typ) or exts.get(f"source:{typ}")
                if cls is None:
                    raise CompileError(f"unknown source type '{typ}'")
                mcls = SOURCE_MAPPERS.get(mname)
                if mcls is None:
                    raise CompileError(f"unknown source map '{mname}'")
                try:
                    src = cls(opts, mcls(schema), app.input_handlers[sid])
                except ValueError as e:   # bad on.error options
                    raise CompileError(f"stream '{sid}': {e}") from e
                src.stream_id = sid
                app.sources.append(src)
            else:
                cls = SINK_TYPES.get(typ) or exts.get(f"sink:{typ}")
                if cls is None:
                    raise CompileError(f"unknown sink type '{typ}'")
                # nested @map(type=...) wins over a flat map= element
                dist = None
                for sub in ann.nested:
                    sname = sub.name.lower()
                    if sname == "map":
                        mname = (sub.element("type") or mname).lower()
                    elif sname == "distribution":
                        dist = sub
                mcls = SINK_MAPPERS.get(mname)
                if mcls is None:
                    raise CompileError(f"unknown sink map '{mname}'")
                from .runtime import StreamCallbackReceiver
                if dist is not None:
                    strategy_name = (dist.element("strategy")
                                     or "").lower()
                    scls = DISTRIBUTION_STRATEGIES.get(strategy_name) \
                        or exts.get(f"distributionstrategy:{strategy_name}")
                    if scls is None:
                        raise CompileError(
                            f"unknown distribution strategy "
                            f"'{strategy_name}'")
                    dests = [d for d in dist.nested
                             if d.name.lower() == "destination"]
                    if not dests:
                        raise CompileError(
                            "@distribution needs at least one "
                            "@destination")
                    dist_opts = {k.lower(): v
                                 for k, v in dist.elements.items()}
                    dest_opts = []
                    children = []
                    try:
                        for d in dests:
                            merged = dict(opts)
                            merged.update(
                                {k.lower(): v
                                 for k, v in d.elements.items()})
                            dest_opts.append(merged)
                            children.append(cls(merged, mcls(schema)))
                    except ValueError as e:   # bad on.error options
                        raise CompileError(f"stream '{sid}': {e}") from e
                    strat = scls()
                    try:
                        strat.init(schema, dist_opts, dest_opts)
                    except ValueError as e:
                        raise CompileError(str(e)) from e
                    snk = DistributedSink(children, strat)
                    for c in children:
                        c.stream_id = sid
                        c.junction = app.junctions[sid]
                else:
                    try:
                        snk = cls(opts, mcls(schema))
                    except ValueError as e:   # bad on.error options
                        raise CompileError(f"stream '{sid}': {e}") from e
                    snk.stream_id = sid
                    snk.junction = app.junctions[sid]
                app.junctions[sid].subscribe(StreamCallbackReceiver(snk))
                app.sinks.append(snk)
