"""I/O connectors: Source/Sink SPI, mappers, the in-memory transport,
and connection retry.

Reference mapping:
- stream/input/source/Source.java:155 (connectWithRetry + backoff)
- stream/output/sink/Sink.java:174-243 (publish with retry / @OnError)
- util/transport/InMemoryBroker.java:29 + InMemorySource/InMemorySink
- stream/input/source/SourceMapper / stream/output/sink/SinkMapper SPIs
- util/transport/BackoffRetryCounter.java

Host-side by design: connectors bridge external systems to the
InputHandler / StreamCallback boundary; the device pipeline starts after
ingestion. Custom transports register through the extension SPI as
`source:<type>` / `sink:<type>` classes.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from .stream import Event, StreamCallback


class ConnectionUnavailableException(Exception):
    """Transport temporarily unreachable; triggers retry with backoff."""


class BackoffRetryCounter:
    """Exponential backoff: 5ms, 10ms, ..., capped at 1s (the reference
    steps seconds; scaled down so tests run fast)."""

    def __init__(self, base_ms: int = 5, cap_ms: int = 1000):
        self.base_ms = base_ms
        self.cap_ms = cap_ms
        self._n = 0

    def next_wait_s(self) -> float:
        w = min(self.base_ms * (2 ** self._n), self.cap_ms) / 1000.0
        self._n += 1
        return w

    def reset(self) -> None:
        self._n = 0


class InMemoryBroker:
    """Process-wide topic pub/sub (util/transport/InMemoryBroker.java:29)."""

    _topics: dict = {}
    _lock = threading.Lock()

    @classmethod
    def subscribe(cls, topic: str, fn: Callable[[Any], None]) -> Callable:
        with cls._lock:
            cls._topics.setdefault(topic, []).append(fn)
        return fn

    @classmethod
    def unsubscribe(cls, topic: str, fn: Callable) -> None:
        with cls._lock:
            subs = cls._topics.get(topic, [])
            if fn in subs:
                subs.remove(fn)

    @classmethod
    def publish(cls, topic: str, message: Any) -> None:
        with cls._lock:
            subs = list(cls._topics.get(topic, []))
        for fn in subs:
            fn(message)


# ---------------------------------------------------------------------------
# mappers
# ---------------------------------------------------------------------------


class SourceMapper:
    """Transport payload -> event data tuple(s)."""

    def __init__(self, schema):
        self.schema = schema

    def map(self, payload) -> list[tuple]:
        raise NotImplementedError


class PassThroughSourceMapper(SourceMapper):
    def map(self, payload):
        if isinstance(payload, Event):
            return [tuple(payload.data)]
        if isinstance(payload, (list, tuple)) and payload and \
                isinstance(payload[0], (list, tuple)):
            return [tuple(p) for p in payload]
        return [tuple(payload)]


class JsonSourceMapper(SourceMapper):
    """JSON object (or list of objects) keyed by attribute name
    (the out-of-tree siddhi-map-json default mapping)."""

    def map(self, payload):
        import json
        obj = json.loads(payload) if isinstance(payload, (str, bytes)) \
            else payload
        objs = obj if isinstance(obj, list) else [obj]
        names = [a.name for a in self.schema.attributes]
        return [tuple(o.get(n) for n in names) for o in objs]


class SinkMapper:
    def __init__(self, schema):
        self.schema = schema

    def map(self, event: Event):
        raise NotImplementedError


class PassThroughSinkMapper(SinkMapper):
    def map(self, event: Event):
        return event


class JsonSinkMapper(SinkMapper):
    def map(self, event: Event):
        import json
        return json.dumps({a.name: v for a, v in
                           zip(self.schema.attributes, event.data)})


SOURCE_MAPPERS = {"passthrough": PassThroughSourceMapper,
                  "json": JsonSourceMapper}
SINK_MAPPERS = {"passthrough": PassThroughSinkMapper,
                "json": JsonSinkMapper}


# ---------------------------------------------------------------------------
# sources / sinks
# ---------------------------------------------------------------------------


class Source:
    """Receives external payloads and feeds an InputHandler
    (stream/input/source/Source.java SPI). Subclasses implement
    connect/disconnect; payloads go through self.on_payload."""

    def __init__(self, options: dict, mapper: SourceMapper, handler):
        self.options = options
        self.mapper = mapper
        self.handler = handler
        self.connected = False
        self._paused = threading.Event()
        self._paused.set()  # not paused

    # -- lifecycle --------------------------------------------------------
    def connect(self) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass

    def connect_with_retry(self, max_tries: int = 12) -> None:
        """Source.connectWithRetry (Source.java:155): exponential backoff
        until the transport accepts the connection."""
        backoff = BackoffRetryCounter()
        for _ in range(max_tries):
            try:
                self.connect()
                self.connected = True
                return
            except ConnectionUnavailableException:
                time.sleep(backoff.next_wait_s())
        raise ConnectionUnavailableException(
            f"source {type(self).__name__} failed to connect after "
            f"{max_tries} attempts")

    def pause(self) -> None:
        self._paused.clear()

    def resume(self) -> None:
        self._paused.set()

    def on_payload(self, payload) -> None:
        self._paused.wait()
        rows = self.mapper.map(payload)
        if rows:
            self.handler.send(rows if len(rows) > 1 else rows[0])


class InMemorySource(Source):
    """@source(type='inMemory', topic='x')
    (stream/input/source/InMemorySource.java)."""

    def connect(self) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory source needs a topic option")
        self._sub = InMemoryBroker.subscribe(topic, self.on_payload)

    def disconnect(self) -> None:
        topic = self.options.get("topic")
        if topic is not None and getattr(self, "_sub", None) is not None:
            InMemoryBroker.unsubscribe(topic, self._sub)


class Sink(StreamCallback):
    """Publishes stream events to an external system
    (stream/output/sink/Sink.java SPI); publish failures retry with
    backoff, then follow the on-error action."""

    def __init__(self, options: dict, mapper: SinkMapper):
        super().__init__()
        self.options = options
        self.mapper = mapper

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    def publish(self, payload) -> None:
        raise NotImplementedError

    def receive(self, events: list[Event]) -> None:
        for e in events:
            payload = self.mapper.map(e)
            backoff = BackoffRetryCounter()
            for attempt in range(4):
                try:
                    self.publish(payload)
                    break
                except ConnectionUnavailableException:
                    if attempt == 3:
                        raise
                    time.sleep(backoff.next_wait_s())


class InMemorySink(Sink):
    """@sink(type='inMemory', topic='x')
    (stream/output/sink/InMemorySink.java)."""

    def publish(self, payload) -> None:
        topic = self.options.get("topic")
        if topic is None:
            raise ValueError("inMemory sink needs a topic option")
        InMemoryBroker.publish(topic, payload)


def _java_string_hash(s: str) -> int:
    """Java String.hashCode — the reference's partitioned strategy keys
    destinations by partitionKeyValue.hashCode() % destinationCount
    (PartitionedDistributionStrategy.java:100-110)."""
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    if h >= 0x80000000:
        h -= 0x100000000
    return h


class DistributionStrategy:
    """Destination chooser SPI (stream/output/sink/distributed/
    DistributionStrategy.java): returns the destination ids an event
    is published to."""

    def init(self, schema, dist_opts: dict, dest_opts: list[dict]) -> None:
        self.n = len(dest_opts)

    def destinations(self, event: Event) -> list[int]:
        raise NotImplementedError


class RoundRobinDistributionStrategy(DistributionStrategy):
    """RoundRobinDistributionStrategy.java:49 — cycle destinations per
    published event."""

    def init(self, schema, dist_opts, dest_opts):
        super().init(schema, dist_opts, dest_opts)
        self._i = 0

    def destinations(self, event):
        d = self._i % self.n
        self._i += 1
        return [d]


class PartitionedDistributionStrategy(DistributionStrategy):
    """PartitionedDistributionStrategy.java:52 — hash of the partitionKey
    attribute value picks the destination."""

    def init(self, schema, dist_opts, dest_opts):
        super().init(schema, dist_opts, dest_opts)
        key = dist_opts.get("partitionkey")
        if not key:
            raise ValueError(
                "PartitionKey is required for partitioned distribution "
                "strategy.")
        try:
            self._pos = schema.index_of(key)
        except KeyError:
            raise ValueError(
                f"Could not find partition key attribute '{key}'")

    def destinations(self, event):
        v = event.data[self._pos]
        return [abs(_java_string_hash(str(v))) % self.n]


class BroadcastDistributionStrategy(DistributionStrategy):
    """BroadcastDistributionStrategy.java — every destination."""

    def destinations(self, event):
        return list(range(self.n))


DISTRIBUTION_STRATEGIES = {
    "roundrobin": RoundRobinDistributionStrategy,
    "partitioned": PartitionedDistributionStrategy,
    "broadcast": BroadcastDistributionStrategy,
}


class DistributedSink(StreamCallback):
    """@sink(..., @distribution(strategy=..., @destination(...), ...)):
    one child sink per @destination, events routed by the strategy
    (DistributedTransport.java:47 + MultiClientDistributedSink — each
    destination holds its own client/connection)."""

    def __init__(self, children: list[Sink],
                 strategy: DistributionStrategy):
        super().__init__()
        self.children = children
        self.strategy = strategy

    def connect(self) -> None:
        for c in self.children:
            c.connect()

    def disconnect(self) -> None:
        for c in self.children:
            c.disconnect()

    def receive(self, events: list[Event]) -> None:
        for e in events:
            for d in self.strategy.destinations(e):
                self.children[d].receive([e])


SOURCE_TYPES = {"inmemory": InMemorySource}
SINK_TYPES = {"inmemory": InMemorySink}


def build_io(app, exts: dict) -> None:
    """Planner pass: wire @source/@sink annotations on stream definitions
    (reference: SiddhiAppRuntimeBuilder source/sink attachment).
    exts: the planner's lowercased extension registry."""
    from ..ops.expr import CompileError
    for sid, sd in app.ast.stream_definitions.items():
        for ann in sd.annotations:
            kind = ann.name.lower()
            if kind not in ("source", "sink"):
                continue
            opts = {k.lower(): v for k, v in ann.elements.items()}
            typ = (opts.pop("type", "") or "").lower()
            mname = (opts.pop("map", "passthrough") or "").lower()
            schema = app.schemas[sid]
            if kind == "source":
                cls = SOURCE_TYPES.get(typ) or exts.get(f"source:{typ}")
                if cls is None:
                    raise CompileError(f"unknown source type '{typ}'")
                mcls = SOURCE_MAPPERS.get(mname)
                if mcls is None:
                    raise CompileError(f"unknown source map '{mname}'")
                src = cls(opts, mcls(schema), app.input_handlers[sid])
                app.sources.append(src)
            else:
                cls = SINK_TYPES.get(typ) or exts.get(f"sink:{typ}")
                if cls is None:
                    raise CompileError(f"unknown sink type '{typ}'")
                # nested @map(type=...) wins over a flat map= element
                dist = None
                for sub in ann.nested:
                    sname = sub.name.lower()
                    if sname == "map":
                        mname = (sub.element("type") or mname).lower()
                    elif sname == "distribution":
                        dist = sub
                mcls = SINK_MAPPERS.get(mname)
                if mcls is None:
                    raise CompileError(f"unknown sink map '{mname}'")
                from .runtime import StreamCallbackReceiver
                if dist is not None:
                    strategy_name = (dist.element("strategy")
                                     or "").lower()
                    scls = DISTRIBUTION_STRATEGIES.get(strategy_name) \
                        or exts.get(f"distributionstrategy:{strategy_name}")
                    if scls is None:
                        raise CompileError(
                            f"unknown distribution strategy "
                            f"'{strategy_name}'")
                    dests = [d for d in dist.nested
                             if d.name.lower() == "destination"]
                    if not dests:
                        raise CompileError(
                            "@distribution needs at least one "
                            "@destination")
                    dist_opts = {k.lower(): v
                                 for k, v in dist.elements.items()}
                    dest_opts = []
                    children = []
                    for d in dests:
                        merged = dict(opts)
                        merged.update(
                            {k.lower(): v for k, v in d.elements.items()})
                        dest_opts.append(merged)
                        children.append(cls(merged, mcls(schema)))
                    strat = scls()
                    try:
                        strat.init(schema, dist_opts, dest_opts)
                    except ValueError as e:
                        raise CompileError(str(e)) from e
                    snk = DistributedSink(children, strat)
                else:
                    snk = cls(opts, mcls(schema))
                app.junctions[sid].subscribe(StreamCallbackReceiver(snk))
                app.sinks.append(snk)
