"""Host-side stream layer: events, junctions, input handlers, callbacks.

Reference mapping:
- Event (io.siddhi.core.event.Event)            -> Event dataclass
- StreamJunction (stream/StreamJunction.java:61) -> StreamJunction (sync pub/sub;
  `@Async(buffer.size, workers, batch.size.max)` switches it to a bounded
  host-side micro-batch queue drained by a worker thread — the TPU-shaped
  stand-in for the reference's LMAX Disruptor ring buffer,
  StreamJunction.java:276-313. batch.size.max is the latency/throughput
  dial: small batches -> low latency, large -> high throughput; on the
  columnar send_arrays path it caps the device chunk size instead, since
  that path already pipelines device-side without a thread hop.)
- InputHandler (stream/input/InputHandler.java:28) -> InputHandler
- StreamCallback (stream/output/StreamCallback.java:38) -> StreamCallback
- QueryCallback (query/output/callback/QueryCallback.java:37) -> QueryCallback

The junction is the host edge of the device dataflow: queries subscribe as
receivers; events are handed over as row lists and each receiver decides how
to batch them onto the device.
"""
from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Callable, Optional, Sequence

from ..obs.tracing import maybe_span

log = logging.getLogger("siddhi_tpu.stream")


@dataclasses.dataclass
class Event:
    timestamp: int
    data: tuple
    is_expired: bool = False

    def __repr__(self):
        kind = "EXPIRED" if self.is_expired else "CURRENT"
        return f"Event{{ts={self.timestamp}, data={list(self.data)}, {kind}}}"


class Receiver:
    """A junction subscriber (query input or stream callback)."""

    def receive(self, events: list[Event]) -> None:
        raise NotImplementedError


# sentinel that stops an @Async junction's drain worker (a dedicated
# object, not None: the sentinel can be dequeued mid-coalesce and must
# survive the carry slot)
_STOP = object()

# set while a drain worker holds the app barrier dispatching a batch —
# lets chained @Async publishes detect they must not block on a full
# downstream buffer (see StreamJunction.publish)
_IN_DISPATCH = threading.local()


class StreamJunction:
    """Per-stream pub/sub hub. Synchronous: publish calls every receiver
    inline, preserving the reference's sync-mode semantics
    (StreamJunction.java:166-177)."""

    def __init__(self, stream_id: str, schema):
        self.stream_id = stream_id
        self.schema = schema
        self.receivers: list[Receiver] = []
        self.fault_junction: Optional["StreamJunction"] = None
        self.on_error_action: str = "LOG"
        # wired by the app runtime (junction_for): the owning app (error
        # store resolution) and the app-wide per-stream error counters
        self.app = None
        self.error_stats = None
        # per-stream ingest throughput (obs registry
        # siddhi.<app>.stream.<id>.throughput); lazily created when
        # statistics are enabled — marked at the host boundary, so the
        # numbers are free (no device syncs)
        self.throughput = None
        # fan-out fusion group (plan/optimizer.py FanoutGroup): when the
        # optimizer fused this junction's plain-query subscribers into
        # one program, the batch publish paths call it ONCE per chunk
        # instead of once per receiver; re-derived with the fused chains
        self.fanout = None
        self._lock = threading.Lock()
        # @Async state (None = synchronous junction)
        self.async_conf: Optional[tuple[int, int]] = None  # (buffer, batch)
        self._queue = None
        self._worker: Optional[threading.Thread] = None
        self._drained = threading.Condition()
        self._pending = 0
        self._app = None

    def subscribe(self, receiver: Receiver) -> None:
        self.receivers.append(receiver)
        # a new subscriber can break a fused insert-into segment's
        # single-consumer invariant — re-derive segments on a live app
        # (no-op before start; core/runtime._build_fused_chains)
        app = self.app
        if app is not None and getattr(app, "running", False):
            app._rebuild_fused_chains()

    # -- @Async micro-batch pipeline -------------------------------------
    def enable_async(self, app, buffer_size: int, batch_max: int) -> None:
        """Switch to async mode: publishes enqueue into a bounded buffer
        (backpressure blocks the producer, like the Disruptor's
        BlockingWaitStrategy) and one worker drains it, coalescing up to
        batch.size.max events per dispatch (StreamHandler batching).
        `workers` collapses to one: device steps serialize on the chip, so
        extra host threads only add contention."""
        import queue as _q
        self.async_conf = (int(buffer_size), int(batch_max))
        self._queue = _q.Queue(maxsize=int(buffer_size))
        self._app = app
        self._worker = threading.Thread(
            target=self._drain_loop, name=f"async-{self.stream_id}",
            daemon=True)
        self._worker.start()

    def _drain_loop(self) -> None:
        # publishes are pre-split to <= batch.size.max at enqueue, so this
        # only ever coalesces whole items (order preserved via `carry`;
        # the _STOP sentinel also rides the carry slot so it is never
        # lost when dequeued mid-coalesce)
        import queue as _q
        _, batch_max = self.async_conf
        carry = None
        while True:
            item = carry if carry is not None else self._queue.get()
            carry = None
            if item is _STOP:
                with self._drained:
                    self._pending -= 1
                    self._drained.notify_all()
                return
            batch = list(item)
            n_items = 1
            while len(batch) < batch_max:
                try:
                    nxt = self._queue.get_nowait()
                except _q.Empty:
                    break
                if nxt is _STOP or len(batch) + len(nxt) > batch_max:
                    carry = nxt
                    break
                batch.extend(nxt)
                n_items += 1
            _IN_DISPATCH.active = True
            # @Async streams open the sampled SLO span at DISPATCH time
            # (queue wait is a saturation signal — async.depth — not
            # part of the ingest->emit latency; docs/observability.md)
            slo = getattr(self._app, "slo", None)
            tok = slo.ingest_begin(self.stream_id) if slo is not None \
                else None
            try:
                with self._app.barrier:
                    self._app.on_ingest(self.stream_id, batch)
                    self._publish_sync(batch)
            finally:
                if tok is not None:
                    slo.ingest_end(tok)
                _IN_DISPATCH.active = False
            with self._drained:
                self._pending -= n_items
                self._drained.notify_all()

    def flush_async(self, timeout: float = 30.0) -> None:
        """Block until every queued publish has been dispatched."""
        if self._queue is None:
            return
        import time as _t
        deadline = _t.monotonic() + timeout
        with self._drained:
            while self._pending > 0:
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"@Async stream '{self.stream_id}' did not drain "
                        f"within {timeout}s ({self._pending} pending)")
                self._drained.wait(remaining)

    def stop_async(self) -> None:
        if self._worker is None:
            return
        with self._drained:
            self._pending += 1
        self._queue.put(_STOP)
        self._worker.join(timeout=10)
        self._worker = None
        # later publishes fall back to the sync path instead of feeding a
        # dead queue (sends are already rejected by the running check)
        self._queue = None

    def mark_ingest(self, n: int) -> None:
        """Host-boundary stream throughput mark (obs); no-op with
        statistics OFF."""
        app = self.app
        if app is None or app.stats_level <= 0:
            return
        if self.throughput is None:
            from .stats import ThroughputTracker
            self.throughput = ThroughputTracker()
        self.throughput.mark(n)

    def count_error(self, n: int = 1) -> None:
        if self.error_stats is not None:
            self.error_stats.increment(self.stream_id, n)

    def publish_fault(self, events: list[Event], exc: Exception) -> bool:
        """Convert failing events + exception into fault events on the
        `!stream` junction; False when no fault junction is wired."""
        if self.fault_junction is None or not events:
            return False
        msg = f"{type(exc).__name__}: {exc}"
        self.fault_junction.publish([
            Event(e.timestamp, tuple(e.data) + (msg,),
                  is_expired=e.is_expired) for e in events])
        return True

    def store_error(self, events: list[Event], exc: Exception,
                    attempts: int = 1) -> bool:
        """Capture failing events into the app's error store for later
        replay; False when no app is wired (standalone junction)."""
        if self.app is None or not events:
            return False
        from ..resilience.errorstore import ErroredEvent
        self.app._error_store().store(
            self.app.name,
            ErroredEvent.from_events(
                self.stream_id, events, f"{type(exc).__name__}: {exc}",
                attempts=attempts, now=self.app.current_time()))
        return True

    def _handle_error(self, events: Optional[list[Event]],
                      exc: Exception) -> None:
        """@OnError routing (StreamJunction.handleError:368-430): STREAM
        converts the failing events + exception into fault events on the
        `!stream` junction; STORE captures them in the error store for
        replay; LOG (default) logs and continues."""
        self.count_error()
        if self.on_error_action == "STREAM" and events and \
                self.publish_fault(events, exc):
            return
        if self.on_error_action == "STORE" and events and \
                self.store_error(events, exc):
            log.warning(
                "stream '%s': %d event(s) routed to the error store "
                "after %s", self.stream_id, len(events), exc)
            return
        log.error("error processing events on stream '%s' (action=%s)",
                  self.stream_id, self.on_error_action, exc_info=exc)

    def publish(self, events: list[Event]) -> None:
        if not events:
            return
        queue = self._queue  # snapshot: stop_async may null it concurrently
        if queue is not None:
            # async mode: enqueue in <= batch.size.max slices; a full
            # buffer blocks the producer (Disruptor BlockingWaitStrategy).
            # EXCEPT when the producer is itself a drain worker holding
            # the app barrier (chained @Async streams): blocking there
            # deadlocks — no other worker can take the barrier to drain
            # this queue — so the slice is dispatched inline instead
            # (possible reordering against queued items, only in the
            # already-pathological full-buffer case; the reference's
            # Disruptor deadlocks outright in the same cycle).
            import queue as _q
            _, batch_max = self.async_conf
            slices = [events[i:i + batch_max]
                      for i in range(0, len(events), batch_max)]
            for s in slices:
                if getattr(_IN_DISPATCH, "active", False):
                    try:
                        with self._drained:
                            self._pending += 1
                        queue.put_nowait(s)
                    except _q.Full:
                        with self._drained:
                            self._pending -= 1
                        # inline dispatch still advances the clock (the
                        # drain path does this before _publish_sync too);
                        # the worker already holds the app barrier
                        with self._app.barrier:
                            self._app.on_ingest(self.stream_id, s)
                            self._publish_sync(s)
                else:
                    with self._drained:
                        self._pending += 1
                    queue.put(s)
            return
        self._publish_sync(events)

    def _publish_sync(self, events: list[Event]) -> None:
        with maybe_span(self.app, "junction", self.stream_id,
                        events=len(events)):
            for r in list(self.receivers):
                try:
                    r.receive(events)
                except Exception as exc:  # noqa: BLE001 — fault-stream
                    self._handle_error(events, exc)  # contract

    def publish_batch(self, batch, last_ts: int) -> None:
        """Columnar fast path: receivers that implement process_batch get
        the device batch directly; row-oriented receivers get decoded
        events (decoded at most once)."""
        decoded = None

        def decode():
            from .event import EXPIRED, rows_from_batch
            rows = rows_from_batch(self.schema.types, batch)
            return [Event(ts, vals, is_expired=(kind == EXPIRED))
                    for ts, kind, vals in rows]

        fanout = self.fanout
        with maybe_span(self.app, "junction", self.stream_id,
                        capacity=int(batch.capacity)):
            fanout_done = False
            for r in list(self.receivers):
                try:
                    if fanout is not None and fanout.covers(r):
                        # fused fan-out: ONE dispatch for every grouped
                        # subscriber (plan/optimizer.py), fired when the
                        # loop reaches the first member
                        if not fanout_done:
                            fanout_done = True
                            fanout.process_batch(batch, last_ts)
                    elif hasattr(r, "process_batch"):
                        r.process_batch(batch, last_ts)
                    else:
                        if decoded is None:
                            decoded = decode()
                        r.receive(decoded)
                except Exception as exc:  # noqa: BLE001 — fault-stream
                    if decoded is None:  # contract
                        try:
                            decoded = decode()
                        except Exception:  # noqa: BLE001
                            decoded = []
                    self._handle_error(decoded, exc)


class InputHandler:
    """User entry point for one stream (InputHandler.send overloads:
    Object[] / Event / Event[] — stream/input/InputHandler.java:40-75)."""

    def __init__(self, stream_id: str, junction: StreamJunction, app_runtime):
        self.stream_id = stream_id
        self.junction = junction
        self.app = app_runtime
        self._encoder = None  # lazy sticky PackedEncoder (core/ingest.py)
        self._pipeline = None  # lazy IngestPipeline (double-buffering)
        # serializes columnar sends per stream: the sticky encoder and
        # the pipeline worker are single-writer; ordering is always
        # _ingest_lock -> app.barrier (never the reverse)
        self._ingest_lock = threading.RLock()

    def send(self, data) -> None:
        if not self.app.running:
            raise RuntimeError(
                f"app '{self.app.name}' is not running; call start() first")
        now = self.app.current_time
        if isinstance(data, (list, tuple)) and len(data) == 0:
            return
        if isinstance(data, Event):
            events = [data]
        elif isinstance(data, (list, tuple)) and data and isinstance(
                data[0], Event):
            events = list(data)
        elif (isinstance(data, (list, tuple)) and data
              and isinstance(data[0], (list, tuple))):
            events = [Event(timestamp=now(), data=tuple(d)) for d in data]
        else:
            events = [Event(timestamp=now(), data=tuple(data))]
        self.junction.mark_ingest(len(events))
        buf = self.app._reorder.get(self.stream_id)
        if buf is not None:
            # bounded-lateness reorder buffer (resilience/ordering.py):
            # events are buffered, watermark-sorted and released through
            # _dispatch_rows; late events resolve per the stream policy
            with maybe_span(self.app, "ingest", self.stream_id,
                            events=len(events), buffered=1), \
                    self.app.barrier:
                buf.ingest_rows(events)
            return
        with maybe_span(self.app, "ingest", self.stream_id,
                        events=len(events)):
            if self.junction._queue is not None:
                # @Async: hand off to the junction's worker, which
                # advances the clock when the batch is actually
                # dispatched
                self.junction.publish(events)
                return
            with self.app.barrier:
                self._dispatch_rows(events)

    def _dispatch_rows(self, events) -> None:
        """Row publish body (caller holds the app barrier unless the
        junction is @Async): advance the clock, publish, fire timers
        armed during processing. The reorder-buffer flush releases
        through here too, so buffered and direct ingest share one
        dispatch contract."""
        if self.junction._queue is not None:
            self.junction.publish(events)
            return
        # sampled ingest->emit span (obs/slo.py): queries that decode
        # host rows during this dispatch attribute against its start
        slo = self.app.slo
        tok = slo.ingest_begin(self.stream_id) if slo is not None \
            else None
        try:
            self.app.on_ingest(self.stream_id, events)
            self.junction.publish(events)
            # timers armed DURING processing (e.g. hop boundaries the
            # chunk's own event-time jump crossed) fire now, not at the
            # next external tick
            if self.app._playback and \
                    self.app._playback_time is not None:
                self.app.scheduler.advance_to(self.app._playback_time)
        finally:
            if tok is not None:
                slo.ingest_end(tok)

    def send_arrays(self, ts, cols) -> None:
        """Columnar ingest: numpy timestamp + data column arrays
        (STRING columns as dictionary codes). Device batches with no
        per-row Python — the framework's intended high-throughput operating
        mode. Capacities are bucketed so jit caches stay warm.

        When every subscriber supports the packed path, a chunk travels as
        ONE adaptively-encoded uint8 buffer with one device transfer and
        zero per-batch host syncs (core/ingest.py); otherwise the
        EventBatch path is used."""
        if not self.app.running:
            raise RuntimeError(
                f"app '{self.app.name}' is not running; call start() first")
        n = len(ts)
        if n == 0:
            return
        self.app._columnar = True
        with self._ingest_lock:
            buf = self.app._reorder.get(self.stream_id)
            if buf is not None:
                # columnar reorder buffer: the chunk lands in numpy
                # segments; the watermark-driven flush re-emits sorted
                # chunks through _dispatch_arrays (same bucketed
                # capacities, zero new jits)
                self.junction.mark_ingest(n)
                with maybe_span(self.app, "ingest", self.stream_id,
                                rows=n, buffered=1), self.app.barrier:
                    buf.ingest_columns(ts, cols)
                return
            self._dispatch_arrays(ts, cols)

    def _dispatch_arrays(self, ts, cols, mark: bool = True) -> None:
        """Columnar publish body: chunk to bucketed capacities and
        dispatch. Direct ingest and reorder-buffer releases share this
        path; releases pass mark=False (ingest throughput was already
        marked at arrival). When every receiver is packed-capable and
        the pipeline kill switch is on, multi-chunk sends run double-
        buffered: the pipeline worker encodes chunk N+1 while this
        thread dispatches chunk N (core/ingest.py IngestPipeline)."""
        from .ingest import (PackedEncoder, pipeline_chunk_cap,
                             pipeline_enabled)
        from .runtime import BATCH_BUCKETS
        n = len(ts)
        packed_ok = all(getattr(r, "supports_packed", False)
                        for r in self.junction.receivers)
        max_cap = BATCH_BUCKETS[-1]
        # sort-heavy receivers cap their step capacity (see runtime.py
        # SORT_HEAVY_CAP): chunk accordingly so every receiver can consume
        # the chunk without re-splitting. Packed consumers that scan
        # sub-batches inside the step (max_packed_capacity=None) take the
        # whole chunk in one dispatch instead.
        for r in self.junction.receivers:
            if packed_ok:
                rc = getattr(r, "max_packed_capacity",
                             getattr(r, "max_step_capacity", None))
            else:
                rc = getattr(r, "max_step_capacity", None)
            if rc is not None:
                max_cap = min(max_cap, rc)
        if self.junction.async_conf is not None:
            # @Async batch.size.max caps the device chunk on the columnar
            # path — the latency/throughput dial (small chunks = low
            # latency, big = throughput); no thread hop is added since
            # packed dispatch already pipelines device-side
            max_cap = min(max_cap, self.junction.async_conf[1])
        # cost-evidence chunk caps (plan/optimizer.py): a fused group or
        # chain head with measured per-capacity centers pins the chunk
        # size the evidence says is fastest per event
        fanout = self.junction.fanout
        if fanout is not None and fanout.preferred_cap:
            max_cap = min(max_cap, fanout.preferred_cap)
        for r in self.junction.receivers:
            pc = getattr(r, "preferred_ingest_cap", None)
            if pc:
                max_cap = min(max_cap, pc)
        if packed_ok and self._encoder is None:
            self._encoder = PackedEncoder(self.junction.schema)
        pipelined = packed_ok and pipeline_enabled()
        if pipelined:
            max_cap = pipeline_chunk_cap(n, max_cap)
        if pipelined and n > max_cap:
            self._dispatch_packed_pipelined(ts, cols, max_cap, mark)
            return
        for start in range(0, n, max_cap):
            t = ts[start:start + max_cap]
            c = [col[start:start + max_cap] for col in cols]
            self._dispatch_chunk(t, c, packed_ok, mark)

    def _dispatch_chunk(self, t, c, packed_ok: bool, mark: bool,
                        chunk=None) -> None:
        """Dispatch ONE bucketed chunk (serial and pipelined branches
        share this body; the pipelined branch passes a pre-encoded
        ``chunk``)."""
        from .event import batch_from_columns
        from .ingest import PackedChunk
        from .runtime import bucket_capacity
        last_ts = int(t[-1])
        if mark:
            self.junction.mark_ingest(len(t))
        # sampled ingest->emit span per device chunk (obs/slo.py)
        slo = self.app.slo
        tok = slo.ingest_begin(self.stream_id) if slo is not None \
            else None
        try:
            with maybe_span(self.app, "ingest", self.stream_id,
                            rows=len(t)), self.app.barrier:
                # columnar fast path: fire only dues STRICTLY BEFORE
                # the chunk's span now — in-span window expiry happens
                # inside the chunk's own step at exact per-row points,
                # so firing intermediate timers first only adds
                # dispatches (the post-publish advance_to below
                # catches up the rest)
                self.app.on_ingest_span(int(t[0]), last_ts)
                if packed_ok:
                    if chunk is None:
                        chunk = PackedChunk.build(
                            self._encoder, t, c, bucket_capacity(len(t)),
                            now=self.app.current_time())
                    self._publish_packed(chunk)
                else:
                    batch = batch_from_columns(
                        self.junction.schema, t, c,
                        capacity=bucket_capacity(len(t)))
                    self.junction.publish_batch(batch, last_ts)
                if self.app._playback:
                    # catch up timers the chunk's own steps did not
                    # subsume (multi-boundary batch flushes, absent
                    # deadlines past the span)
                    self.app.scheduler.advance_to(last_ts)
        finally:
            if tok is not None:
                slo.ingest_end(tok)

    def _publish_packed(self, chunk) -> None:
        fanout = self.junction.fanout
        fanout_done = False
        for r in list(self.junction.receivers):
            if fanout is not None and fanout.covers(r):
                # fused fan-out: one program for every grouped
                # subscriber (plan/optimizer.py)
                if not fanout_done:
                    fanout_done = True
                    fanout.process_packed(chunk)
                continue
            r.process_packed(chunk)

    def _dispatch_packed_pipelined(self, ts, cols, max_cap: int,
                                   mark: bool) -> None:
        """Double-buffered columnar dispatch: the pipeline worker
        encodes chunk N+1 (pure numpy — the heavy ufuncs release the
        GIL) while this thread dispatches chunk N (H2D + compute via
        JAX async dispatch). Playback ``now`` per chunk is precomputed
        host-side to the exact value the serial path's on_ingest_span
        would install, so both pipeline settings stay bit-identical
        (tests/test_ingest_pipeline.py)."""
        from .ingest import IngestPipeline, PackedChunk
        from .runtime import bucket_capacity
        app = self.app
        n = len(ts)
        slices = [(ts[s:s + max_cap], [col[s:s + max_cap]
                                       for col in cols])
                  for s in range(0, n, max_cap)]
        if app._playback:
            nows = []
            cur = app._playback_time
            reorder = bool(app._reorder)
            for t, _ in slices:
                last = int(t[-1])
                cur = max(last, cur) if (reorder and cur is not None) \
                    else last
                nows.append(cur)
        else:
            nows = [None] * len(slices)
        if self._pipeline is None:
            self._pipeline = IngestPipeline(self.stream_id)
        enc = self._encoder

        def encode(i):
            t, c = slices[i]
            now = nows[i]
            return PackedChunk.build(
                enc, t, c, bucket_capacity(len(t)),
                now=app.current_time() if now is None else now)

        def dispatch(i, chunk):
            t, c = slices[i]
            self._dispatch_chunk(t, c, True, mark, chunk=chunk)

        st = self._pipeline.stats
        before = (st["wall_s"], st["overlap_s"])
        with maybe_span(app, "ingest_pipeline", self.stream_id,
                        chunks=len(slices), rows=n) as sp:
            self._pipeline.run(len(slices), encode, dispatch)
            # overlap attribution on the span itself: how much of this
            # send's encode ran concurrently with H2D/compute
            sp.set(wall_s=round(st["wall_s"] - before[0], 6),
                   overlap_s=round(st["overlap_s"] - before[1], 6))

    def _dispatch_device_batch(self, batch, first_ts: int,
                               last_ts: int) -> None:
        """Publish a device-resident EventBatch (reorder-ring release,
        resilience/ordering.py) under the same clock/timer contract as
        _dispatch_chunk — no host column transfer, no re-encode. The
        caller holds the app barrier (ring flushes run inside the
        ingest barrier section; the barrier is reentrant)."""
        slo = self.app.slo
        tok = slo.ingest_begin(self.stream_id) if slo is not None \
            else None
        try:
            with maybe_span(self.app, "ingest", self.stream_id,
                            rows=int(batch.capacity)), self.app.barrier:
                self.app.on_ingest_span(int(first_ts), int(last_ts))
                self.junction.publish_batch(batch, int(last_ts))
                if self.app._playback:
                    self.app.scheduler.advance_to(int(last_ts))
        finally:
            if tok is not None:
                slo.ingest_end(tok)

    def ingest_stats(self) -> Optional[dict]:
        """Zero-copy + pipeline counters for ``statistics()['ingest']``
        (core/runtime.py _collect_observability)."""
        out: dict = {}
        enc = self._encoder
        if enc is not None and enc.stats["chunks"]:
            out.update(enc.stats)
        p = self._pipeline
        if p is not None and p.stats["sends"]:
            st = p.stats
            busy = st["encode_s"] + st["dispatch_s"]
            out["pipeline_sends"] = st["sends"]
            out["pipeline_chunks"] = st["chunks"]
            out["encode_s"] = round(st["encode_s"], 6)
            out["dispatch_s"] = round(st["dispatch_s"], 6)
            out["wall_s"] = round(st["wall_s"], 6)
            out["overlap_s"] = round(st["overlap_s"], 6)
            out["overlap_frac"] = round(st["overlap_s"] / busy, 4) \
                if busy > 0 else 0.0
        return out or None

    def close(self) -> None:
        """Join the ingest pipeline worker (runtime shutdown)."""
        with self._ingest_lock:
            if self._pipeline is not None:
                self._pipeline.close()
                self._pipeline = None


class StreamCallback(Receiver):
    """Subscribe to a stream and receive raw events. Subclass and override
    receive(), or pass fn= to the constructor."""

    def __init__(self, fn: Optional[Callable[[list[Event]], None]] = None):
        self._fn = fn

    def receive(self, events: list[Event]) -> None:
        if self._fn is not None:
            self._fn(events)


class QueryCallback:
    """Per-query callback: receive(timestamp, in_events, removed_events),
    matching QueryCallback.receive(ts, inEvents, removeEvents)."""

    def __init__(self, fn: Optional[Callable] = None):
        self._fn = fn

    def receive(self, timestamp: int, in_events, removed_events) -> None:
        if self._fn is not None:
            self._fn(timestamp, in_events, removed_events)
