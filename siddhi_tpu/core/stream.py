"""Host-side stream layer: events, junctions, input handlers, callbacks.

Reference mapping:
- Event (io.siddhi.core.event.Event)            -> Event dataclass
- StreamJunction (stream/StreamJunction.java:61) -> StreamJunction (sync pub/sub;
  async micro-batch pipelining is a junction option, see @Async in runtime.py)
- InputHandler (stream/input/InputHandler.java:28) -> InputHandler
- StreamCallback (stream/output/StreamCallback.java:38) -> StreamCallback
- QueryCallback (query/output/callback/QueryCallback.java:37) -> QueryCallback

The junction is the host edge of the device dataflow: queries subscribe as
receivers; events are handed over as row lists and each receiver decides how
to batch them onto the device.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional, Sequence


@dataclasses.dataclass
class Event:
    timestamp: int
    data: tuple
    is_expired: bool = False

    def __repr__(self):
        kind = "EXPIRED" if self.is_expired else "CURRENT"
        return f"Event{{ts={self.timestamp}, data={list(self.data)}, {kind}}}"


class Receiver:
    """A junction subscriber (query input or stream callback)."""

    def receive(self, events: list[Event]) -> None:
        raise NotImplementedError


class StreamJunction:
    """Per-stream pub/sub hub. Synchronous: publish calls every receiver
    inline, preserving the reference's sync-mode semantics
    (StreamJunction.java:166-177)."""

    def __init__(self, stream_id: str, schema):
        self.stream_id = stream_id
        self.schema = schema
        self.receivers: list[Receiver] = []
        self.fault_junction: Optional["StreamJunction"] = None
        self.on_error_action: str = "LOG"
        self._lock = threading.Lock()

    def subscribe(self, receiver: Receiver) -> None:
        self.receivers.append(receiver)

    def _handle_error(self, events: Optional[list[Event]],
                      exc: Exception) -> None:
        """@OnError routing (StreamJunction.handleError:368-430): STREAM
        converts the failing events + exception into fault events on the
        `!stream` junction; LOG (default) logs and continues."""
        if self.on_error_action == "STREAM" and \
                self.fault_junction is not None and events:
            msg = f"{type(exc).__name__}: {exc}"
            self.fault_junction.publish([
                Event(e.timestamp, tuple(e.data) + (msg,),
                      is_expired=e.is_expired) for e in events])
            return
        import traceback
        print(f"[siddhi_tpu] error processing events on stream "
              f"'{self.stream_id}' (action=LOG):")
        traceback.print_exc()

    def publish(self, events: list[Event]) -> None:
        if not events:
            return
        for r in list(self.receivers):
            try:
                r.receive(events)
            except Exception as exc:  # noqa: BLE001 — fault-stream contract
                self._handle_error(events, exc)

    def publish_batch(self, batch, last_ts: int) -> None:
        """Columnar fast path: receivers that implement process_batch get
        the device batch directly; row-oriented receivers get decoded
        events (decoded at most once)."""
        decoded = None

        def decode():
            from .event import EXPIRED, rows_from_batch
            rows = rows_from_batch(self.schema.types, batch)
            return [Event(ts, vals, is_expired=(kind == EXPIRED))
                    for ts, kind, vals in rows]

        for r in list(self.receivers):
            try:
                if hasattr(r, "process_batch"):
                    r.process_batch(batch, last_ts)
                else:
                    if decoded is None:
                        decoded = decode()
                    r.receive(decoded)
            except Exception as exc:  # noqa: BLE001 — fault-stream contract
                if decoded is None:
                    try:
                        decoded = decode()
                    except Exception:  # noqa: BLE001
                        decoded = []
                self._handle_error(decoded, exc)


class InputHandler:
    """User entry point for one stream (InputHandler.send overloads:
    Object[] / Event / Event[] — stream/input/InputHandler.java:40-75)."""

    def __init__(self, stream_id: str, junction: StreamJunction, app_runtime):
        self.stream_id = stream_id
        self.junction = junction
        self.app = app_runtime
        self._encoder = None  # lazy sticky PackedEncoder (core/ingest.py)

    def send(self, data) -> None:
        if not self.app.running:
            raise RuntimeError(
                f"app '{self.app.name}' is not running; call start() first")
        now = self.app.current_time
        if isinstance(data, (list, tuple)) and len(data) == 0:
            return
        if isinstance(data, Event):
            events = [data]
        elif isinstance(data, (list, tuple)) and data and isinstance(
                data[0], Event):
            events = list(data)
        elif (isinstance(data, (list, tuple)) and data
              and isinstance(data[0], (list, tuple))):
            events = [Event(timestamp=now(), data=tuple(d)) for d in data]
        else:
            events = [Event(timestamp=now(), data=tuple(data))]
        with self.app.barrier:
            self.app.on_ingest(self.stream_id, events)
            self.junction.publish(events)

    def send_arrays(self, ts, cols) -> None:
        """Columnar ingest: numpy timestamp + data column arrays
        (STRING columns as dictionary codes). Device batches with no
        per-row Python — the framework's intended high-throughput operating
        mode. Capacities are bucketed so jit caches stay warm.

        When every subscriber supports the packed path, a chunk travels as
        ONE adaptively-encoded uint8 buffer with one device transfer and
        zero per-batch host syncs (core/ingest.py); otherwise the
        EventBatch path is used."""
        from .event import batch_from_columns
        from .ingest import PackedChunk, PackedEncoder
        from .runtime import BATCH_BUCKETS, bucket_capacity
        if not self.app.running:
            raise RuntimeError(
                f"app '{self.app.name}' is not running; call start() first")
        n = len(ts)
        if n == 0:
            return
        packed_ok = all(getattr(r, "supports_packed", False)
                        for r in self.junction.receivers)
        max_cap = BATCH_BUCKETS[-1]
        # sort-heavy receivers cap their step capacity (see runtime.py
        # SORT_HEAVY_CAP): chunk accordingly so every receiver can consume
        # the chunk without re-splitting
        for r in self.junction.receivers:
            rc = getattr(r, "max_step_capacity", None)
            if rc is not None:
                max_cap = min(max_cap, rc)
        for start in range(0, n, max_cap):
            t = ts[start:start + max_cap]
            c = [col[start:start + max_cap] for col in cols]
            last_ts = int(t[-1])
            with self.app.barrier:
                self.app.on_ingest_ts(last_ts)
                if packed_ok:
                    if self._encoder is None:
                        self._encoder = PackedEncoder(self.junction.schema)
                    chunk = PackedChunk.build(
                        self._encoder, t, c, bucket_capacity(len(t)),
                        now=self.app.current_time())
                    for r in list(self.junction.receivers):
                        r.process_packed(chunk)
                else:
                    batch = batch_from_columns(
                        self.junction.schema, t, c,
                        capacity=bucket_capacity(len(t)))
                    self.junction.publish_batch(batch, last_ts)


class StreamCallback(Receiver):
    """Subscribe to a stream and receive raw events. Subclass and override
    receive(), or pass fn= to the constructor."""

    def __init__(self, fn: Optional[Callable[[list[Event]], None]] = None):
        self._fn = fn

    def receive(self, events: list[Event]) -> None:
        if self._fn is not None:
            self._fn(events)


class QueryCallback:
    """Per-query callback: receive(timestamp, in_events, removed_events),
    matching QueryCallback.receive(ts, inEvents, removeEvents)."""

    def __init__(self, fn: Optional[Callable] = None):
        self._fn = fn

    def receive(self, timestamp: int, in_events, removed_events) -> None:
        if self._fn is not None:
            self._fn(timestamp, in_events, removed_events)
