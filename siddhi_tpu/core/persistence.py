"""Checkpoint / restore: snapshot every piece of device state to host
bytes, store by revision, restore bit-exact.

Reference mapping:
- SnapshotService.fullSnapshot (util/snapshot/SnapshotService.java:90-183)
  — quiesce, walk partitionId -> query -> element -> State.snapshot(),
  Java-serialize                          -> SiddhiAppRuntime.snapshot()
- PersistenceStore SPI (util/persistence/InMemoryPersistenceStore.java:33,
  FileSystemPersistenceStore.java:37)     -> the two store classes here
- persist()/restoreRevision()/restoreLastRevision()/clearAllRevisions()
  (core/SiddhiAppRuntimeImpl.java:677-755) -> same-named runtime methods

TPU-native simplification: every piece of runtime state is ALREADY a pytree
of device arrays (operator states, NFA pending tables, join side states,
table contents, partition slot tables). A full snapshot is one
jax.device_get of those pytrees + pickle; restore is the inverse. No
per-element StateHolder walk, no ThreadBarrier: the runtime locks each
query once (the step lock) while reading its state.

The snapshot also carries the GLOBAL string dictionary (codes embedded in
device columns must decode identically after a process restart) and the
playback clock.
"""
from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Optional

SNAPSHOT_FORMAT = 1


class PersistenceStore:
    """SPI: save/load/clear revisions for an app
    (util/persistence/PersistenceStore.java)."""

    def save(self, app_name: str, revision: str, data: bytes) -> None:
        raise NotImplementedError

    def load(self, app_name: str, revision: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_last_revision(self, app_name: str) -> Optional[str]:
        raise NotImplementedError

    def list_revisions(self, app_name: str) -> list[str]:
        """All revisions for an app, oldest first (the checkpoint
        supervisor walks this newest-first to fall back past corrupted
        snapshots)."""
        raise NotImplementedError

    def clear_all_revisions(self, app_name: str) -> None:
        raise NotImplementedError


class InMemoryPersistenceStore(PersistenceStore):
    """(InMemoryPersistenceStore.java:33)"""

    def __init__(self):
        self._lock = threading.Lock()
        self._revisions: dict[str, dict[str, bytes]] = {}

    def save(self, app_name, revision, data):
        with self._lock:
            self._revisions.setdefault(app_name, {})[revision] = data

    def load(self, app_name, revision):
        with self._lock:  # save() mutates nested dicts concurrently
            return self._revisions.get(app_name, {}).get(revision)

    def get_last_revision(self, app_name):
        with self._lock:
            revs = self._revisions.get(app_name)
            return sorted(revs)[-1] if revs else None

    def list_revisions(self, app_name):
        with self._lock:
            return sorted(self._revisions.get(app_name, ()))

    def clear_all_revisions(self, app_name):
        with self._lock:
            self._revisions.pop(app_name, None)


class FileSystemPersistenceStore(PersistenceStore):
    """One file per revision under base_dir/app_name/
    (FileSystemPersistenceStore.java:37)."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def _dir(self, app_name: str) -> str:
        return os.path.join(self.base_dir, app_name)

    def save(self, app_name, revision, data):
        d = self._dir(app_name)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{revision}.tmp")
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, os.path.join(d, f"{revision}.snapshot"))

    def load(self, app_name, revision):
        path = os.path.join(self._dir(app_name), f"{revision}.snapshot")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def get_last_revision(self, app_name):
        revs = self.list_revisions(app_name)
        return revs[-1] if revs else None

    def list_revisions(self, app_name):
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return []
        return sorted(f[:-len(".snapshot")] for f in os.listdir(d)
                      if f.endswith(".snapshot"))

    def clear_all_revisions(self, app_name):
        d = self._dir(app_name)
        if not os.path.isdir(d):
            return
        for f in os.listdir(d):
            if f.endswith(".snapshot"):
                os.remove(os.path.join(d, f))


_rev_lock = threading.Lock()
_last_rev_ms = 0


def new_revision(app_name: str) -> str:
    """Monotonic, sortable revision id (reference: restoreRevision ids are
    '<millis>_<appName>'). The wall clock alone is NOT monotonic at
    checkpoint speed — two persists inside the same millisecond would
    collide on one id (observed once snapshots stopped copying state
    buffers), so the last issued millisecond is bumped forward when the
    clock hasn't advanced."""
    global _last_rev_ms
    with _rev_lock:
        ms = int(time.time() * 1000)
        if ms <= _last_rev_ms:
            ms = _last_rev_ms + 1
        _last_rev_ms = ms
    return f"{ms:015d}_{app_name}"


def dump_strings() -> list:
    """Snapshot the global string dictionary (codes -> strings)."""
    from .types import GLOBAL_STRINGS
    return list(GLOBAL_STRINGS._to_str)


def load_strings(entries: list) -> None:
    """Merge a snapshot's string dictionary back, code-stable.

    After a process restart the table is (nearly) empty and the snapshot's
    codes re-occupy their slots. If this process already interned a
    DIFFERENT string at a conflicting code, the snapshot cannot be mapped
    — that is an operator error (restoring into a live, unrelated process)
    and raises.
    """
    from .types import GLOBAL_STRINGS as g
    with g._lock:
        for code, s in enumerate(entries):
            if code < len(g._to_str):
                cur = g._to_str[code]
                if cur != s:
                    raise ValueError(
                        f"string-table conflict at code {code}: snapshot "
                        f"has {s!r}, process has {cur!r} — restore into a "
                        "fresh process")
            else:
                g._to_str.append(s)
                if s is not None:
                    g._to_code[s] = code


def serialize(payload: dict) -> bytes:
    return pickle.dumps({"format": SNAPSHOT_FORMAT, **payload},
                        protocol=pickle.HIGHEST_PROTOCOL)


class _SnapshotUnpickler(pickle.Unpickler):
    """Restricted unpickler: snapshot payloads are pure data (numpy
    arrays/scalars, containers, strings, numbers), so only numpy
    reconstruction callables are allowed. A tampered revision file can
    then corrupt state but NOT execute arbitrary code — the reference's
    Java-serialization snapshots have the same class of weakness with no
    such guard."""

    _ALLOWED = {
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy.core.numeric", "_frombuffer"),
        ("numpy._core.numeric", "_frombuffer"),
        ("numpy", "ndarray"),
        ("numpy", "dtype"),
        ("numpy", "bool_"),
    }

    def find_class(self, module, name):
        # exact allowlist + numpy.dtypes dtype classes ONLY — a broad
        # "any public numpy callable" rule would admit gadgets like
        # numpy.savetxt/fromfile (attacker-controlled file IO)
        if (module, name) in self._ALLOWED or (
                module == "numpy.dtypes" and name.endswith("DType")):
            import importlib
            mod = importlib.import_module(module)
            return getattr(mod, name)
        raise pickle.UnpicklingError(
            f"snapshot refers to non-data callable {module}.{name} — "
            "refusing to unpickle (tampered or incompatible revision)")


def deserialize(data: bytes) -> dict:
    import io
    payload = _SnapshotUnpickler(io.BytesIO(data)).load()
    if payload.get("format") != SNAPSHOT_FORMAT:
        raise ValueError(f"unsupported snapshot format "
                         f"{payload.get('format')!r}")
    return payload
