"""SiddhiManager: top-level facade (reference: core/SiddhiManager.java:49).

createSiddhiAppRuntime parses + plans + returns a runtime; also the
registration point for persistence stores and extensions.
"""
from __future__ import annotations

from typing import Optional

from ..lang import ast as A
from ..lang.parser import parse
from .runtime import SiddhiAppRuntime


class SiddhiManager:
    def __init__(self):
        self.app_runtimes: dict[str, SiddhiAppRuntime] = {}
        self.extensions: dict[str, object] = {}
        self.persistence_store = None
        self.error_store = None

    def create_siddhi_app_runtime(self, source, partition_mesh=None,
                                  mesh=None) -> SiddhiAppRuntime:
        """mesh: optional jax.sharding.Mesh — partition blocks then
        shard their key-slot axis over its first axis via the regex
        rule table (multi-chip key-partitioned execution,
        parallel/partition.py + parallel/sharding.py), and the runtime
        reports per-device placement in statistics()['mesh'].
        ``partition_mesh`` is the pre-PR-12 name, kept as an alias."""
        if isinstance(source, str):
            app_ast = parse(source)
        elif isinstance(source, A.SiddhiApp):
            app_ast = source
        else:
            raise TypeError("expected SiddhiQL text or SiddhiApp")
        rt = SiddhiAppRuntime(app_ast, manager=self,
                              partition_mesh=partition_mesh
                              if partition_mesh is not None else mesh)
        self.app_runtimes[rt.name] = rt
        return rt

    # camelCase alias mirroring the reference API surface
    createSiddhiAppRuntime = create_siddhi_app_runtime

    def get_siddhi_app_runtime(self, name: str) -> Optional[SiddhiAppRuntime]:
        return self.app_runtimes.get(name)

    def validate_siddhi_app(self, source) -> None:
        """Parse + plan, then discard (reference SiddhiManager.validateSiddhiApp)."""
        if isinstance(source, str):
            app_ast = parse(source)
        else:
            app_ast = source
        SiddhiAppRuntime(app_ast, manager=None)

    def warmup(self, buckets=None, samples=None, workers=None) -> dict:
        """AOT-compile every registered app's step programs (see
        SiddhiAppRuntime.warmup / docs/compile_cache.md). Returns
        {app_name: warmup telemetry}."""
        return {name: rt.warmup(buckets=buckets, samples=samples,
                                workers=workers)
                for name, rt in self.app_runtimes.items()}

    def set_extension(self, name: str, ext) -> None:
        self.extensions[name.lower()] = ext

    def set_persistence_store(self, store) -> None:
        self.persistence_store = store

    def set_error_store(self, store) -> None:
        """Shared error store (resilience/errorstore.py): failed events
        captured by on.error='STORE' land here and survive app restarts
        for replay."""
        self.error_store = store

    def shutdown(self) -> None:
        for rt in list(self.app_runtimes.values()):
            rt.shutdown()
        self.app_runtimes.clear()
