"""Record tables: external-store-backed tables + cache fronting.

Reference mapping:
- table/record/AbstractRecordTable.java:55 — store SPI (init/add/find/
  contains/delete/update/updateOrAdd over Object[] records, conditions
  handed to the store pre-compiled)            -> RecordTable
- table/record/ExpressionBuilder.java + BaseExpressionVisitor.java —
  condition AST walked through a visitor the store implements (RDBMS
  stores build SQL, Mongo stores build queries...) -> StoreCondition
  tree + ExpressionVisitor
- table/record/AbstractQueryableRecordTable.java:99 — compiled-selection
  pushdown                                      -> find() takes the
  compiled condition; selection/order/limit stay host-side (stores that
  can push further override find_select)
- table/CacheTable.java:62 (+ CacheTableFIFO/LRU/LFU, util/cache/
  CacheExpirer.java) — bounded cache fronting a record table
                                               -> CacheTableRuntime: the
  cache is a DEVICE-resident TableRuntime (bounded columnar buffer), so
  cached store tables join/filter on-device like in-memory tables; the
  host keeps recency/frequency metadata and applies policy eviction
- query/table/util/TestStore.java — in-memory AbstractRecordTable test
  double                                        -> InMemoryStore

TPU-first split: record tables are host/IO objects by nature (network
stores), so reads/writes run on the host at query-output and on-demand
boundaries; ONLY the @Cache front is device-resident state. Joins and
IN-table filters require @Cache (the device step cannot call out to a
store mid-jit); uncached store tables reject those plans with a clear
error, matching the "explicit capacity, explicit boundary" design
stance.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Iterable, Optional

from ..lang import ast as A
from ..ops.expr import CompileError
from .event import StreamSchema

# ---------------------------------------------------------------------------
# compiled store conditions (ExpressionBuilder / BaseExpressionVisitor)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StoreCompare:
    op: str                     # '==','!=','<','<=','>','>='
    left: "StoreNode"
    right: "StoreNode"


@dataclasses.dataclass
class StoreAnd:
    left: "StoreNode"
    right: "StoreNode"


@dataclasses.dataclass
class StoreOr:
    left: "StoreNode"
    right: "StoreNode"


@dataclasses.dataclass
class StoreNot:
    expr: "StoreNode"


@dataclasses.dataclass
class StoreConstant:
    value: Any


@dataclasses.dataclass
class StoreVariable:
    """A table attribute reference (the store's own column)."""
    attribute: str
    index: int


@dataclasses.dataclass
class StoreParameter:
    """A stream-side value: bound per matching event at call time
    (the reference's variableExpressionExecutorMap placeholders)."""
    name: str


StoreNode = Any


class ExpressionVisitor:
    """Walk hooks for store implementations translating a condition to
    their native query language (BaseExpressionVisitor.java)."""

    def begin_visit_and(self):
        pass

    def end_visit_and(self):
        pass

    def begin_visit_or(self):
        pass

    def end_visit_or(self):
        pass

    def begin_visit_not(self):
        pass

    def end_visit_not(self):
        pass

    def begin_visit_compare(self, op: str):
        pass

    def end_visit_compare(self, op: str):
        pass

    def visit_constant(self, value):
        pass

    def visit_store_variable(self, attribute: str):
        pass

    def visit_parameter(self, name: str):
        pass


def walk(node: StoreNode, v: ExpressionVisitor) -> None:
    if isinstance(node, StoreAnd):
        v.begin_visit_and()
        walk(node.left, v)
        walk(node.right, v)
        v.end_visit_and()
    elif isinstance(node, StoreOr):
        v.begin_visit_or()
        walk(node.left, v)
        walk(node.right, v)
        v.end_visit_or()
    elif isinstance(node, StoreNot):
        v.begin_visit_not()
        walk(node.expr, v)
        v.end_visit_not()
    elif isinstance(node, StoreCompare):
        v.begin_visit_compare(node.op)
        walk(node.left, v)
        walk(node.right, v)
        v.end_visit_compare(node.op)
    elif isinstance(node, StoreConstant):
        v.visit_constant(node.value)
    elif isinstance(node, StoreVariable):
        v.visit_store_variable(node.attribute)
    elif isinstance(node, StoreParameter):
        v.visit_parameter(node.name)
    else:
        raise TypeError(f"unknown store condition node {node!r}")


@dataclasses.dataclass
class CompiledStoreCondition:
    """A condition split into a store-side tree + stream-side parameter
    evaluators (called per triggering event on the host)."""
    root: Optional[StoreNode]                     # None == match-all
    param_fns: dict                               # name -> fn(event_row)

    def bind(self, event_row: Optional[tuple]) -> dict:
        return {n: f(event_row) for n, f in self.param_fns.items()}

    def matches(self, record: tuple, params: dict) -> bool:
        """Default in-memory evaluation (stores with their own query
        engine never call this)."""
        return _eval(self.root, record, params) if self.root is not None \
            else True


def _eval(node, rec, params):
    if isinstance(node, StoreAnd):
        return _eval(node.left, rec, params) and \
            _eval(node.right, rec, params)
    if isinstance(node, StoreOr):
        return _eval(node.left, rec, params) or \
            _eval(node.right, rec, params)
    if isinstance(node, StoreNot):
        return not _eval(node.expr, rec, params)
    if isinstance(node, StoreCompare):
        lv = _value(node.left, rec, params)
        rv = _value(node.right, rec, params)
        if lv is None or rv is None:
            return False  # compare-with-null is FALSE (reference)
        return {"==": lv == rv, "!=": lv != rv, "<": lv < rv,
                "<=": lv <= rv, ">": lv > rv, ">=": lv >= rv}[node.op]
    raise TypeError(f"non-boolean store node {node!r}")


def _value(node, rec, params):
    if isinstance(node, StoreConstant):
        return node.value
    if isinstance(node, StoreVariable):
        return rec[node.index]
    if isinstance(node, StoreParameter):
        return params[node.name]
    raise TypeError(f"non-value store node {node!r}")


def compile_store_condition(expr: Optional[A.Expression],
                            table_id: str, schema: StreamSchema,
                            stream_eval: Callable[[A.Expression],
                                                  Callable],
                            stream_has: Callable[[str], bool] =
                            lambda n: False,
                            alias: Optional[str] = None) -> \
        CompiledStoreCondition:
    """Split an ON condition into the store-side tree (references to the
    table's own attributes, constants, comparisons) and stream-side
    subexpressions, which become named parameters evaluated per event
    (CollectionExpressionParser's store/stream split). Bare attribute
    names bind to the EVENT side when it has the attribute — the same
    meta resolution order as the device TableOnScope
    (ExpressionParser.java:1330-1339) — with the table column as
    fallback."""
    params: dict = {}

    def is_table_var(e) -> bool:
        if not isinstance(e, A.Variable):
            return False
        if e.stream_ref is not None and e.stream_ref not in (
                table_id, alias) and not stream_has(e.attribute):
            raise CompileError(
                f"unknown stream reference '{e.stream_ref}' in store "
                f"condition for table '{table_id}'")
        if e.stream_ref in (table_id, alias) and e.stream_ref is not None:
            if e.attribute not in schema.names:
                raise CompileError(
                    f"'{e.attribute}' is not an attribute of table "
                    f"'{table_id}'")
            return True
        return (e.stream_ref is None and e.attribute in schema.names
                and not stream_has(e.attribute))

    def mentions_table(e) -> bool:
        if is_table_var(e):
            return True
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if hasattr(v, "__dataclass_fields__") and mentions_table(v):
                return True
            if isinstance(v, (list, tuple)):
                for x in v:
                    if hasattr(x, "__dataclass_fields__") and \
                            mentions_table(x):
                        return True
        return False

    def as_param(e: A.Expression) -> StoreParameter:
        name = f"p{len(params)}"
        params[name] = stream_eval(e)
        return StoreParameter(name)

    def conv(e: A.Expression) -> StoreNode:
        if isinstance(e, A.And):
            return StoreAnd(conv(e.left), conv(e.right))
        if isinstance(e, A.Or):
            return StoreOr(conv(e.left), conv(e.right))
        if isinstance(e, A.Not):
            return StoreNot(conv(e.expr))
        if isinstance(e, A.Compare):
            return StoreCompare(e.op, conv_val(e.left), conv_val(e.right))
        if isinstance(e, A.Constant):
            return StoreConstant(e.value)
        raise CompileError(
            f"store condition: unsupported construct "
            f"{type(e).__name__} (push-down supports and/or/not/compare)")

    def conv_val(e: A.Expression) -> StoreNode:
        if isinstance(e, A.Constant):
            return StoreConstant(e.value)
        if is_table_var(e):
            return StoreVariable(e.attribute,
                                 schema.index_of(e.attribute))
        if mentions_table(e):
            raise CompileError(
                "store condition: table attributes may only appear as "
                "bare comparison operands for push-down")
        return as_param(e)

    if expr is None:
        return CompiledStoreCondition(None, {})
    return CompiledStoreCondition(conv(expr), params)


# ---------------------------------------------------------------------------
# the store SPI
# ---------------------------------------------------------------------------


class RecordTable:
    """Extension point for external stores (AbstractRecordTable.java:55).
    Subclass, implement the record ops, register the class under its
    @Store(type='...') name via SiddhiManager.set_extension("store:<type>",
    cls) — the built-in 'inMemory'/'testStore' need no registration."""

    def init(self, table_id: str, schema: StreamSchema,
             properties: dict) -> None:
        self.table_id = table_id
        self.schema = schema
        self.properties = properties

    def connect(self) -> None:
        pass

    def disconnect(self) -> None:
        pass

    # -- record operations (each Object[] == one tuple) -------------------
    def add(self, records: list[tuple]) -> None:
        raise NotImplementedError

    def find(self, condition: CompiledStoreCondition,
             params: dict) -> Iterable[tuple]:
        raise NotImplementedError

    def contains(self, condition: CompiledStoreCondition,
                 params: dict) -> bool:
        for _ in self.find(condition, params):
            return True
        return False

    def delete(self, condition: CompiledStoreCondition,
               param_maps: list[dict]) -> int:
        raise NotImplementedError

    def update(self, condition: CompiledStoreCondition,
               param_maps: list[dict],
               set_values: list[dict]) -> int:
        """set_values[i]: {attr_index: value} applied where condition
        matches param_maps[i]."""
        raise NotImplementedError

    def update_or_add(self, condition: CompiledStoreCondition,
                      param_maps: list[dict], set_values: list[dict],
                      add_rows: list[tuple]) -> None:
        raise NotImplementedError


class InMemoryStore(RecordTable):
    """In-tree record-table double (TestStore.java + its condition
    visitor): a plain Python list of tuples evaluated with the default
    condition interpreter. Also the reference's 'inMemory' store type."""

    def init(self, table_id, schema, properties):
        super().init(table_id, schema, properties)
        self.records: list[tuple] = []
        self.lock = threading.Lock()
        self.calls: list[str] = []  # test observability

    def add(self, records):
        with self.lock:
            self.calls.append("add")
            self.records.extend(tuple(r) for r in records)

    def find(self, condition, params):
        with self.lock:
            self.calls.append("find")
            return [r for r in self.records
                    if condition.matches(r, params)]

    def delete(self, condition, param_maps):
        with self.lock:
            self.calls.append("delete")
            n0 = len(self.records)
            for params in param_maps:
                self.records = [r for r in self.records
                                if not condition.matches(r, params)]
            return n0 - len(self.records)

    def update(self, condition, param_maps, set_values):
        with self.lock:
            self.calls.append("update")
            n = 0
            for params, sets in zip(param_maps, set_values):
                for i, r in enumerate(self.records):
                    if condition.matches(r, params):
                        row = list(r)
                        for ai, v in sets.items():
                            row[ai] = v
                        self.records[i] = tuple(row)
                        n += 1
            return n

    def update_or_add(self, condition, param_maps, set_values, add_rows):
        with self.lock:
            self.calls.append("update_or_add")
            for params, sets, row in zip(param_maps, set_values, add_rows):
                hit = False
                for i, r in enumerate(self.records):
                    if condition.matches(r, params):
                        nr = list(r)
                        for ai, v in sets.items():
                            nr[ai] = v
                        self.records[i] = tuple(nr)
                        hit = True
                if not hit:
                    self.records.append(tuple(row))


STORE_TYPES: dict = {
    "inmemory": InMemoryStore,
    "teststore": InMemoryStore,
}


# ---------------------------------------------------------------------------
# runtimes
# ---------------------------------------------------------------------------


class RecordTableRuntime:
    """Host-side runtime for one @Store table: compiles conditions once,
    evaluates stream-side parameters per row, serializes store access."""

    is_record_table = True

    def __init__(self, table_id: str, schema: StreamSchema,
                 store: RecordTable):
        self.table_id = table_id
        self.schema = schema
        self.store = store
        self.lock = threading.Lock()

    def compile_condition(self, on: Optional[A.Expression],
                          stream_eval,
                          stream_has=lambda n: False,
                          alias=None) -> CompiledStoreCondition:
        return compile_store_condition(on, self.table_id, self.schema,
                                       stream_eval, stream_has, alias)

    # row-level ops used by output handlers / on-demand ------------------
    def insert_rows(self, rows: list[tuple]) -> None:
        with self.lock:
            self.store.add(rows)

    def find_rows(self, cond: CompiledStoreCondition,
                  event_rows: list) -> list[tuple]:
        with self.lock:
            out = []
            for ev in event_rows:
                out.extend(self.store.find(cond, cond.bind(ev)))
            return out

    def delete_rows(self, cond, event_rows) -> int:
        with self.lock:
            return self.store.delete(
                cond, [cond.bind(ev) for ev in event_rows])

    def update_rows(self, cond, event_rows, set_values) -> int:
        with self.lock:
            return self.store.update(
                cond, [cond.bind(ev) for ev in event_rows], set_values)

    def update_or_add_rows(self, cond, event_rows, set_values,
                           add_rows) -> None:
        with self.lock:
            self.store.update_or_add(
                cond, [cond.bind(ev) for ev in event_rows], set_values,
                add_rows)


class CacheTableRuntime(RecordTableRuntime):
    """@Cache(size, cache.policy, retention.period, purge.interval)
    fronting a record table (CacheTable.java:62 + FIFO/LRU/LFU variants
    + CacheExpirer). The cache itself is a bounded DEVICE TableRuntime,
    so cached store tables participate in device joins/filters exactly
    like in-memory tables; the host owns policy metadata (recency,
    frequency, insert time) and evicts via masked device deletes."""

    def __init__(self, table_id, schema, store, max_size: int,
                 policy: str = "FIFO",
                 retention_ms: Optional[int] = None):
        from ..ops.table import TableRuntime
        super().__init__(table_id, schema, store)
        if policy.upper() not in ("FIFO", "LRU", "LFU"):
            raise CompileError(
                f"@Cache policy '{policy}' unknown (FIFO|LRU|LFU)")
        self.policy = policy.upper()
        self.max_size = int(max_size)
        self.retention_ms = retention_ms
        # the cache registers under the TABLE's id in app.tables so join/
        # filter table_deps resolve to it transparently
        self.cache = TableRuntime(table_id, schema,
                                  capacity=self.max_size)
        # host-side policy metadata keyed by record tuple
        self._meta_lock = threading.Lock()
        self._added_at: dict = {}
        self._used_at: dict = {}
        self._uses: dict = {}
        # True while the cache provably holds EVERY store row (preloaded
        # fully, no eviction/expiry since): only then may reads be served
        # from the cache alone — a partially-matching cache would return
        # incomplete results (CacheTable serves reads from cache only
        # when the table fits; otherwise queries go to the store)
        self.cache_complete = False
        # queries whose jitted joins/filters read the device cache table
        # directly (registered by the planner). The host find_rows path
        # falls back to the store when incomplete; the device path CANNOT
        # — so losing completeness with compiled readers is surfaced
        # loudly (once per loss) and counted for statistics()
        self.compiled_readers: set = set()
        self.completeness_losses = 0
        # clock for retention/recency: wired to the app's current_time by
        # the planner so playback apps expire on event time
        self.now_fn = lambda: int(time.time() * 1000)

    def _lose_completeness(self, reason: str) -> None:
        if self.cache_complete:
            self.completeness_losses += 1
            if self.compiled_readers:
                import logging
                logging.getLogger("siddhi_tpu.store").warning(
                    "store table '%s': cache lost completeness (%s); "
                    "device-compiled reads in %s now see a PARTIAL "
                    "snapshot until the cache is reloaded",
                    self.table_id, reason,
                    sorted(self.compiled_readers))
        self.cache_complete = False

    # -- policy bookkeeping ----------------------------------------------
    def _touch(self, rows: Iterable[tuple], now_ms: int) -> None:
        with self._meta_lock:
            for r in rows:
                self._used_at[r] = now_ms
                self._uses[r] = self._uses.get(r, 0) + 1

    def _note_add(self, rows: Iterable[tuple], now_ms: int) -> None:
        with self._meta_lock:
            for r in rows:
                self._added_at[r] = now_ms
                self._used_at[r] = now_ms
                self._uses[r] = 0

    def _now(self) -> int:
        return int(self.now_fn())

    def _evict_candidates(self, n: int) -> list[tuple]:
        with self._meta_lock:
            if self.policy == "FIFO":
                key = lambda r: self._added_at.get(r, 0)  # noqa: E731
            elif self.policy == "LRU":
                key = lambda r: self._used_at.get(r, 0)  # noqa: E731
            else:  # LFU
                key = lambda r: self._uses.get(r, 0)  # noqa: E731
            return sorted(self._added_at, key=key)[:n]

    # -- cache maintenance (host boundary) -------------------------------
    def cache_rows(self) -> list[tuple]:
        from .ondemand import rows_of_table
        return rows_of_table(self.cache)

    def _cache_delete(self, rows: list[tuple]) -> None:
        from .ondemand import delete_rows_of_table
        delete_rows_of_table(self.cache, rows)
        with self._meta_lock:
            for r in rows:
                self._added_at.pop(r, None)
                self._used_at.pop(r, None)
                self._uses.pop(r, None)

    def _cache_add(self, rows: list[tuple], now_ms: int) -> None:
        if not rows:
            return
        current = {tuple(r) for r in self.cache_rows()}
        fresh = [tuple(r) for r in rows if tuple(r) not in current]
        # never admit more than the device table can hold: metadata for
        # silently-dropped rows would accumulate as phantom entries —
        # and a truncated admission means the cache no longer mirrors
        # the store, so completeness is void
        if len(fresh) > self.max_size:
            fresh = fresh[: self.max_size]
            self._lose_completeness("admission truncated at cache size")
        if not fresh:
            return
        overflow = len(current) + len(fresh) - self.max_size
        if overflow > 0:
            self._cache_delete(self._evict_candidates(overflow))
            self._lose_completeness("eviction (cache over max_size)")
        from .ondemand import insert_rows_of_table
        insert_rows_of_table(self.cache, fresh, now_ms)
        self._note_add(fresh, now_ms)

    def preload(self, now_ms: int) -> None:
        """Load up to max_size rows from the store on start
        (CacheTable preload); completeness recorded for the read path."""
        all_rows = list(self.store.find(
            CompiledStoreCondition(None, {}), {}))
        self._cache_add(all_rows[: self.max_size], now_ms)
        self.cache_complete = len(all_rows) <= self.max_size

    def purge_expired(self, now_ms: int) -> None:
        """Drop cache rows older than retention.period
        (util/cache/CacheExpirer.java)."""
        if self.retention_ms is None:
            return
        with self._meta_lock:
            stale = [r for r, t in self._added_at.items()
                     if now_ms - t > self.retention_ms]
        if stale:
            self._cache_delete(stale)
            self._lose_completeness("retention purge")

    # -- reads: cache only when provably complete ------------------------
    def find_rows(self, cond, event_rows):
        now_ms = self._now()
        if self.cache_complete:
            cached = self.cache_rows()
            out = []
            for ev in event_rows:
                params = cond.bind(ev)
                hits = [r for r in cached
                        if cond.matches(tuple(r), params)]
                self._touch([tuple(h) for h in hits], now_ms)
                out.extend(hits)
            return out
        # incomplete cache: the store answers (a cache holding SOME
        # matching rows must not short-circuit); results warm the cache
        fetched = super().find_rows(cond, event_rows)
        self._cache_add(fetched, now_ms)
        self._touch([tuple(r) for r in fetched], now_ms)
        return fetched

    # -- writes go through to the store AND keep the cache coherent ------
    def insert_rows(self, rows):
        super().insert_rows(rows)
        self._cache_add([tuple(r) for r in rows], self._now())

    def delete_rows(self, cond, event_rows):
        n = super().delete_rows(cond, event_rows)
        cached = [tuple(r) for r in self.cache_rows()]  # decode ONCE
        stale = []
        for ev in event_rows:
            params = cond.bind(ev)
            stale.extend(r for r in cached if cond.matches(r, params))
        self._cache_delete(stale)
        return n

    def update_rows(self, cond, event_rows, set_values):
        n = super().update_rows(cond, event_rows, set_values)
        self._refresh_after_write(cond, event_rows)
        return n

    def update_or_add_rows(self, cond, event_rows, set_values, add_rows):
        super().update_or_add_rows(cond, event_rows, set_values, add_rows)
        self._refresh_after_write(cond, event_rows)

    def _refresh_after_write(self, cond, event_rows):
        # updated records change content: drop matching cache rows; the
        # next read re-fetches the fresh values
        cached = [tuple(r) for r in self.cache_rows()]  # decode ONCE
        stale = []
        for ev in event_rows:
            params = cond.bind(ev)
            stale.extend(r for r in cached if cond.matches(r, params))
        self._cache_delete(stale)
        self._lose_completeness("write invalidation")


# ---------------------------------------------------------------------------
# host-side expression evaluation (stream-side params, SET values)
# ---------------------------------------------------------------------------


def host_eval(expr: A.Expression, schema: StreamSchema) -> Callable:
    """Compile a stream-side expression to fn(row_values) -> python value
    (the host boundary mirror of the device expression compiler; store
    writes happen at on-demand / query-output rates, not per-event)."""
    if isinstance(expr, A.Constant):
        v = expr.value
        return lambda row: v
    if isinstance(expr, A.Variable):
        try:
            idx = schema.index_of(expr.attribute)
        except KeyError:
            raise CompileError(
                f"'{expr.attribute}' is not resolvable in this store "
                "expression context")
        return lambda row: row[idx]
    if isinstance(expr, A.MathOp):
        lf = host_eval(expr.left, schema)
        rf = host_eval(expr.right, schema)
        op = expr.op

        def fn(row):
            a, b = lf(row), rf(row)
            if a is None or b is None:
                return None
            if op == "+":
                return a + b
            if op == "-":
                return a - b
            if op == "*":
                return a * b
            if op == "/":
                return a / b if b else None
            if op == "%":
                return a % b if b else None
            raise CompileError(f"host eval: unknown op {op}")
        return fn
    raise CompileError(
        f"store parameter expressions support constants, attributes and "
        f"arithmetic; got {type(expr).__name__}")


def parse_duration_ms(text: str) -> int:
    """'10 sec' / '1 min' / '500 millisec' -> ms (annotation values)."""
    parts = str(text).strip().split()
    if len(parts) == 1 and parts[0].isdigit():
        return int(parts[0])
    if len(parts) != 2:
        raise CompileError(f"cannot parse duration {text!r}")
    n = int(parts[0])
    unit = parts[1].lower().rstrip("s")
    factor = {"millisecond": 1, "millisec": 1, "ms": 1, "second": 1000,
              "sec": 1000, "minute": 60_000, "min": 60_000,
              "hour": 3_600_000}.get(unit)
    if factor is None:
        raise CompileError(f"cannot parse duration unit {unit!r}")
    return n * factor


class StoreOutputHandler:
    """Query output -> record table (the host edge of
    InsertIntoTableCallback / DeleteTableCallback / UpdateTableCallback /
    UpdateOrInsertTableCallback for @Store tables): decoded CURRENT rows
    drive store calls with the pre-compiled condition."""

    def __init__(self, rt: RecordTableRuntime, kind: str,
                 on: Optional[A.Expression], set_clause,
                 out_schema: StreamSchema):
        self.rt = rt
        self.kind = kind
        self.out_schema = out_schema
        self.cond = rt.compile_condition(
            on, lambda e: host_eval(e, out_schema),
            stream_has=lambda n: n in out_schema.names)
        self.set_fns = []
        for var, expr in (set_clause or []):
            self.set_fns.append((rt.schema.index_of(var.attribute),
                                 host_eval(expr, out_schema)))

    def handle_device_batch(self, out, timestamp, current=None) -> bool:
        return False  # store IO needs decoded rows

    def handle(self, timestamp, rows) -> None:
        from .event import CURRENT as _CUR  # row kinds: 0 == CURRENT
        acting = [vals for ts, kind, vals in rows if kind == 0]
        if not acting:
            return
        if self.kind == "insert":
            self.rt.insert_rows([tuple(v) for v in acting])
        elif self.kind == "delete":
            self.rt.delete_rows(self.cond, acting)
        elif self.kind == "update":
            sets = [{i: f(row) for i, f in self.set_fns}
                    for row in acting]
            self.rt.update_rows(self.cond, acting, sets)
        elif self.kind == "update_or_insert":
            sets = [{i: f(row) for i, f in self.set_fns}
                    for row in acting]
            adds = []
            for row, s in zip(acting, sets):
                add = [None] * len(self.rt.schema.attributes)
                for i, v in s.items():
                    add[i] = v
                # unset attributes fall back to same-named output values
                for a, att in enumerate(self.rt.schema.attributes):
                    if add[a] is None and att.name in self.out_schema.names:
                        add[a] = row[self.out_schema.index_of(att.name)]
                adds.append(tuple(add))
            self.rt.update_or_add_rows(self.cond, acting, sets, adds)


def build_record_table(tid: str, schema: StreamSchema,
                       store_annotation, extensions: dict):
    """@Store(type='x', key=val..., @Cache(...)) -> runtime
    (DefinitionParserHelper's store branch)."""
    stype = store_annotation.element("type")
    if not stype:
        raise CompileError(f"table '{tid}': @Store needs type='...'")
    cls = extensions.get(f"store:{stype.lower()}") or \
        STORE_TYPES.get(stype.lower())
    if cls is None:
        raise CompileError(
            f"table '{tid}': unknown store type '{stype}' (register it "
            f"via manager.set_extension('store:{stype}', cls))")
    store = cls()
    store.init(tid, schema, dict(store_annotation.elements))
    cache_a = None
    for n in store_annotation.nested:
        if n.name.lower() == "cache":
            cache_a = n
    if cache_a is None:
        return RecordTableRuntime(tid, schema, store)
    size = int(cache_a.element("size") or 128)
    policy = cache_a.element("cache.policy") or "FIFO"
    retention = cache_a.element("retention.period")
    retention_ms = parse_duration_ms(retention) if retention else None
    rt = CacheTableRuntime(tid, schema, store, size, policy, retention_ms)
    purge = cache_a.element("purge.interval")
    rt.purge_interval_ms = parse_duration_ms(purge) if purge else (
        30_000 if retention_ms else None)
    return rt
