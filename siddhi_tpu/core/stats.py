"""Statistics: throughput / latency / memory trackers with OFF / BASIC /
DETAIL levels.

Reference mapping:
- util/statistics/* (ThroughputTracker, LatencyTracker,
  MemoryUsageTracker, BufferedEventsTracker; Dropwizard impls in
  util/statistics/metrics/)
- levels OFF/BASIC/DETAIL (util/statistics/metrics/Level.java)
- @app:statistics parsing (SiddhiAppParser.java:116-141) and runtime
  switching (SiddhiAppRuntimeImpl.setStatisticsLevel:859)

Measurement model for an async device pipeline: BASIC counts events and
wall time at the host boundary (no device syncs — the numbers are free);
DETAIL additionally blocks until the device step completes to measure
true per-step latency (accurate, but serializes the pipeline — exactly
the reference's caveat that DETAIL metrics cost throughput)."""
from __future__ import annotations

import time
from typing import Optional

OFF, BASIC, DETAIL = 0, 1, 2
_LEVELS = {"OFF": OFF, "BASIC": BASIC, "DETAIL": DETAIL}


def parse_level(text: Optional[str]) -> int:
    if text is None:
        return OFF
    return _LEVELS.get(str(text).upper(), BASIC)


class ThroughputTracker:
    def __init__(self):
        self.count = 0
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None

    def mark(self, n: int) -> None:
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
        self._t_last = now
        self.count += n

    def events_per_sec(self) -> Optional[float]:
        if self._t0 is None or self._t_last is None or \
                self._t_last <= self._t0:
            return None
        return self.count / (self._t_last - self._t0)


class LatencyTracker:
    """Windowed latency stats in ms (markIn/markOut around a step).
    mark_in/mark_out pair up per thread so concurrent steps (ingest vs
    scheduler timers) don't cross-contaminate samples."""

    CAP = 4096

    def __init__(self):
        import threading
        self.samples: list[float] = []
        self._tls = threading.local()
        self._lock = threading.Lock()

    def mark_in(self) -> None:
        self._tls.t0 = time.perf_counter()

    def mark_out(self) -> None:
        t0 = getattr(self._tls, "t0", None)
        if t0 is None:
            return
        self._tls.t0 = None
        dt = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            if len(self.samples) >= self.CAP:
                del self.samples[: self.CAP // 2]
            self.samples.append(dt)

    def summary(self) -> Optional[dict]:
        # snapshot under the lock: mark_out mutates samples (del + append)
        # concurrently, and sorting a list mid-mutation drops/duplicates
        # entries (or raises on the resize)
        with self._lock:
            if not self.samples:
                return None
            s = sorted(self.samples)
        n = len(s)
        return {"avg_ms": round(sum(s) / n, 3),
                "p50_ms": round(s[n // 2], 3),
                "p95_ms": round(s[min(n - 1, (n * 95) // 100)], 3),
                "p99_ms": round(s[min(n - 1, (n * 99) // 100)], 3),
                "samples": n}


def pytree_nbytes(tree) -> int:
    import numpy as np
    total = 0
    if isinstance(tree, dict):
        vals = tree.values()
    elif isinstance(tree, (tuple, list)):
        vals = tree
    else:
        vals = [tree]
        if hasattr(tree, "nbytes"):
            return int(tree.nbytes)
        if isinstance(tree, (int, float, bool)):
            return 8
        return 0
    for v in vals:
        if hasattr(v, "nbytes"):
            total += int(v.nbytes)
        elif isinstance(v, (dict, tuple, list)):
            total += pytree_nbytes(v)
        elif isinstance(v, np.generic):
            total += int(v.nbytes)
    return total


class StreamErrorStats:
    """Per-stream error counters, app-scoped: every junction on-error
    handling pass and every terminal sink publish failure increments the
    origin stream's counter (always on — errors are rare enough that the
    count is free, and silent drops are the one thing stats must never
    hide)."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}

    def increment(self, stream_id: str, n: int = 1) -> None:
        with self._lock:
            self._counts[stream_id] = self._counts.get(stream_id, 0) + n

    def count(self, stream_id: str) -> int:
        with self._lock:
            return self._counts.get(stream_id, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._counts)


class QueryStats:
    """Per-query tracker bundle (created when statistics are enabled)."""

    def __init__(self):
        self.throughput = ThroughputTracker()
        self.latency = LatencyTracker()
