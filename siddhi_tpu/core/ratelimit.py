"""Output rate limiters: host-side gatekeepers between a query's device
output and its callbacks / insert-into handlers.

Reference mapping (query/output/ratelimit/):
- OutputRateLimiter.java:43 (base, sendToCallBacks :64-108)
- event/{All,First,Last,FirstGroupBy,LastGroupBy}PerEventOutputRateLimiter
- time/{All,First,Last,FirstGroupBy,LastGroupBy}PerTimeOutputRateLimiter
- snapshot/* -> SnapshotRateLimiter (simplified: emits the latest row —
  per group key when the query groups — every interval; the reference's
  windowed/aggregation re-emission variants collapse to this because the
  device selector already materializes per-group current values)

Rate limiting is intentionally HOST-side: its entire purpose is to shrink
the event rate crossing the host boundary, and its state (counters, small
buffers) is tiny. Rows are (ts, kind, values) tuples as produced by
rows_from_batch; only CURRENT/EXPIRED rows count
(AllPerEventOutputRateLimiter.java:57).

Time-based limiters schedule flushes on the app Scheduler, so playback
replay drives them deterministically.
"""
from __future__ import annotations

from typing import Callable, Optional

from .event import CURRENT, EXPIRED

Row = tuple  # (ts, kind, values)


class OutputRateLimiter:
    """Base: process(ts, rows) gates rows; emit() forwards downstream."""

    needs_timers = False

    def __init__(self):
        self.emit: Callable = lambda ts, rows: None

    def process(self, timestamp: int, rows: list[Row]) -> None:
        raise NotImplementedError

    def start(self, app) -> None:
        """Attach to the app (scheduler access for time-based flushes)."""
        self.app = app

    # -- persistence ------------------------------------------------------
    def snapshot_state(self) -> dict:
        return {}

    def restore_state(self, snap: dict) -> None:
        pass


def _countable(rows):
    return [r for r in rows if r[1] in (CURRENT, EXPIRED)]


class PassThroughRateLimiter(OutputRateLimiter):
    def process(self, timestamp, rows):
        self.emit(timestamp, rows)


class AllPerEventRateLimiter(OutputRateLimiter):
    """Buffer every event; flush the batch when N have accumulated
    (event/AllPerEventOutputRateLimiter.java:55-66)."""

    def __init__(self, n: int):
        super().__init__()
        self.n = n
        self.counter = 0
        self.buffer: list[Row] = []

    def process(self, timestamp, rows):
        out = []
        for r in _countable(rows):
            self.buffer.append(r)
            self.counter += 1
            if self.counter == self.n:
                out.extend(self.buffer)
                self.buffer.clear()
                self.counter = 0
        if out:
            self.emit(timestamp, out)

    def snapshot_state(self):
        return {"counter": self.counter, "buffer": list(self.buffer)}

    def restore_state(self, snap):
        self.counter = snap["counter"]
        self.buffer = list(snap["buffer"])


class FirstPerEventRateLimiter(OutputRateLimiter):
    """Emit the 1st of every N events
    (event/FirstPerEventOutputRateLimiter.java:54-63)."""

    def __init__(self, n: int, key_fn: Optional[Callable] = None):
        super().__init__()
        self.n = n
        self.key_fn = key_fn
        self.counters: dict = {None: 0}

    def process(self, timestamp, rows):
        out = []
        for r in _countable(rows):
            k = self.key_fn(r) if self.key_fn else None
            c = self.counters.get(k, 0) + 1
            if c == 1:
                out.append(r)
            if c == self.n:
                c = 0
            self.counters[k] = c
        if out:
            self.emit(timestamp, out)

    def snapshot_state(self):
        return {"counters": dict(self.counters)}

    def restore_state(self, snap):
        self.counters = dict(snap["counters"])


class LastPerEventRateLimiter(OutputRateLimiter):
    """Emit the Nth (last) of every N events
    (event/LastPerEventOutputRateLimiter.java)."""

    def __init__(self, n: int, key_fn: Optional[Callable] = None):
        super().__init__()
        self.n = n
        self.key_fn = key_fn
        self.counters: dict = {}
        self.last: dict = {}

    def process(self, timestamp, rows):
        out = []
        for r in _countable(rows):
            k = self.key_fn(r) if self.key_fn else None
            self.last[k] = r
            c = self.counters.get(k, 0) + 1
            if c == self.n:
                out.append(self.last.pop(k))
                c = 0
            self.counters[k] = c
        if out:
            self.emit(timestamp, out)

    def snapshot_state(self):
        return {"counters": dict(self.counters), "last": dict(self.last)}

    def restore_state(self, snap):
        self.counters = dict(snap["counters"])
        self.last = dict(snap["last"])


class FirstPerTimeRateLimiter(OutputRateLimiter):
    """Emit the first event to arrive in each T window; event-driven, no
    timers (time/FirstPerTimeOutputRateLimiter.java:61-66)."""

    def __init__(self, ms: int, key_fn: Optional[Callable] = None):
        super().__init__()
        self.ms = ms
        self.key_fn = key_fn
        self.output_time: dict = {}

    def process(self, timestamp, rows):
        now = self.app.current_time()
        out = []
        for r in _countable(rows):
            k = self.key_fn(r) if self.key_fn else None
            ot = self.output_time.get(k)
            if ot is None or ot + self.ms <= now:
                self.output_time[k] = now
                out.append(r)
        if out:
            self.emit(timestamp, out)

    def snapshot_state(self):
        return {"output_time": dict(self.output_time)}

    def restore_state(self, snap):
        self.output_time = dict(snap["output_time"])


class _ScheduledRateLimiter(OutputRateLimiter):
    """Shared machinery for limiters that flush on a T-interval timer."""

    needs_timers = True

    def __init__(self, ms: int):
        super().__init__()
        self.ms = ms
        self._due: Optional[int] = None

    def _arm(self) -> None:
        if self._due is not None:
            return
        due = self.app.current_time() + self.ms
        self._due = due
        self.app.scheduler.notify_at(due, self._on_timer)

    def _on_timer(self, due: int) -> None:
        self._due = None
        if not self.app.running:
            return
        self.flush(due)

    def flush(self, due: int) -> None:
        raise NotImplementedError


class AllPerTimeRateLimiter(_ScheduledRateLimiter):
    """Buffer everything; flush every T
    (time/AllPerTimeOutputRateLimiter.java)."""

    def __init__(self, ms: int):
        super().__init__(ms)
        self.buffer: list[Row] = []

    def process(self, timestamp, rows):
        got = _countable(rows)
        if got:
            self.buffer.extend(got)
            self._arm()

    def flush(self, due):
        if self.buffer:
            out, self.buffer = self.buffer, []
            self.emit(due, out)

    def snapshot_state(self):
        return {"buffer": list(self.buffer)}

    def restore_state(self, snap):
        self.buffer = list(snap["buffer"])


class LastPerTimeRateLimiter(_ScheduledRateLimiter):
    """Keep the last event (per group key when grouped); emit at each
    interval end (time/LastPerTimeOutputRateLimiter.java)."""

    def __init__(self, ms: int, key_fn: Optional[Callable] = None):
        super().__init__(ms)
        self.key_fn = key_fn
        self.last: dict = {}

    def process(self, timestamp, rows):
        got = _countable(rows)
        if got:
            for r in got:
                self.last[self.key_fn(r) if self.key_fn else None] = r
            self._arm()

    def flush(self, due):
        if self.last:
            out = list(self.last.values())
            self.last.clear()
            self.emit(due, out)

    def snapshot_state(self):
        return {"last": dict(self.last)}

    def restore_state(self, snap):
        self.last = dict(snap["last"])


class SnapshotRateLimiter(_ScheduledRateLimiter):
    """`output snapshot every T`: re-emit the latest value (per group when
    grouped) as CURRENT at each interval (snapshot/*; simplified — see
    module docstring). Unlike last-per-time the snapshot is retained
    across intervals."""

    def __init__(self, ms: int, key_fn: Optional[Callable] = None):
        super().__init__(ms)
        self.key_fn = key_fn
        self.snap: dict = {}

    def process(self, timestamp, rows):
        got = [r for r in rows if r[1] == CURRENT]
        if got:
            for r in got:
                self.snap[self.key_fn(r) if self.key_fn else None] = r
            self._arm()

    def flush(self, due):
        if self.snap:
            out = [(due, CURRENT, r[2]) for r in self.snap.values()]
            self.emit(due, out)
            self._arm()

    def snapshot_state(self):
        return {"snap": dict(self.snap)}

    def restore_state(self, snap):
        self.snap = dict(snap["snap"])


def build_rate_limiter(rate, group_key_fn: Optional[Callable]):
    """AST OutputRate -> limiter (reference: OutputParser rate selection).
    group_key_fn extracts the query's group-by key from an output row (for
    the GroupBy limiter variants); None when the query has no group-by."""
    from ..lang import ast as A
    if rate is None:
        return None
    if isinstance(rate, A.EventOutputRate):
        if rate.type == "all":
            return AllPerEventRateLimiter(rate.events)
        if rate.type == "first":
            return FirstPerEventRateLimiter(rate.events, group_key_fn)
        if rate.type == "last":
            return LastPerEventRateLimiter(rate.events, group_key_fn)
    if isinstance(rate, A.TimeOutputRate):
        if rate.type == "all":
            return AllPerTimeRateLimiter(rate.ms)
        if rate.type == "first":
            return FirstPerTimeRateLimiter(rate.ms, group_key_fn)
        if rate.type == "last":
            return LastPerTimeRateLimiter(rate.ms, group_key_fn)
    if isinstance(rate, A.SnapshotOutputRate):
        return SnapshotRateLimiter(rate.ms, group_key_fn)
    raise ValueError(f"unknown output rate {rate!r}")
