"""Ahead-of-time compile service: parallel step lowering + persistent
cache telemetry (docs/compile_cache.md).

Every query in an app compiles to one (or a few) jitted step programs.
Left to the default lazy path, those programs compile serially, one at a
time, on the first chunk that reaches each query — for a realistic app
that is minutes of wall clock before the first result, paid AFTER
traffic has started arriving (the r01..r05 bench rounds all died inside
this phase). Siddhi deploys in milliseconds because its executor tree
is interpreted; the TPU build gets the same deploy-time behavior by
compiling everything up front, in parallel, and persisting the results:

1. `CompileService.specs(buckets)` enumerates every jitted step the app
   can dispatch for the configured ingest buckets — per-query row +
   packed steps, fused-chain steps, per-stream pattern steps, join side
   steps, partition trigger steps, and the cap-16 timer-batch shapes —
   together with zero-filled arguments of the exact shapes/dtypes the
   runtime will pass.
2. `warmup()` executes each spec once on a thread pool. XLA compilation
   releases the GIL, so N steps compile concurrently and wall time is
   max(compile) instead of sum(compile). Warming by *calling the
   runtime's own cached jit object* (not a parallel AOT handle)
   guarantees the dispatch-path caches are the ones that get hot: the
   first real chunk performs zero traces and zero compiles.
3. Compiles are persisted via JAX's compilation cache
   (`SIDDHI_TPU_CACHE_DIR`, wired in `siddhi_tpu/__init__.py` with
   min-compile-time/min-entry-size 0 so every program is written).
   A warm process start loads executables from disk instead of
   recompiling; the hit/miss counters below make that observable.

Packed-ingest steps are keyed by the sticky per-stream encoding tuple
(core/ingest.py). Traffic has not arrived at warmup time, so the service
compiles the encoder's INITIAL encoding by default (affine timestamps +
constant columns — what the first chunk of zeros-and-ramps traffic
produces) and accepts per-stream `samples` to derive the encoding real
traffic will settle on.

Telemetry (per app, cumulative over warmups) surfaces through
`SiddhiAppRuntime.statistics()["compile"]` and the warmup() return
value: program count, compile wall ms, persistent-cache hits/misses,
and at DETAIL stats level the per-step timing list.
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from .event import EventBatch, StreamSchema
from .ingest import (initial_encoding, encoding_for_sample, layout,
                     pipeline_enabled, pipeline_split_cap,
                     zero_packed_buffer)

# -- persistent-cache hit/miss counters --------------------------------------
# jax.monitoring events are process-global; one listener feeds every
# CompileService (snapshots delta around each warmup).

_CACHE_COUNTS = {"hits": 0, "misses": 0}
_CACHE_LOCK = threading.Lock()


def _cache_event(event: str, **kwargs) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        with _CACHE_LOCK:
            _CACHE_COUNTS["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        with _CACHE_LOCK:
            _CACHE_COUNTS["misses"] += 1


try:  # monitoring is a stable public module, but stay import-safe
    jax.monitoring.register_event_listener(_cache_event)
except Exception:  # noqa: BLE001 — telemetry is best-effort
    pass


def cache_counts() -> dict:
    with _CACHE_LOCK:
        return dict(_CACHE_COUNTS)


def warm_buckets_from_env() -> tuple:
    """`SIDDHI_TPU_WARM_BUCKETS='1024,65536'` -> (1024, 65536). Unset or
    empty/'0' means no automatic warmup at start()."""
    raw = os.environ.get("SIDDHI_TPU_WARM_BUCKETS", "")
    if not raw or raw.strip() in ("0", "off"):
        return ()
    from .runtime import bucket_capacity
    out = []
    for part in raw.split(","):
        part = part.strip()
        if part:
            out.append(bucket_capacity(int(part)))
    return tuple(sorted(set(out)))


def _workers_from_env() -> int:
    raw = os.environ.get("SIDDHI_TPU_COMPILE_WORKERS", "")
    if raw:
        return max(1, int(raw))
    return max(1, min(8, os.cpu_count() or 1))


# -- zero-argument builders ---------------------------------------------------
#
# Two modes, selected by `abstract_spec_args()`:
#
# - concrete (default): real zero device buffers — what warmup() calls
#   the jitted steps with (the call donates its arguments, so the
#   builders allocate fresh buffers, never the runtime's own state).
# - abstract: `jax.ShapeDtypeStruct` leaves — what the compiled-program
#   auditor (analysis/programs.py) traces/lowers the same specs with.
#   Even a trivial `jnp.zeros` dispatches a fill program through the
#   persistent compile cache, so the audit's zero-device-work /
#   zero-new-compiles contract requires that NO concrete array is ever
#   built on the audit path.

_ABSTRACT_SPECS = threading.local()


def _abstract() -> bool:
    return getattr(_ABSTRACT_SPECS, "on", False)


@contextlib.contextmanager
def abstract_spec_args():
    """Within this context every spec builder emits
    `jax.ShapeDtypeStruct` argument leaves instead of zero device
    buffers. Thread-local: a concurrent warmup on another thread still
    materializes real buffers."""
    _ABSTRACT_SPECS.on = True
    try:
        yield
    finally:
        _ABSTRACT_SPECS.on = False


def spec_args_abstract() -> bool:
    """True inside `abstract_spec_args()` — spec builders that cannot
    route every allocation through the helpers below (mesh placement in
    serving/pool.py needs concrete buffers) branch on this."""
    return _abstract()


def _sds(x) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(jnp.shape(x)), jnp.result_type(x))


def zeros_array(shape, dtype):
    """`jnp.zeros` twin that respects abstract-spec mode (the serving
    pool's vmapped spec builders construct their stacked-slot arguments
    through this so pool programs audit without device work)."""
    if _abstract():
        return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))
    return jnp.zeros(shape, dtype)


def _zeros_like_tree(tree):
    if _abstract():
        return jax.tree_util.tree_map(_sds, tree)
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.result_type(x)), tree)


def _zero_batch(schema: StreamSchema, capacity: int) -> EventBatch:
    if _abstract():
        from .types import SET_LANES, AttrType, np_dtype

        def col(t):
            if t is AttrType.OBJECT:
                return jax.ShapeDtypeStruct((capacity, 1 + SET_LANES),
                                            jnp.int64)
            return jax.ShapeDtypeStruct((capacity,),
                                        jnp.dtype(np_dtype(t)))
        return EventBatch(
            ts=jax.ShapeDtypeStruct((capacity,), jnp.int64),
            cols=tuple(col(t) for t in schema.types),
            nulls=tuple(jax.ShapeDtypeStruct((capacity,), jnp.bool_)
                        for _ in schema.types),
            kind=jax.ShapeDtypeStruct((capacity,), jnp.int32),
            valid=jax.ShapeDtypeStruct((capacity,), jnp.bool_))
    return EventBatch.empty(schema, capacity)


def _zero_packed(schema: StreamSchema, enc: tuple, capacity: int):
    if _abstract():
        _, _, total = layout(len(schema.types), enc, capacity)
        return jax.ShapeDtypeStruct((total,), jnp.uint8)
    return zero_packed_buffer(schema, enc, capacity)


def _zero_now():
    if _abstract():
        return jax.ShapeDtypeStruct((), jnp.int64)
    return jnp.asarray(0, dtype=jnp.int64)


class CompileSpec:
    """One warmable program: a display key + a builder that returns
    (jitted_fn, args). The builder runs on the main thread (it touches
    the runtime's jit caches); the call runs on the pool."""

    __slots__ = ("key", "build")

    def __init__(self, key: str, build: Callable):
        self.key = key
        self.build = build


class CompileService:
    """Per-app AOT compiler: enumerate + compile every step program."""

    def __init__(self, app):
        self.app = app
        self.records: list[dict] = []   # [{"step", "ms"}...]
        self.total_ms = 0.0
        self.programs = 0
        # programs whose example args carry a multi-device sharding
        # (mesh pools / sharded partitions warm through here — the
        # telemetry proves the AOT pass compiled the SHARDED program,
        # not a single-device twin that never dispatches)
        self.sharded_programs = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.warmups = 0
        # keys already compiled by THIS service: repeat warmups (pool
        # re-warm after restore, overlapping cap lists) skip them —
        # identical (step, shape-bucket) specs lower exactly once
        self._warmed_keys: set[str] = set()
        # last compiled-program audit summary (analysis/programs.py):
        # a live view — rides statistics()['compile'] and
        # ExplainReport.programs, never the plan hash
        self.audit: Optional[dict] = None
        self._lock = threading.Lock()
        # in-flight warmups: while > 0 the app is compiling and must not
        # be marked ready (service GET /ready load-balancer semantics)
        self._inflight = 0
        # cooperative cancellation: undeploy of a still-warming app sets
        # this so the background warmup bails between specs instead of
        # compiling for a dead app (core/service.py undeploy)
        self._cancel = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- readiness (service /ready) --------------------------------------
    @property
    def ready(self) -> bool:
        """True when no warmup is in flight. An app that never warms up
        (no buckets configured) is trivially ready."""
        with self._lock:
            return self._inflight == 0

    def _begin(self) -> None:
        with self._lock:
            self._inflight += 1

    def _end(self) -> None:
        with self._lock:
            self._inflight -= 1

    def cancel(self) -> None:
        """Ask in-flight warmups to stop compiling (checked between
        specs; the spec being compiled finishes — XLA compiles are not
        interruptible). Sticky until the next warmup begins."""
        self._cancel.set()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for background warmup threads (undeploy: cancel() then
        join() so the inflight count provably returns to zero instead of
        leaking behind a daemon thread)."""
        for t in list(self._threads):
            t.join(timeout=timeout)
        self._threads = [t for t in self._threads if t.is_alive()]

    def warmup_async(self, buckets=None, samples: Optional[dict] = None,
                     workers: Optional[int] = None) -> threading.Thread:
        """Run warmup() on a daemon thread. Readiness flips to False
        SYNCHRONOUSLY (before this returns), so a deploy that kicks off
        an async warm is observed not-ready until the compiles land."""
        self._begin()

        def run():
            try:
                self.warmup(buckets=buckets, samples=samples,
                            workers=workers)
            finally:
                self._end()

        t = threading.Thread(target=run, daemon=True,
                             name=f"siddhi-warmup-{self.app.name}")
        self._threads.append(t)
        t.start()
        return t

    # -- enumeration -----------------------------------------------------

    def _encodings(self, schema: StreamSchema, samples: Optional[dict]):
        """Packed encodings to warm for one stream: the encoder's initial
        (cold) encoding, plus the sticky encoding a traffic sample would
        settle on."""
        encs = [initial_encoding(schema)]
        if samples and schema.stream_id in samples:
            ts, cols = samples[schema.stream_id]
            enc = encoding_for_sample(schema, ts, cols)
            if enc not in encs:
                encs.append(enc)
        return encs

    def specs(self, buckets, samples: Optional[dict] = None) -> list:
        """Every step program the app can dispatch for the given ingest
        buckets, deduplicated by key. Mirrors the dispatch paths:
        send_arrays' per-junction capacity negotiation, process_batch's
        sort-heavy splitting, and the cap-16 timer-batch shapes."""
        from .runtime import (BATCH_BUCKETS, JoinStreamReceiver,
                              PatternStreamReceiver, QueryRuntime,
                              bucket_capacity)
        from ..parallel.partition import BlockStreamReceiver
        from ..resilience.ordering import ring_enabled
        app = self.app
        buckets = tuple(sorted({bucket_capacity(int(b)) for b in buckets}))
        timer_cap = BATCH_BUCKETS[0]
        specs: dict[str, CompileSpec] = {}

        def add(key: str, build: Callable) -> None:
            if key not in specs:
                specs[key] = CompileSpec(key, build)

        fused_members = set()
        for q in app.queries.values():
            ch = getattr(q, "_fused_chain", None)
            if ch is not None:
                for m in ch.queries[1:]:
                    fused_members.add(id(m))

        # -- ingest-path steps, per junction (send_arrays negotiation) ---
        for sid, j in app.junctions.items():
            receivers = list(j.receivers)
            if not receivers or not buckets:
                continue
            packed_ok = all(getattr(r, "supports_packed", False)
                            for r in receivers)
            jcap = BATCH_BUCKETS[-1]
            for r in receivers:
                if packed_ok:
                    rc = getattr(r, "max_packed_capacity",
                                 getattr(r, "max_step_capacity", None))
                else:
                    rc = getattr(r, "max_step_capacity", None)
                if rc is not None:
                    jcap = min(jcap, rc)
            if j.async_conf is not None:
                jcap = min(jcap, j.async_conf[1])
            # cost-evidence chunk caps (plan/optimizer.py) pin the
            # dispatch shape — mirror the send_arrays negotiation so
            # the warmed programs are the ones traffic will hit
            fanout = getattr(j, "fanout", None)
            if fanout is not None and fanout.preferred_cap:
                jcap = min(jcap, fanout.preferred_cap)
            for r in receivers:
                pc = getattr(r, "preferred_ingest_cap", None)
                if pc:
                    jcap = min(jcap, pc)
            caps = sorted({bucket_capacity(min(B, jcap)) for B in buckets})
            if packed_ok and pipeline_enabled():
                # pipelined dispatch splits oversized sends into
                # pipeline_split_cap()-row sub-chunks (core/ingest.py) —
                # warm those shapes so the overlap path hits no compiles
                sub = pipeline_split_cap()
                extra = {bucket_capacity(min(B, jcap, sub))
                         for B in buckets if B > sub}
                if extra - set(caps):
                    caps = sorted(set(caps) | extra)
            if fanout is not None:
                # ONE fused fan-out program covers every grouped
                # subscriber; members keep their timer-batch specs below
                fcaps = sorted({min(c, fanout.max_step_capacity or c)
                                for c in caps})
                self._fanout_specs(add, fanout, j.schema, fcaps,
                                   packed_ok, samples)
            for r in receivers:
                if fanout is not None and fanout.covers(r):
                    continue  # grouped — dispatches via the fanout step
                if isinstance(r, QueryRuntime):
                    if id(r) in fused_members:
                        continue  # fused segments dispatch via the head
                    target = r._fused_chain or r
                    self._query_specs(add, target, j.schema, caps,
                                      packed_ok, samples)
                elif isinstance(r, PatternStreamReceiver):
                    self._pattern_specs(add, r.runtime, r.stream_id,
                                        j.schema, caps, packed_ok, samples)
                elif isinstance(r, JoinStreamReceiver):
                    self._join_specs(add, r.runtime, r.side, j.schema,
                                     caps, packed_ok, samples)
                elif isinstance(r, BlockStreamReceiver):
                    self._partition_specs(add, r.block, sid, j.schema,
                                          caps)
            buf = getattr(app, "_reorder", {}).get(sid)
            if (buf is not None and ring_enabled()
                    and buf.ring_eligible()):
                self._ring_specs(add, sid, j, receivers, fanout,
                                 fused_members, samples)

        # -- named windows: fed by InsertIntoWindowHandler at the feeding
        # query's batch capacity (approximated by the ingest buckets)
        if buckets:
            for wq in app.named_windows.values():
                caps = sorted({bucket_capacity(
                    min(B, wq.max_step_capacity or B)) for B in buckets})
                self._query_specs(add, wq, wq.in_schema, caps,
                                  packed_ok=False, samples=samples)

        # -- timer-batch steps (cap-16 row shapes, scheduler-driven) ------
        for q in list(app.queries.values()) + list(
                app.named_windows.values()):
            self._timer_specs(add, q, timer_cap)
        for block in app.partitions.values():
            self._partition_timer_specs(add, block, timer_cap)
        return list(specs.values())

    # -- per-runtime spec builders ---------------------------------------

    def _query_specs(self, add, q, schema, caps, packed_ok, samples):
        """Row + packed steps for a plain QueryRuntime or a FusedChain."""
        from .runtime import FusedChain
        fused = isinstance(q, FusedChain)
        name = q.name
        app = self.app

        def tstates_zero():
            return {t: _zeros_like_tree(app.tables[t].state)
                    for t in q.table_deps}

        def states_zero():
            if fused:
                return (tuple(_zeros_like_tree(m.states)
                              for m in q.queries),
                        tuple(_zero_now()
                              for _ in q.queries))
            return (_zeros_like_tree(q.states), _zero_now())

        head = q.head if fused else q
        row_caps = sorted({min(c, head.max_step_capacity or c)
                           for c in caps})
        for cap in row_caps:
            def build(cap=cap):
                states, emitted = states_zero()
                fn = q._step_for() if fused else q._step_for(cap)
                return fn, (states, tstates_zero(), emitted,
                            _zero_batch(schema, cap), _zero_now())
            add(f"{name}/row/{cap}", build)
        if packed_ok:
            pk_caps = sorted({min(c, head.max_packed_capacity or c)
                              for c in caps})
            for enc in self._encodings(schema, samples):
                for cap in pk_caps:
                    def build(enc=enc, cap=cap):
                        states, emitted = states_zero()
                        fn = q._packed_step_for(enc, cap)
                        return fn, (states, tstates_zero(), emitted,
                                    _zero_packed(schema, enc, cap))
                    add(f"{name}/packed/{cap}/{','.join(enc)}", build)

    def _fanout_specs(self, add, group, schema, caps, packed_ok,
                      samples):
        """Row + packed steps for a fused fan-out group
        (plan/optimizer.py FanoutGroup): one program per chunk shape
        covering every grouped subscriber of the junction."""
        app = self.app
        name = f"fanout:{group.name}"

        def tstates_zero():
            return {t: _zeros_like_tree(app.tables[t].state)
                    for t in group.table_deps}

        def states_zero():
            st, em = group._read_states()
            return _zeros_like_tree(st), _zeros_like_tree(em)

        for cap in caps:
            def build(cap=cap):
                states, emitted = states_zero()
                fn = group._step_for()
                return fn, (states, tstates_zero(), emitted,
                            _zero_batch(schema, cap), _zero_now())
            add(f"{name}/row/{cap}", build)
        if packed_ok:
            pk_caps = sorted({min(c, group.max_packed_capacity or c)
                              for c in caps})
            for enc in self._encodings(schema, samples):
                for cap in pk_caps:
                    def build(enc=enc, cap=cap):
                        states, emitted = states_zero()
                        fn = group._packed_step_for(enc, cap)
                        return fn, (states, tstates_zero(), emitted,
                                    _zero_packed(schema, enc, cap))
                    add(f"{name}/packed/{cap}/{','.join(enc)}", build)

    def _ring_specs(self, add, sid, j, receivers, fanout, fused_members,
                    samples):
        """Device reorder-ring step (resilience/ordering.py) plus the
        consumer programs its releases dispatch. The ring emits
        EventBatches of capacity 2*C which each receiver's
        process_batch slices at max_step_capacity — warm the ring sort
        AND those row shapes so the opt-in ring costs zero steady-state
        compiles and its programs join the compiled-program audit."""
        from .runtime import (JoinStreamReceiver, PatternStreamReceiver,
                              QueryRuntime)
        from ..parallel.partition import BlockStreamReceiver
        from ..resilience.ordering import ring_step_for
        from .types import np_dtype
        schema = j.schema
        buf = self.app._reorder[sid]
        C = buf.ring_capacity()
        R = 2 * C

        def build():
            fn = ring_step_for(schema.types, C)
            sts = zeros_array((C,), jnp.int64)
            scols = tuple(zeros_array((C,), np_dtype(t))
                          for t in schema.types)
            in_ts = zeros_array((C,), jnp.int64)
            in_cols = tuple(zeros_array((C,), np_dtype(t))
                            for t in schema.types)

            def sc(dt):
                if _abstract():
                    return jax.ShapeDtypeStruct((), jnp.dtype(dt))
                return jnp.asarray(0, dtype=dt)

            return fn, (sts, scols, in_ts, in_cols, sc(jnp.int32),
                        sc(jnp.int32), sc(jnp.int64), sc(jnp.int32),
                        sc(jnp.bool_))
        add(f"ring:{sid}/{C}", build)

        def split_caps(ms):
            # split_batch slices the 2C release into ms-row chunks plus
            # one R%ms-row tail — exactly the shapes dispatch will hit
            if not ms or R <= ms:
                return [R]
            out = {ms}
            if R % ms:
                out.add(R % ms)
            return sorted(out)

        if fanout is not None:
            self._fanout_specs(add, fanout, schema,
                               split_caps(fanout.max_step_capacity),
                               packed_ok=False, samples=samples)
        for r in receivers:
            if fanout is not None and fanout.covers(r):
                continue
            ms = getattr(r, "max_step_capacity", None)
            caps = split_caps(ms)
            if isinstance(r, QueryRuntime):
                if id(r) in fused_members:
                    continue
                target = r._fused_chain or r
                self._query_specs(add, target, schema, caps,
                                  packed_ok=False, samples=samples)
            elif isinstance(r, PatternStreamReceiver):
                self._pattern_specs(add, r.runtime, r.stream_id,
                                    schema, caps, packed_ok=False,
                                    samples=samples)
            elif isinstance(r, JoinStreamReceiver):
                self._join_specs(add, r.runtime, r.side, schema, caps,
                                 packed_ok=False, samples=samples)
            elif isinstance(r, BlockStreamReceiver):
                self._partition_specs(add, r.block, sid, schema, caps)

    def _pattern_specs(self, add, q, stream_id, schema, caps, packed_ok,
                       samples):
        app = self.app

        def tstates_zero():
            return {t: _zeros_like_tree(app.tables[t].state)
                    for t in q.table_deps}

        row_caps = sorted({min(c, q.max_step_capacity or c) for c in caps})
        for cap in row_caps:
            def build(cap=cap):
                fn = q._step_for_stream(stream_id)
                return fn, (_zeros_like_tree(q.nfa_state),
                            _zeros_like_tree(q.states), tstates_zero(),
                            _zero_now(),
                            _zero_batch(schema, cap), _zero_now())
            add(f"{q.name}/pattern/{stream_id}/row/{cap}", build)
        if packed_ok:
            for enc in self._encodings(schema, samples):
                for cap in row_caps:
                    def build(enc=enc, cap=cap):
                        fn = q._step_for_stream(stream_id, (enc, cap))
                        return fn, (_zeros_like_tree(q.nfa_state),
                                    _zeros_like_tree(q.states),
                                    tstates_zero(),
                                    _zero_now(),
                                    _zero_packed(schema, enc, cap))
                    add(f"{q.name}/pattern/{stream_id}/packed/{cap}/"
                        f"{','.join(enc)}", build)

    def _join_specs(self, add, q, side, schema, caps, packed_ok, samples):
        app = self.app
        opp = "R" if side == "L" else "L"

        def tstates_zero():
            return {t: _zeros_like_tree(app.tables[t].state)
                    for t in q.table_deps}

        def side_zero(s):
            return _zeros_like_tree(q.side_states[s])

        row_caps = sorted({min(c, q.max_step_capacity or c) for c in caps})
        for cap in row_caps:
            def build(cap=cap):
                fn = q._step_for_side(side)
                return fn, (side_zero(side), side_zero(opp),
                            _zeros_like_tree(q.states), tstates_zero(),
                            _zero_now(),
                            _zero_batch(schema, cap), _zero_now())
            add(f"{q.name}/join/{side}/row/{cap}", build)
        if packed_ok:
            for enc in self._encodings(schema, samples):
                for cap in row_caps:
                    def build(enc=enc, cap=cap):
                        fn = q._step_for_side(side, (enc, cap))
                        return fn, (side_zero(side), side_zero(opp),
                                    _zeros_like_tree(q.states),
                                    tstates_zero(),
                                    _zero_now(),
                                    _zero_packed(schema, enc, cap))
                    add(f"{q.name}/join/{side}/packed/{cap}/"
                        f"{','.join(enc)}", build)

    def _partition_specs(self, add, block, stream_id, schema, caps):
        row_caps = sorted({min(c, block.max_step_capacity or c)
                           for c in caps})
        for cap in row_caps:
            def build(cap=cap):
                fn = block._step_for(("stream", stream_id), cap)
                return fn, (_zeros_like_tree(block.slot_tbl),
                            _zeros_like_tree(block.qstates),
                            _zeros_like_tree(block._emitted),
                            _zeros_like_tree(block._lost),
                            _zero_batch(schema, cap), _zero_now())
            add(f"{block.name}/stream/{stream_id}/{cap}", build)

    def _partition_timer_specs(self, add, block, timer_cap):
        for plan in block.plans:
            if not block._has_timers.get(plan.name):
                continue

            def build(plan=plan):
                fn = block._step_for(("timer", plan.name), timer_cap)
                return fn, (_zeros_like_tree(block.slot_tbl),
                            _zeros_like_tree(block.qstates),
                            _zeros_like_tree(block._emitted),
                            _zeros_like_tree(block._lost),
                            _zero_batch(plan.in_schema, timer_cap),
                            _zero_now())
            add(f"{block.name}/timer/{plan.name}/{timer_cap}", build)

    def _timer_specs(self, add, q, timer_cap):
        """Scheduler-driven shapes: cap-16 TIMER batches run through the
        row steps; absent-pattern engines add a dedicated timer step and
        a due readback program."""
        from .runtime import (FusedChain, JoinQueryRuntime,
                              PatternQueryRuntime, QueryRuntime)
        app = self.app
        if isinstance(q, PatternQueryRuntime):
            if not getattr(q.engine, "has_absent", False):
                return

            def build_timer():
                fn = q._timer_step_for()
                return fn, (_zeros_like_tree(q.nfa_state),
                            _zeros_like_tree(q.states),
                            _zero_now(), _zero_now())
            add(f"{q.name}/pattern/timer", build_timer)

            def build_due():
                fn = q._due_fn_for()
                return fn, (_zeros_like_tree(q.nfa_state),)
            add(f"{q.name}/pattern/due", build_due)
            return
        if isinstance(q, JoinQueryRuntime):
            if not q._has_timers:
                return
            for side in ("L", "R"):
                schema = q.in_schemas[side]

                def build(side=side, schema=schema):
                    fn = q._step_for_side(side)
                    opp = "R" if side == "L" else "L"
                    return fn, (
                        _zeros_like_tree(q.side_states[side]),
                        _zeros_like_tree(q.side_states[opp]),
                        _zeros_like_tree(q.states),
                        {t: _zeros_like_tree(app.tables[t].state)
                         for t in q.table_deps},
                        _zero_now(),
                        _zero_batch(schema, timer_cap), _zero_now())
                add(f"{q.name}/join/{side}/row/{timer_cap}", build)
            return
        if isinstance(q, QueryRuntime):
            if not q._has_timers:
                return
            target = q._fused_chain or q

            def build():
                fused = isinstance(target, FusedChain)
                if fused:
                    states = (tuple(_zeros_like_tree(m.states)
                                    for m in target.queries),
                              tuple(_zero_now()
                                    for _ in target.queries))
                else:
                    states = (_zeros_like_tree(q.states),
                              _zero_now())
                st, emitted = states
                fn = target._step_for() if fused \
                    else target._step_for(timer_cap)
                tst = {t: _zeros_like_tree(app.tables[t].state)
                       for t in target.table_deps}
                return fn, (st, tst, emitted,
                            _zero_batch(q.in_schema, timer_cap),
                            _zero_now())
            add(f"{target.name}/row/{timer_cap}", build)

    # -- execution -------------------------------------------------------

    def warmup(self, buckets=None, samples: Optional[dict] = None,
               workers: Optional[int] = None) -> dict:
        """Compile every enumerated step, concurrently. Returns (and
        accumulates) telemetry: programs, compile_ms, cache hits/misses,
        per-step records."""
        if buckets is None:
            buckets = warm_buckets_from_env()
        self._begin()  # readiness: not ready while compiling
        try:
            return self._warmup(buckets, samples, workers)
        finally:
            self._end()

    def warm_specs(self, specs: list, workers: Optional[int] = None) -> dict:
        """Compile an externally-built spec list through this service:
        same thread pool, cache counters, cancellation and cumulative
        telemetry as warmup(). The serving TenantPool feeds its vmapped
        tenant-axis programs through here so a pool's whole compile
        story lands in ONE statistics()['compile'] entry."""
        self._begin()
        try:
            return self._run_specs(specs, workers)
        finally:
            self._end()

    def _warmup(self, buckets, samples: Optional[dict],
                workers: Optional[int]) -> dict:
        return self._run_specs(self.specs(buckets, samples=samples),
                               workers)

    def _run_specs(self, specs: list,
                   workers: Optional[int]) -> dict:
        self._cancel.clear()
        before = cache_counts()
        t0 = time.perf_counter()
        records: list[dict] = []
        errors: list[dict] = []
        cancelled: list[str] = []

        # dedupe: drop duplicate keys within this batch AND keys this
        # service already compiled (externally-built lists — the pool's
        # template-keyed specs — carry no specs()-style key dict, and a
        # re-warm with overlapping caps must not lower the same program
        # twice). Failed/cancelled specs are NOT remembered: they retry
        # on the next warmup.
        with self._lock:
            seen = set(self._warmed_keys)
        deduped = 0
        todo = []
        for s in specs:
            if s.key in seen:
                deduped += 1
                continue
            seen.add(s.key)
            todo.append(s)
        specs = todo

        def run(spec: CompileSpec) -> None:
            if self._cancel.is_set():
                # undeploy raced the warmup: stop compiling for an app
                # that is already gone (specs still run lazily if the
                # app ever dispatches again)
                cancelled.append(spec.key)
                return
            s0 = time.perf_counter()
            try:
                fn, args = spec.build()
                sharded = any(
                    len(getattr(getattr(leaf, "sharding", None),
                                "device_set", ())) > 1
                    for leaf in jax.tree_util.tree_leaves(args))
                out = fn(*args)
                jax.block_until_ready(out)
            except Exception as e:  # noqa: BLE001 — warmup is best-effort:
                # a failed spec falls back to lazy compile on first chunk
                errors.append({"step": spec.key,
                               "error": f"{type(e).__name__}: {e}"})
                return
            rec = {"step": spec.key,
                   "ms": round((time.perf_counter() - s0) * 1e3, 1)}
            if sharded:
                rec["sharded"] = True
            records.append(rec)

        nworkers = workers or _workers_from_env()
        if specs:
            with ThreadPoolExecutor(max_workers=nworkers) as pool:
                list(pool.map(run, specs))
        if errors:
            logging.getLogger("siddhi_tpu.compile").warning(
                "app '%s': %d warmup spec(s) failed and will compile "
                "lazily: %s", self.app.name, len(errors), errors[:3])
        wall = time.perf_counter() - t0
        after = cache_counts()
        n_sharded = sum(1 for r in records if r.get("sharded"))
        result = {
            "programs": len(records),
            "sharded_programs": n_sharded,
            "seconds": round(wall, 3),
            "compile_ms": round(wall * 1e3, 1),
            "cache_hits": after["hits"] - before["hits"],
            "cache_misses": after["misses"] - before["misses"],
            "steps": sorted(records, key=lambda r: -r["ms"]),
        }
        if errors:
            result["errors"] = errors
        if cancelled:
            result["cancelled"] = len(cancelled)
        if deduped:
            result["deduped"] = deduped
        with self._lock:
            self._warmed_keys.update(r["step"] for r in records)
            self.warmups += 1
            self.programs += result["programs"]
            self.sharded_programs += n_sharded
            self.total_ms += result["compile_ms"]
            self.cache_hits += result["cache_hits"]
            self.cache_misses += result["cache_misses"]
            self.records.extend(records)
        return result

    def summary(self, detail: bool = False) -> dict:
        with self._lock:
            out = {
                "warmups": self.warmups,
                "programs": self.programs,
                "sharded_programs": self.sharded_programs,
                "compile_ms": round(self.total_ms, 1),
                "cache_hits": self.cache_hits,
                "cache_misses": self.cache_misses,
            }
            if self.audit is not None:
                out["audit"] = dict(self.audit)
            if detail:
                out["steps"] = sorted(self.records,
                                      key=lambda r: -r["ms"])
        return out
