"""On-demand (store) queries: `runtime.query("from T select ... ")`.

Reference mapping:
- util/parser/OnDemandQueryParser.java:87 — parse + dispatch per kind
- query/{Find,Select,Delete,Update,UpdateOrInsert,Insert}OnDemandQueryRuntime

Execution model: the device does the data-parallel part (condition mask +
projection expressions over the table's columnar state in one jitted-free
XLA call per expression); the host does the control-plane part (group-by,
aggregation over the few matching rows, order/limit/offset). On-demand
queries are interactive, low-rate operations — the reference also runs
them synchronously on the caller thread.

Supported: SELECT (projection, group by, sum/avg/count/min/max/
distinctCount aggregates, order by, limit/offset), DELETE, UPDATE,
UPDATE OR INSERT, INSERT (constant selection) — against in-memory tables
and named windows (their retained buffer). `within`/`per` (incremental
aggregations) are handled by aggregation runtimes.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..lang import ast as A
from ..ops.expr import (CompileError, SingleStreamScope, compile_expression,
                        env_from_batch)
from ..ops.selector import output_attribute_name
from .event import CURRENT, EventBatch, StreamSchema
from .types import AttrType, GLOBAL_STRINGS

_AGGS = {"sum", "avg", "count", "min", "max", "distinctcount"}


def _find_agg(expr):
    """Return (name, arg_expr) of the outermost aggregator call, or None."""
    if isinstance(expr, A.AttributeFunction) and \
            expr.namespace is None and expr.name.lower() in _AGGS:
        arg = expr.parameters[0] if expr.parameters else None
        return expr.name.lower(), arg
    return None


def _has_agg(expr) -> bool:
    if _find_agg(expr):
        return True
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, A.Expression) and _has_agg(v):
            return True
        if isinstance(v, list) and any(
                isinstance(x, A.Expression) and _has_agg(x) for x in v):
            return True
    return False


def _batch_of_buffer(buf: dict) -> EventBatch:
    cap = buf["valid"].shape[0]
    return EventBatch(
        ts=buf.get("ts", jnp.zeros((cap,), jnp.int64)),
        cols=tuple(buf["cols"]),
        nulls=tuple(buf["nulls"]),
        kind=jnp.zeros((cap,), jnp.int32),
        valid=buf["valid"],
    )


def _decode(values, nulls, typ, key_tag="od", row_ids=None):
    out = []
    for r, (v, nl) in enumerate(zip(values, nulls)):
        if nl:
            out.append(None)
        elif typ is AttrType.STRING:
            rid = int(row_ids[r]) if row_ids is not None else r
            out.append(GLOBAL_STRINGS.decode(
                int(v), uuid_key=("od", key_tag, rid)))
        elif typ is AttrType.BOOL:
            out.append(bool(v))
        elif typ in (AttrType.FLOAT, AttrType.DOUBLE):
            out.append(float(v))
        else:
            out.append(int(v))
    return out


def rows_of_table(table) -> list:
    """Decode a device TableRuntime's valid rows (seq order) to python
    tuples — the host boundary used by cache maintenance."""
    st = jax.device_get(table.state)
    order = np.argsort(np.where(st["valid"], st["seq"], 2 ** 62))
    rows = []
    for i in order:
        if not st["valid"][i]:
            continue
        vals = []
        for c, t in enumerate(table.schema.types):
            v, nl = st["cols"][c][i], st["nulls"][c][i]
            if nl:
                vals.append(None)
            elif t is AttrType.STRING:
                vals.append(GLOBAL_STRINGS.decode(
                    int(v), uuid_key=("row", table.table_id,
                                      int(st["seq"][i]), c)))
            elif t is AttrType.BOOL:
                vals.append(bool(v))
            elif t in (AttrType.FLOAT, AttrType.DOUBLE):
                vals.append(float(v))
            else:
                vals.append(int(v))
        rows.append(tuple(vals))
    return rows


def insert_rows_of_table(table, rows: list, now_ms: int) -> None:
    from .event import batch_from_rows
    from .runtime import bucket_capacity
    with table.lock:
        for start in range(0, len(rows), 8192):
            chunk = rows[start:start + 8192]
            batch = batch_from_rows(table.schema, [tuple(r) for r in chunk],
                                    [now_ms] * len(chunk),
                                    bucket_capacity(len(chunk)))
            table.state = table.insert(table.state, batch, batch.valid)


def delete_rows_of_table(table, rows: list) -> None:
    """Invalidate rows equal (as decoded tuples) to any of `rows`."""
    if not rows:
        return
    kill = {tuple(r) for r in rows}
    with table.lock:
        st = jax.device_get(table.state)
        current = rows_of_table(table)
        # map seq-ordered decode back to physical indices
        order = np.argsort(np.where(st["valid"], st["seq"], 2 ** 62))
        phys = [i for i in order if st["valid"][i]]
        valid = np.array(st["valid"])
        for i, row in zip(phys, current):
            if row in kill:
                valid[i] = False
        # copy=True: jnp.asarray may alias the numpy buffer zero-copy,
        # and table states feed donated step arguments (runtime._donate)
        table.state = {**table.state, "valid": jnp.array(valid, copy=True)}


class OnDemandExecutor:
    """Per-app executor for store queries."""

    def __init__(self, app):
        self.app = app

    def _source(self, q: A.OnDemandQuery):
        app = self.app
        tid = q.input_id
        if tid is None and q.output is not None:
            tid = getattr(q.output, "target", None)
        t = app.tables.get(tid)
        if t is not None:
            return t, t.schema, t.buffer(t.state)
        w = app.named_windows.get(tid)
        if w is not None:
            op = w.operators[0]
            return None, w.in_schema, op.findable_buffer(w.states[0])
        a = app.aggregations.get(tid)
        if a is not None:
            if q.per is None:
                raise CompileError(
                    "querying an aggregation needs `per '<duration>'`")
            per = q.per.value if isinstance(q.per, A.Constant) else None
            if per is None:
                raise CompileError("per must be a constant duration")
            start = end = None
            if q.within is not None:
                s, e = q.within
                if not isinstance(s, A.Constant) or \
                        (e is not None and not isinstance(e, A.Constant)):
                    raise CompileError(
                        "within bounds must be constant epoch-ms longs")
                start = int(s.value)
                end = int(e.value) if e is not None else None
            schema, buf = a.materialize(str(per), start, end)
            return None, schema, buf
        raise CompileError(
            f"on-demand query: '{tid}' is not a defined table, window, "
            "or aggregation")

    def execute(self, q: A.OnDemandQuery):
        if isinstance(q, str):
            from ..lang.parser import parse_on_demand_query
            q = parse_on_demand_query(q)
        tid = q.input_id
        if tid is None and q.output is not None:
            tid = getattr(q.output, "target", None)
        rt = self.app.record_tables.get(tid)
        if rt is not None:
            return self._execute_record(q, rt)
        table, schema, buf = self._source(q)
        scope = SingleStreamScope(schema, aliases=(q.alias,))
        batch = _batch_of_buffer(buf)
        env = env_from_batch(batch)
        env["__now__"] = jnp.int64(self.app.current_time())
        out = q.output
        # write outputs carry their own ON clause (`delete T on ...`)
        cond_ast = getattr(out, "on", None) if out is not None else None
        if cond_ast is None:
            cond_ast = q.on
        mask = batch.valid
        if cond_ast is not None:
            cond = compile_expression(cond_ast, scope)
            if cond.type is not AttrType.BOOL:
                raise CompileError("on-demand ON condition must be BOOL")
            c = cond.fn(env)
            mask = mask & c.values & ~c.nulls
        if out is None or isinstance(out, A.ReturnStream):
            return self._select(q, schema, scope, env, mask, buf)
        if table is None:
            raise CompileError(
                "on-demand writes target tables, not windows")
        if isinstance(out, A.DeleteStream):
            return self._delete(table, mask)
        if isinstance(out, (A.UpdateStream, A.UpdateOrInsertStream)):
            upsert = isinstance(out, A.UpdateOrInsertStream)
            return self._update(q, table, schema, scope, env, mask, upsert)
        if isinstance(out, A.InsertIntoStream):
            return self._insert(q, table, schema, scope)
        raise CompileError(
            f"unsupported on-demand output {type(out).__name__}")

    # -- SELECT ----------------------------------------------------------
    def _select(self, q, schema, scope, env, mask, buf=None):
        sel = q.selector
        mask_h = np.asarray(jax.device_get(mask))
        idx = np.nonzero(mask_h)[0]

        row_ids = None
        if isinstance(buf, dict) and "seq" in buf:
            # stable per-row identity: uuid() cells survive re-reads of
            # the same stored row and never collide across rows
            row_ids = np.asarray(jax.device_get(buf["seq"]))[idx]

        def eval_rows(expr, pos=0):
            ce = compile_expression(expr, scope)
            c = ce.fn(env)
            vals = np.asarray(jax.device_get(c.values))[idx]
            nulls = np.asarray(jax.device_get(c.nulls))[idx]
            # column-identity tag (position + expression): uuid() cells
            # stay distinct per column and stable across repeated queries
            return _decode(vals, nulls, ce.type,
                           key_tag=(q.input_id, pos, repr(expr)),
                           row_ids=row_ids)

        if sel.select_all or not sel.attributes:
            names = [a.name for a in schema.attributes]
            cols = [eval_rows(A.Variable(attribute=n), p)
                    for p, n in enumerate(names)]
            rows = [tuple(col[i] for col in cols)
                    for i in range(len(idx))]
            return self._order_limit(q, rows, names)

        has_agg = bool(sel.group_by) or any(
            _has_agg(oa.expression) for oa in sel.attributes)
        names = [output_attribute_name(oa, i)
                 for i, oa in enumerate(sel.attributes)]
        if not has_agg:
            cols = [eval_rows(oa.expression, p)
                for p, oa in enumerate(sel.attributes)]
            rows = [tuple(col[i] for col in cols)
                    for i in range(len(idx))]
            return self._order_limit(q, rows, names)

        # group-by + aggregation (host side over matching rows)
        gb_cols = [eval_rows(g) for g in sel.group_by]
        n = len(idx)
        groups: dict = {}
        for i in range(n):
            k = tuple(col[i] for col in gb_cols) if gb_cols else ()
            groups.setdefault(k, []).append(i)
        attr_plans = []
        for p, oa in enumerate(sel.attributes):
            agg = _find_agg(oa.expression)
            if agg is not None:
                name, arg = agg
                vals = eval_rows(arg, p) if arg is not None else [1] * n
                attr_plans.append(("agg", name, vals))
            else:
                attr_plans.append(("plain", None,
                                   eval_rows(oa.expression, p)))
        rows = []
        for k, members in groups.items():
            row = []
            for kind, aname, vals in attr_plans:
                if kind == "plain":
                    row.append(vals[members[0]])
                    continue
                vs = [vals[i] for i in members if vals[i] is not None]
                if aname == "count":
                    row.append(len(members))
                elif not vs:
                    row.append(None)
                elif aname == "sum":
                    row.append(sum(vs))
                elif aname == "avg":
                    row.append(sum(vs) / len(vs))
                elif aname == "min":
                    row.append(min(vs))
                elif aname == "max":
                    row.append(max(vs))
                elif aname == "distinctcount":
                    row.append(len(set(vs)))
            rows.append(tuple(row))
        return self._order_limit(q, rows, names)

    def _order_limit(self, q, rows, names):
        sel = q.selector
        for ob in reversed(sel.order_by):
            try:
                i = names.index(ob.variable.attribute)
            except ValueError:
                raise CompileError(
                    f"order by '{ob.variable.attribute}' is not in the "
                    "selection")
            rows.sort(key=lambda r: (r[i] is None, r[i]),
                      reverse=(ob.order == "desc"))
        off = int(q.selector.offset.value) if sel.offset is not None else 0
        lim = int(q.selector.limit.value) if sel.limit is not None \
            else None
        rows = rows[off:off + lim] if lim is not None else rows[off:]
        return rows

    # -- writes ----------------------------------------------------------
    def _delete(self, table, mask):
        with table.lock:
            n = int(jax.device_get(jnp.sum(mask.astype(jnp.int32))))
            table.state = dict(table.state)
            table.state["valid"] = table.state["valid"] & ~self._unorder(
                table, mask)
        return n

    def _unorder(self, table, mask):
        """buffer() returns rows in seq order; map the mask back to the
        table's physical slot order."""
        order = jnp.argsort(jnp.where(table.state["valid"],
                                      table.state["seq"],
                                      jnp.int64(2 ** 62)))
        inv = jnp.argsort(order)
        return mask[inv]

    def _update(self, q, table, schema, scope, env, mask, upsert):
        sets = q.output.set_clause
        if not sets:
            raise CompileError("on-demand update needs a SET clause")
        phys_mask = self._unorder(table, mask)
        any_match = bool(jax.device_get(jnp.any(mask)))
        with table.lock:
            st = dict(table.state)
            order = jnp.argsort(jnp.where(st["valid"], st["seq"],
                                          jnp.int64(2 ** 62)))
            inv = jnp.argsort(order)
            if any_match or not upsert:
                cols = list(st["cols"])
                nulls = list(st["nulls"])
                for var, expr in sets:
                    ci = schema.index_of(var.attribute)
                    ce = compile_expression(expr, scope)
                    v = ce.fn(env)
                    vals = jnp.broadcast_to(v.values, phys_mask.shape)
                    nls = jnp.broadcast_to(v.nulls, phys_mask.shape)
                    cols[ci] = jnp.where(phys_mask,
                                         vals[inv].astype(cols[ci].dtype),
                                         cols[ci])
                    nulls[ci] = jnp.where(phys_mask, nls[inv], nulls[ci])
                st["cols"] = tuple(cols)
                st["nulls"] = tuple(nulls)
                table.state = st
                return int(jax.device_get(
                    jnp.sum(mask.astype(jnp.int32))))
        # upsert with no match: insert a row built from the SET constants
        row = [None] * len(schema.attributes)
        for var, expr in sets:
            if not isinstance(expr, A.Constant):
                raise CompileError(
                    "update-or-insert insert path needs constant SET "
                    "values")
            row[schema.index_of(var.attribute)] = expr.value
        self._insert_row(table, schema, row)
        return 1

    def _insert(self, q, table, schema, scope):
        sel = q.selector
        if sel.select_all or not sel.attributes:
            raise CompileError("on-demand insert needs a value selection")
        row = []
        for oa in sel.attributes:
            if not isinstance(oa.expression, A.Constant):
                raise CompileError(
                    "on-demand insert selection must be constants")
            row.append(oa.expression.value)
        self._insert_row(table, schema, row)
        return 1

    def _insert_row(self, table, schema, row):
        from .event import batch_from_rows
        batch = batch_from_rows(schema, [tuple(row)],
                                [self.app.current_time()], 8)
        with table.lock:
            table.state = table.insert(table.state, batch,
                                       batch.valid)


    # -- record (@Store) tables: host path --------------------------------
    def _execute_record(self, q, rt):
        """On-demand queries against @Store tables: conditions push down
        through the store SPI (OnDemandQueryParser's record-table branch,
        AbstractQueryableRecordTable.java:99); selection/order/limit run
        host-side on the returned records."""
        from .store import host_eval
        out = q.output
        cond_ast = getattr(out, "on", None) if out is not None else None
        if cond_ast is None:
            cond_ast = q.on
        empty = StreamSchema("#none", ())
        cond = rt.compile_condition(cond_ast,
                                    lambda e: host_eval(e, empty),
                                    alias=q.alias)
        if out is None or isinstance(out, A.ReturnStream):
            rows = rt.find_rows(cond, [None])
            sel = q.selector
            if sel.select_all or not sel.attributes:
                names = list(rt.schema.names)
                out_rows = [tuple(r) for r in rows]
            else:
                names, fns = [], []
                for oa in sel.attributes:
                    e = oa.expression
                    if not isinstance(e, (A.Variable, A.Constant,
                                          A.MathOp)):
                        raise CompileError(
                            "record-table on-demand select supports "
                            "attributes/constants/arithmetic")
                    fns.append(host_eval(e, rt.schema))
                    names.append(oa.rename or (
                        e.attribute if isinstance(e, A.Variable)
                        else f"c{len(names)}"))
                out_rows = [tuple(f(r) for f in fns) for r in rows]
            return self._order_limit(q, out_rows, names)
        if isinstance(out, A.DeleteStream):
            return rt.delete_rows(cond, [None])
        if isinstance(out, (A.UpdateStream, A.UpdateOrInsertStream)):
            sets = q.output.set_clause
            if not sets:
                raise CompileError("on-demand update needs a SET clause")
            set_map = {}
            for var, expr in sets:
                set_map[rt.schema.index_of(var.attribute)] = \
                    host_eval(expr, empty)(None)
            if isinstance(out, A.UpdateOrInsertStream):
                add = [None] * len(rt.schema.attributes)
                for i, v in set_map.items():
                    add[i] = v
                rt.update_or_add_rows(cond, [None], [set_map],
                                      [tuple(add)])
                return 1
            return rt.update_rows(cond, [None], [set_map])
        if isinstance(out, A.InsertIntoStream):
            sel = q.selector
            if sel.select_all or not sel.attributes:
                raise CompileError(
                    "on-demand insert needs a value selection")
            row = tuple(host_eval(oa.expression, empty)(None)
                        for oa in sel.attributes)
            rt.insert_rows([row])
            return 1
        raise CompileError(
            f"unsupported on-demand output {type(out).__name__}")
