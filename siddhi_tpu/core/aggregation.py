"""Incremental aggregation: `define aggregation A from S select ...
group by ... aggregate by ts every sec ... year`.

Reference mapping:
- AggregationRuntime (aggregation/AggregationRuntime.java:81)
- IncrementalExecutor chain (aggregation/IncrementalExecutor.java:103-159)
  — per-duration bucket cascade sec->min->...->year
- incremental decomposition Avg -> sum&count
  (query/selector/attribute/aggregator/incremental/*.java)
- parser util/parser/AggregationParser.java:93
- query side IncrementalAggregateCompileCondition (within ... per ...)

TPU-first design: the reference cascades one duration into the next on
bucket roll (timer-driven, pointer-chasing). Here every duration
aggregates the event batch DIRECTLY into a bounded keyed device table
whose key is hash(group values, bucket start): scatter-add lanes (sum /
count / min / max — all add-only, buckets never remove). Because buckets
are keyed rather than 'current', out-of-order events land in their
correct bucket with no special handling (the reference needs
OutOfOrderEventsDataAggregator). Month/year buckets use exact civil
calendar math on device (days-from-civil integer algorithm).

Query side (`from A within <start>, <end> per 'duration' select ...`)
materializes the duration's table as rows of
(group attrs..., defined aggregate outputs..., AGG_TIMESTAMP) and the
on-demand executor projects/filters over them.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp

from ..lang import ast as A
from ..ops.expr import (CompileError, SingleStreamScope, compile_expression,
                        env_from_batch)
from ..ops.keyed import hash_columns, lookup_or_insert
from ..ops.selector import output_attribute_name
from .event import CURRENT, Attribute, EventBatch, StreamSchema
from .stream import Receiver
from .types import AttrType, np_dtype

DURATIONS = ("seconds", "minutes", "hours", "days", "months", "years")

_FIXED_MS = {"seconds": 1000, "minutes": 60_000, "hours": 3_600_000,
             "days": 86_400_000}

_AGG_LANES = {
    # name -> lane kinds; 'ncount' counts NON-NULL argument values so
    # all-null buckets materialize as null (Siddhi aggregator semantics)
    "sum": ("sum", "ncount"),
    "count": ("count",),
    "avg": ("sum", "ncount"),
    "min": ("min", "ncount"),
    "max": ("max", "ncount"),
}


def _civil_from_days(z):
    """Days since 1970-01-01 -> (year, month) — Hinnant's civil algorithm
    in int64 (exact for the whole representable range)."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m


def _days_from_civil(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def bucket_start(ts_ms, duration: str):
    """Bucket start timestamp (ms) for a duration, on device."""
    if duration in _FIXED_MS:
        w = _FIXED_MS[duration]
        return (ts_ms // w) * w
    days = ts_ms // 86_400_000
    y, m = _civil_from_days(days)
    if duration == "months":
        d0 = _days_from_civil(y, m, jnp.ones_like(m))
    elif duration == "years":
        d0 = _days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    else:
        raise CompileError(f"unknown duration '{duration}'")
    return d0 * 86_400_000


class AggregationRuntime(Receiver):
    """One `define aggregation`: per-duration bounded bucket tables fed
    by a jitted scatter-add step, queried via within/per."""

    supports_packed = False
    K = 4096  # (group, bucket) slots per duration

    def __init__(self, app, ad: A.AggregationDefinition,
                 in_schema: StreamSchema):
        self.app = app
        self.ad = ad
        self.aggregation_id = ad.aggregation_id
        self.in_schema = in_schema
        self.durations = [d for d in DURATIONS if d in ad.durations]
        if not self.durations:
            raise CompileError(
                f"aggregation '{ad.aggregation_id}' has no durations")
        scope = SingleStreamScope(in_schema,
                                  aliases=(getattr(ad.input, "alias",
                                                   None),))
        self.scope = scope
        # aggregate-by timestamp attribute (LONG) or arrival time
        self.ts_idx: Optional[int] = None
        if ad.aggregate_by is not None:
            self.ts_idx = in_schema.index_of(ad.aggregate_by.attribute)
            if in_schema.attributes[self.ts_idx].type is not AttrType.LONG:
                raise CompileError(
                    "aggregate by attribute must be LONG (epoch ms)")

        # group-by: plain variables (AggregationParser restriction)
        self.group_exprs = []
        self.group_attrs = []
        for g in (ad.selector.group_by or []):
            if not isinstance(g, A.Variable):
                raise CompileError(
                    "aggregation group by must be plain attributes")
            self.group_exprs.append(compile_expression(g, scope))
            self.group_attrs.append(Attribute(
                g.attribute, in_schema.type_of(g.attribute)))

        # select attrs: plain group attrs pass through; aggregator calls
        # decompose into add-only lanes
        self.outputs = []   # (name, kind, payload)
        self.lanes = []     # (agg_name, lane_kind, CompiledExpr|None, dtype)
        for i, oa in enumerate(ad.selector.attributes):
            name = output_attribute_name(oa, i)
            e = oa.expression
            if isinstance(e, A.Variable):
                if not any(isinstance(g, A.Variable) and
                           g.attribute == e.attribute
                           for g in (ad.selector.group_by or [])):
                    raise CompileError(
                        f"aggregation select attribute '{name}' must be "
                        "a group-by attribute or an aggregate")
                self.outputs.append((name, "group",
                                     in_schema.index_of(e.attribute)))
                continue
            if isinstance(e, A.AttributeFunction) and e.namespace is None \
                    and e.name.lower() in _AGG_LANES:
                fname = e.name.lower()
                arg = None
                if e.parameters:
                    arg = compile_expression(e.parameters[0], scope)
                elif fname != "count":
                    raise CompileError(f"{fname}() needs an argument")
                lane_ids = []
                for kind in _AGG_LANES[fname]:
                    if kind in ("count", "ncount"):
                        dt = jnp.int64
                    elif arg.type in (AttrType.INT, AttrType.LONG):
                        dt = jnp.int64
                    else:
                        dt = jnp.float64
                    lane_ids.append(len(self.lanes))
                    self.lanes.append((fname, kind, arg, dt))
                out_t = (AttrType.DOUBLE if fname == "avg" or
                         (fname in ("sum", "min", "max") and arg.type
                          not in (AttrType.INT, AttrType.LONG))
                         else AttrType.LONG)
                if fname in ("min", "max") and arg.type in (
                        AttrType.INT, AttrType.LONG):
                    out_t = AttrType.LONG
                self.outputs.append((name, fname, (lane_ids, out_t)))
                continue
            raise CompileError(
                "aggregation select supports group attributes and "
                "sum/avg/count/min/max aggregates")

        out_attrs = []
        for n, kind, payload in self.outputs:
            t = in_schema.attributes[payload].type if kind == "group" \
                else payload[1]
            out_attrs.append(Attribute(n, t))
        out_attrs.append(Attribute("AGG_TIMESTAMP", AttrType.LONG))
        self.out_schema = StreamSchema(ad.aggregation_id,
                                       tuple(out_attrs))

        self.states = {d: self._init_state() for d in self.durations}
        self._lock = threading.Lock()
        self._steps: dict = {}

    def _init_state(self):
        K = self.K
        lanes = []
        for fname, kind, arg, dt in self.lanes:
            if kind == "min":
                init = jnp.iinfo(jnp.int64).max if dt == jnp.int64 \
                    else jnp.inf
            elif kind == "max":
                init = jnp.iinfo(jnp.int64).min if dt == jnp.int64 \
                    else -jnp.inf
            else:
                init = 0
            lanes.append(jnp.full((K,), init, dtype=dt))
        return {
            "keys": jnp.zeros((K,), jnp.int64),
            "used": jnp.zeros((K,), jnp.bool_),
            "bstart": jnp.zeros((K,), jnp.int64),
            "groups": tuple(jnp.zeros((K,), np_dtype(a.type))
                            for a in self.group_attrs),
            "gnulls": tuple(jnp.zeros((K,), jnp.bool_)
                            for _ in self.group_attrs),
            "lanes": tuple(lanes),
            "overflow": jnp.int64(0),
        }

    # -- ingest -----------------------------------------------------------
    def receive(self, events):
        from .runtime import QueryRuntime
        for batch, last_ts in QueryRuntime.encode_chunks(
                self.in_schema, events, None):
            self.process_batch(batch, last_ts)

    def process_batch(self, batch: EventBatch, timestamp: int,
                      now=None) -> None:
        with self._lock:
            step = self._step_for(batch.capacity)
            self.states = step(self.states, batch)

    def _step_for(self, capacity: int):
        fn = self._steps.get(capacity)
        if fn is None:
            fn = jax.jit(self._make_step())
            self._steps[capacity] = fn
        return fn

    def _make_step(self):
        K = self.K

        def step(states, batch: EventBatch):
            env = env_from_batch(batch)
            active = batch.valid & (batch.kind == CURRENT)
            if self.ts_idx is not None:
                ets = batch.cols[self.ts_idx].astype(jnp.int64)
            else:
                ets = batch.ts
            gcols = [ce.fn(env) for ce in self.group_exprs]
            new_states = {}
            for d in self.durations:
                st = states[d]
                bs = bucket_start(ets, d)
                hk = hash_columns(
                    [bs] + [c.values for c in gcols],
                    [jnp.zeros_like(active)] + [c.nulls for c in gcols])
                slots, keys, used, ovf = lookup_or_insert(
                    st["keys"], st["used"], hk, active)
                ok = active & (slots >= 0)
                tgt = jnp.where(ok, slots, jnp.int32(K))
                bstart = st["bstart"].at[tgt].set(
                    jnp.where(ok, bs, 0), mode="drop")
                groups = tuple(
                    g.at[tgt].set(jnp.where(ok, c.values.astype(g.dtype),
                                            0), mode="drop")
                    for g, c in zip(st["groups"], gcols))
                gnulls = tuple(
                    gn.at[tgt].set(jnp.where(ok, c.nulls, False),
                                   mode="drop")
                    for gn, c in zip(st["gnulls"], gcols))
                lanes = []
                for (fname, kind, arg, dt), lv in zip(self.lanes,
                                                      st["lanes"]):
                    if kind == "count":
                        contrib = jnp.where(ok, jnp.int64(1), 0)
                        lanes.append(lv.at[tgt].add(contrib, mode="drop"))
                        continue
                    c = arg.fn(env)
                    eff = ok & ~c.nulls
                    if kind == "ncount":
                        lanes.append(lv.at[tgt].add(
                            jnp.where(eff, jnp.int64(1), 0), mode="drop"))
                        continue
                    v = c.values.astype(dt)
                    if kind == "sum":
                        lanes.append(lv.at[tgt].add(
                            jnp.where(eff, v, 0), mode="drop"))
                    elif kind == "min":
                        lanes.append(lv.at[tgt].min(
                            jnp.where(eff, v, lv.dtype.type(
                                jnp.iinfo(jnp.int64).max)
                                if dt == jnp.int64 else jnp.inf),
                            mode="drop"))
                    else:
                        lanes.append(lv.at[tgt].max(
                            jnp.where(eff, v, lv.dtype.type(
                                jnp.iinfo(jnp.int64).min)
                                if dt == jnp.int64 else -jnp.inf),
                            mode="drop"))
                new_states[d] = {
                    "keys": keys, "used": used, "bstart": bstart,
                    "groups": groups, "gnulls": gnulls,
                    "lanes": tuple(lanes),
                    "overflow": st["overflow"] + ovf,
                }
            return new_states

        return step

    # -- query side -------------------------------------------------------
    def duration_key(self, duration: str) -> str:
        """Normalize a `per '...'` duration spelling to the canonical
        DURATIONS key, validating it against this aggregation."""
        d = duration.lower().rstrip("'\" ")
        alias = {"sec": "seconds", "min": "minutes", "hour": "hours",
                 "day": "days", "month": "months", "year": "years"}
        d = alias.get(d, d)
        if d not in self.durations:
            raise CompileError(
                f"aggregation '{self.aggregation_id}' has no duration "
                f"'{duration}' (available: {self.durations})")
        return d

    def materialize(self, duration: str, start: Optional[int],
                    end: Optional[int]):
        """-> (schema, buffer dict) of finished+running buckets in the
        duration's table, filtered to [start, end] (AGG_TIMESTAMP)."""
        d = self.duration_key(duration)
        with self._lock:
            st = jax.device_get(self.states[d])
        return self.materialize_from(st, d, start, end)

    def materialize_from(self, st: dict, duration: str,
                         start: Optional[int], end: Optional[int]):
        """Materialize from ONE duration's HOST-side state dict (a
        device_get of `states[d]`, or one tenant's slot slice of a
        pool's stacked aggregation state — serving/pool.py
        materialize_tenant)."""
        self.duration_key(duration)
        import numpy as np
        valid = np.asarray(st["used"]).copy()
        bs = np.asarray(st["bstart"])
        if start is not None:
            valid &= bs >= start
        if end is not None:
            valid &= bs < end
        cols = []
        nulls = []
        for name, kind, payload in self.outputs:
            if kind == "group":
                # stored group columns follow group_attrs order
                gi = [a.name for a in self.group_attrs].index(
                    self.in_schema.attributes[payload].name)
                cols.append(np.asarray(st["groups"][gi]))
                nulls.append(np.asarray(st["gnulls"][gi]))
                continue
            lane_ids, out_t = payload
            lvs = [np.asarray(st["lanes"][i]) for i in lane_ids]
            if kind == "avg":
                s, nc = lvs
                cols.append(s / np.maximum(nc, 1))
                nulls.append(nc == 0)
            elif kind == "count":
                cols.append(lvs[0])
                nulls.append(np.zeros_like(valid))
            else:  # sum/min/max: null when no non-null values seen
                v, nc = lvs
                cols.append(np.where(nc == 0, np.zeros_like(v), v))
                nulls.append(nc == 0)
        cols.append(bs)
        nulls.append(np.zeros_like(valid))
        buf = {"cols": tuple(jnp.asarray(c) for c in cols),
               "nulls": tuple(jnp.asarray(n) for n in nulls),
               "ts": jnp.asarray(bs),
               "valid": jnp.asarray(valid)}
        return self.out_schema, buf

    # -- persistence ------------------------------------------------------
    def snapshot_state(self) -> dict:
        with self._lock:
            return jax.device_get(self.states)

    def restore_state(self, snap: dict) -> None:
        from .runtime import _fresh_device
        with self._lock:
            # fresh device buffers: snapshots hold host numpy that
            # device_put may alias zero-copy (see runtime._fresh_device)
            self.states = _fresh_device(snap)
