"""Columnar event model — the TPU-native replacement for the reference's
pointer-linked event model.

Reference mapping:
- Event (ts + Object[] data)                  -> one row of an EventBatch
- StreamEvent type CURRENT/EXPIRED/TIMER/RESET (event/stream/StreamEvent.java:37)
                                              -> the `kind` column
- ComplexEventChunk (mutable linked list)     -> an EventBatch (fixed capacity,
                                                 validity mask)
- MetaStreamEvent (compile-time schema)       -> StreamSchema

An EventBatch is a pytree of device arrays: struct-of-arrays columns plus
timestamp / kind / validity lanes, all of one static capacity B. Invalid rows
are padding; operators must treat them as absent. Per-column null masks carry
Java null semantics through arithmetic (see ops/expr.py).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .types import AttrType, GLOBAL_STRINGS, np_dtype, null_value

# Event kinds (match reference ComplexEvent.Type ordinal semantics)
CURRENT = 0
EXPIRED = 1
TIMER = 2
RESET = 3


@dataclasses.dataclass(frozen=True)
class Attribute:
    name: str
    type: AttrType


@dataclasses.dataclass(frozen=True)
class StreamSchema:
    """Compile-time stream shape (= MetaStreamEvent)."""

    stream_id: str
    attributes: tuple[Attribute, ...]

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(a.name for a in self.attributes)

    @property
    def types(self) -> tuple[AttrType, ...]:
        return tuple(a.type for a in self.attributes)

    def index_of(self, name: str) -> int:
        for i, a in enumerate(self.attributes):
            if a.name == name:
                return i
        raise KeyError(f"stream '{self.stream_id}' has no attribute '{name}'")

    def type_of(self, name: str) -> AttrType:
        return self.attributes[self.index_of(name)].type


@jax.tree_util.register_pytree_node_class
class EventBatch:
    """A fixed-capacity micro-batch of events for one stream.

    cols[i] is the data column for attribute i; nulls[i] its null mask.
    Rows where ``valid`` is False are padding and carry no meaning.
    """

    __slots__ = ("ts", "cols", "nulls", "kind", "valid")

    def __init__(self, ts, cols, nulls, kind, valid):
        self.ts = ts
        self.cols = tuple(cols)
        self.nulls = tuple(nulls)
        self.kind = kind
        self.valid = valid

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.ts, self.cols, self.nulls, self.kind, self.valid), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        ts, cols, nulls, kind, valid = children
        return cls(ts, cols, nulls, kind, valid)

    # -- shape helpers -----------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.ts.shape[0]

    def count(self):
        return jnp.sum(self.valid.astype(jnp.int32))

    @classmethod
    def empty(cls, schema: StreamSchema, capacity: int) -> "EventBatch":
        from .types import col_zeros
        cols = tuple(col_zeros(t, capacity) for t in schema.types)
        nulls = tuple(jnp.zeros((capacity,), dtype=jnp.bool_) for _ in schema.types)
        return cls(
            ts=jnp.zeros((capacity,), dtype=jnp.int64),
            cols=cols,
            nulls=nulls,
            kind=jnp.zeros((capacity,), dtype=jnp.int32),
            valid=jnp.zeros((capacity,), dtype=jnp.bool_),
        )

    def with_kind(self, kind_value: int) -> "EventBatch":
        return EventBatch(
            self.ts,
            self.cols,
            self.nulls,
            jnp.full_like(self.kind, kind_value),
            self.valid,
        )

    def mask(self, keep) -> "EventBatch":
        """Invalidate rows where ``keep`` is False (no compaction)."""
        return EventBatch(self.ts, self.cols, self.nulls, self.kind,
                          jnp.logical_and(self.valid, keep))


def batch_from_rows(
    schema: StreamSchema,
    rows: Sequence[Sequence[Any]],
    timestamps: Sequence[int],
    capacity: int,
    kinds: Sequence[int] | None = None,
) -> EventBatch:
    """Host-side: build a padded EventBatch from Python rows.

    Strings are interned into GLOBAL_STRINGS; None becomes (null mask, in-band
    placeholder).
    """
    n = len(rows)
    assert n <= capacity, (n, capacity)
    ts = np.zeros((capacity,), dtype=np.int64)
    ts[:n] = np.asarray(timestamps, dtype=np.int64)
    kind = np.zeros((capacity,), dtype=np.int32)
    if kinds is not None:
        kind[:n] = np.asarray(kinds, dtype=np.int32)
    valid = np.zeros((capacity,), dtype=np.bool_)
    valid[:n] = True

    cols = []
    nulls = []
    for i, t in enumerate(schema.types):
        dt = np_dtype(t)
        col = np.full((capacity,), null_value(t), dtype=dt)
        nul = np.zeros((capacity,), dtype=np.bool_)
        for r, row in enumerate(rows):
            v = row[i]
            if v is None:
                nul[r] = True
            elif t is AttrType.STRING:
                col[r] = GLOBAL_STRINGS.encode(v)
            elif t is AttrType.BOOL:
                col[r] = bool(v)
            else:
                col[r] = dt(v)
        cols.append(col)
        nulls.append(nul)
    return EventBatch(ts=ts, cols=tuple(cols), nulls=tuple(nulls), kind=kind,
                      valid=valid)


def batch_from_columns(
    schema: StreamSchema,
    ts,
    cols: Sequence,
    capacity: int | None = None,
) -> EventBatch:
    """Columnar fast-path ingest: build an EventBatch straight from numpy
    arrays (no per-row Python). STRING columns must already be dictionary
    codes (GLOBAL_STRINGS.encode). The TPU-native equivalent of the
    reference's Event[] send overload (InputHandler.java:63)."""
    ts = np.asarray(ts, dtype=np.int64)
    n = ts.shape[0]
    capacity = capacity or n
    assert n <= capacity, (n, capacity)
    if len(cols) != len(schema.types):
        raise ValueError(
            f"stream '{schema.stream_id}' expects {len(schema.types)} data "
            f"columns, got {len(cols)}")
    out_ts = np.zeros((capacity,), dtype=np.int64)
    out_ts[:n] = ts
    valid = np.zeros((capacity,), dtype=np.bool_)
    valid[:n] = True
    out_cols, out_nulls = [], []
    for t, c in zip(schema.types, cols):
        dt = np_dtype(t)
        col = np.zeros((capacity,), dtype=dt)
        col[:n] = np.asarray(c, dtype=dt)
        out_cols.append(col)
        out_nulls.append(np.zeros((capacity,), dtype=np.bool_))
    return EventBatch(ts=out_ts, cols=tuple(out_cols),
                      nulls=tuple(out_nulls),
                      kind=np.zeros((capacity,), dtype=np.int32),
                      valid=valid)


_UUID_BATCH_NONCE = itertools.count()


def rows_from_batch(schema_types: Sequence[AttrType], batch) -> list:
    """Host-side: decode a device EventBatch into
    (timestamp, kind, tuple(values)) rows, in row order, skipping padding.

    uuid() sentinel cells materialize here with a per-decode nonce in the
    key: unique across batches, stable within one decode. Callers that
    deliver one emission to several consumers decode once and share the
    rows (QueryRuntime._dispatch_output.rows_once)."""
    nonce = next(_UUID_BATCH_NONCE)
    ts = np.asarray(batch.ts)
    kind = np.asarray(batch.kind)
    valid = np.asarray(batch.valid)
    cols = [np.asarray(c) for c in batch.cols]
    nulls = [np.asarray(nl) for nl in batch.nulls]
    out = []
    for r in range(ts.shape[0]):
        if not valid[r]:
            continue
        vals = []
        for i, t in enumerate(schema_types):
            if nulls[i][r]:
                vals.append(None)
            elif t is AttrType.OBJECT:
                from .types import decode_set
                vals.append(decode_set(cols[i][r]))
            elif t is AttrType.STRING:
                vals.append(GLOBAL_STRINGS.decode(
                    cols[i][r], uuid_key=(nonce, int(ts[r]), r, i)))
            elif t is AttrType.BOOL:
                vals.append(bool(cols[i][r]))
            elif t in (AttrType.FLOAT, AttrType.DOUBLE):
                vals.append(float(cols[i][r]))
            else:
                vals.append(int(cols[i][r]))
        out.append((int(ts[r]), int(kind[r]), tuple(vals)))
    return out
