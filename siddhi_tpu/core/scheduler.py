"""Host-side scheduler: fires TIMER work when wall-clock (or playback
event-time) passes a due timestamp.

Reference mapping:
- util/Scheduler.java:48,113 — notifyAt(ts) + toNotifyQueue drained by a
  worker; in playback mode driven by TimestampGenerator time-change
  listeners instead of wall clock.
- trigger/PeriodicTrigger.java:73 — periodic callbacks reuse the same
  machinery here.

The TPU build keeps expiry *evaluation* on device (windows compare buffered
timestamps against the batch `now` column); the scheduler's only job is to
inject a TIMER batch when no real events arrive to advance time. In playback
mode timers fire synchronously from the ingest path (deterministic replay —
the key to bit-equal tests, reference managment/PlaybackTestCase.java).
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Callable, Optional


class Scheduler:
    """One per app runtime. Callbacks receive the due timestamp (ms)."""

    def __init__(self, playback: bool = False, barrier=None):
        self.playback = playback
        # app quiesce barrier: wall-clock callbacks run under it so a
        # concurrent snapshot sees no half-applied timer step
        self._barrier = barrier if barrier is not None \
            else threading.RLock()
        self._heap: list = []  # (due_ms, seq, callback)
        self._seq = itertools.count()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._running = False
        # called each worker tick to pull in async-deferred dues
        self.resolve_hook = None

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self.playback or self._thread is not None:
            return
        self._running = True
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="siddhi-scheduler")
        self._thread.start()

    def shutdown(self) -> None:
        with self._cv:
            self._running = False
            self._heap.clear()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- API -------------------------------------------------------------
    def notify_at(self, due_ms: int, callback: Callable[[int], None]) -> None:
        with self._cv:
            heapq.heappush(self._heap, (int(due_ms), next(self._seq), callback))
            self._cv.notify_all()

    def pending(self) -> int:
        """Armed timers not yet fired (obs: scheduler backlog gauge)."""
        with self._cv:
            return len(self._heap)

    def lag_ms(self, now_ms: int) -> int:
        """How far the earliest armed timer is overdue relative to
        ``now_ms`` (0 when idle or on time) — the obs timer-lag gauge."""
        with self._cv:
            if not self._heap:
                return 0
            return max(0, int(now_ms) - int(self._heap[0][0]))

    def advance_to(self, now_ms: int) -> None:
        """Playback mode: fire every timer due at or before now_ms,
        synchronously, in due order (deterministic replay)."""
        while True:
            with self._cv:
                if not self._heap or self._heap[0][0] > now_ms:
                    return
                due, _, cb = heapq.heappop(self._heap)
            cb(due)

    # -- wall-clock worker ----------------------------------------------
    def _run(self) -> None:
        while True:
            if self.resolve_hook is not None:
                try:
                    self.resolve_hook()
                except Exception:  # noqa: BLE001
                    pass
            with self._cv:
                if not self._running:
                    return
                if not self._heap:
                    self._cv.wait(timeout=0.5)
                    continue
                due = self._heap[0][0]
                now = time.time() * 1000.0
                if due > now:
                    self._cv.wait(timeout=min((due - now) / 1000.0, 0.5))
                    continue
                due, _, cb = heapq.heappop(self._heap)
            try:
                with self._barrier:
                    cb(due)
            except Exception:  # noqa: BLE001 — scheduler thread must survive
                import traceback
                traceback.print_exc()
