"""Extension SPI: user-registered functions, windows, aggregators,
sources and sinks.

Reference mapping:
- @Extension + SiddhiExtensionLoader (modules/siddhi-annotations/.../
  Extension.java:56, util/SiddhiExtensionLoader.java:58) — compile-time
  classpath scanning + OSGi. Here registration is explicit:
  `SiddhiManager.set_extension("ns:name", obj)` (the reference's
  SiddhiManager.setExtension, SiddhiManager.java:167).
- executor/function/ScriptFunctionExecutor + function/Script.java —
  `define function f[python] return type { expression }` compiles the
  body as a vectorized device expression over the argument columns.

Extension kinds, dispatched by the registered object:
- ScalarFunction: elementwise function usable in any expression;
  `fn` receives jnp value arrays (one per argument) and returns a value
  array; nulls propagate (any null argument -> null result).
- custom WindowOp subclasses (a class, registered under "ns:name", used
  as #window.ns:name(...)): constructed as cls(schema, params,
  expired_enabled=...).
- Source / Sink subclasses (core/io.py) under "source:type" /
  "sink:type".
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp

from ..core.types import AttrType, np_dtype
from ..ops.expr import Col, CompileError, CompiledExpr


@dataclasses.dataclass
class ScalarFunction:
    """Vectorized scalar function extension: out = fn(*value_arrays)."""

    return_type: AttrType
    fn: Callable
    min_args: int = 0
    max_args: int = 16

    def compile(self, name: str, params: list[CompiledExpr]) -> CompiledExpr:
        if not self.min_args <= len(params) <= self.max_args:
            raise CompileError(
                f"{name}() takes {self.min_args}..{self.max_args} "
                f"arguments, got {len(params)}")
        out_t = self.return_type
        f = self.fn

        def run(env):
            cols = [p.fn(env) for p in params]
            vals = f(*[c.values for c in cols])
            nulls = jnp.zeros_like(vals, dtype=jnp.bool_)
            for c in cols:
                nulls = nulls | c.nulls
            return Col(vals.astype(np_dtype(out_t)), nulls)

        return CompiledExpr(out_t, run)


def compile_script_function(fd) -> ScalarFunction:
    """`define function f[python] return <type> { <expression> }`:
    the body is a Python expression over arg0..argN (jnp arrays) with
    jnp in scope — evaluated vectorized on device."""
    lang = (fd.language or "").lower()
    if lang not in ("python", "py"):
        raise CompileError(
            f"script language '{fd.language}' is not supported (python "
            "scripts compile to device expressions; JS needs an engine)")
    rt = fd.return_type
    if isinstance(rt, str):
        rt = AttrType[rt.upper()]
    if rt is AttrType.STRING:
        raise CompileError(
            "python script functions cannot return STRING (dictionary "
            "codes are not computable in scripts)")
    body = fd.body.strip()
    code = compile(body, f"<function {fd.function_id}>", "eval")

    def fn(*arrays):
        scope = {"jnp": jnp}
        for i, a in enumerate(arrays):
            scope[f"arg{i}"] = a
        return jnp.asarray(eval(code, scope))  # noqa: S307 — user script

    return ScalarFunction(return_type=rt, fn=fn)


def build_function_table(app) -> dict:
    """Planner-side: extensions + script functions -> the `functions`
    dict consulted by compile_expression (key -> params adapter)."""
    table = {}
    mgr = app.manager
    exts = dict(getattr(mgr, "extensions", {}) or {}) if mgr else {}
    for key, obj in exts.items():
        if isinstance(obj, ScalarFunction):
            k = key.lower()
            table[k] = (lambda params, o=obj, n=key:
                        o.compile(n, params))
    for fid, fd in app.ast.function_definitions.items():
        sf = compile_script_function(fd)
        table[fid.lower()] = (lambda params, o=sf, n=fid:
                              o.compile(n, params))
    return table
