"""Runtime assembly: query runtimes, the app runtime, and the planner that
builds them from the parsed query object model.

Reference mapping:
- SiddhiAppRuntimeImpl (core/SiddhiAppRuntimeImpl.java:99) -> SiddhiAppRuntime
- QueryRuntimeImpl (query/QueryRuntimeImpl.java:43)        -> QueryRuntime
- SiddhiAppParser/QueryParser/SingleInputStreamParser
  (util/parser/*.java)                                     -> Planner
- Scheduler timer events (util/Scheduler.java:113)         -> TIMER batches
  injected by core/scheduler.py when a window's next_due passes.

Execution model: each query compiles to ONE jitted step function
(state, batch, now) -> (state', out_batch, next_due). The host junction layer
feeds micro-batches in; batch capacity is bucketed so jit caches stay warm.

Chain fusion (docs/performance.md): at app start the planner's junction
graph is walked and fusible `insert into` segments Q1 -> S -> Q2 -> ... are
compiled into ONE jitted chain step, so a micro-batch traverses the whole
segment in a single XLA program instead of paying a host dispatch (plus an
eager kind-rewrite) per hop. `SIDDHI_TPU_FUSE=0` falls back to per-query
dispatch. State/window buffers are donated to their steps
(`SIDDHI_TPU_DONATE=0` opts out) so they update in place instead of
copy-on-writing every chunk.
"""
from __future__ import annotations

import bisect
import contextlib
import dataclasses
import logging
import os
import re
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..lang import ast as A
from ..obs.profiler import op_scope
from ..ops.aggregators import AggregateOp
from ..ops.expr import (CompileError, SingleStreamScope,
                        collect_template_params, compile_expression)
from ..ops.join import (JoinCombinedScope, JoinCross, JoinSideScope,
                        combined_schema)
from ..ops.nfa import MatchScope, NfaCompiler, NfaEngine
from ..ops.nfa_parallel import ParallelNfaEngine, parallel_supported
from ..ops.operators import FilterOp, Operator
from ..ops.selector import (ProjectOp, output_attribute_name,
                            selector_needs_aggregation)
from ..ops.table import (TableFilterOp, TableOutputOp, TableRuntime,
                         expr_mentions_table)
from ..ops.windows2 import (BatchWindowOp, CronWindowOp, DelayWindowOp,
                            EmptyWindowOp, ExternalTimeBatchWindowOp,
                            ExternalTimeWindowOp, FrequentWindowOp,
                            HoppingWindowOp, LossyFrequentWindowOp,
                            SessionWindowOp, SortWindowOp,
                            TimeLengthWindowOp)
from ..ops.windows import (NEG_INF, POS_INF, LengthBatchWindowOp, LengthWindowOp,
                           TimeBatchWindowOp, TimeWindowOp, WindowOp)
from .event import (CURRENT, EXPIRED, TIMER, Attribute, EventBatch, StreamSchema,
                    batch_from_rows, rows_from_batch)
from .ingest import PackedChunk, unpack_buffer
from .scheduler import Scheduler
from .stream import (Event, InputHandler, QueryCallback, Receiver,
                     StreamCallback, StreamJunction)
from .types import AttrType

BATCH_BUCKETS = (16, 128, 1024, 8192, 65536, 262144, 1048576)

# step capacity cap for queries containing sort-heavy operators (windows,
# aggregations, order-by). With the int32 sort keys everywhere (see
# ops/windows.py _rel32 / emission_sort) a 65536-row sort step compiles
# in ~18 s (vs ~6 s at 8192) and runs at the same events/s — so plain
# queries take the full bucket (1M events = 16 steps); the K-vmapped
# partition blocks keep the smaller cap (compile is multiplied by the
# slot axis there)
SORT_HEAVY_CAP = 65536
PARTITION_SORT_HEAVY_CAP = 8192

WINDOW_CLASSES = {
    "time": TimeWindowOp,
    "length": LengthWindowOp,
    "lengthbatch": LengthBatchWindowOp,
    "timebatch": TimeBatchWindowOp,
    "externaltime": ExternalTimeWindowOp,
    "timelength": TimeLengthWindowOp,
    "delay": DelayWindowOp,
    "batch": BatchWindowOp,
    "sort": SortWindowOp,
    "frequent": FrequentWindowOp,
    "lossyfrequent": LossyFrequentWindowOp,
    "externaltimebatch": ExternalTimeBatchWindowOp,
    "session": SessionWindowOp,
    "cron": CronWindowOp,
    "hopping": HoppingWindowOp,
    "hoping": HoppingWindowOp,   # the reference's spelling
}


def bucket_capacity(n: int) -> int:
    i = bisect.bisect_left(BATCH_BUCKETS, n)
    if i == len(BATCH_BUCKETS):
        return BATCH_BUCKETS[-1]
    return BATCH_BUCKETS[i]


JOIN_KERNEL_ENV = "SIDDHI_TPU_JOIN_KERNEL"


def _pick_join_kernel(app_name: str, qname: str,
                      cross) -> tuple[str, str, str]:
    """Join kernel for one JoinCross: ``(kernel, reason, cause)``.

    Policy (docs/performance.md "join kernels"): the banded searchsorted
    probe whenever the ON condition carries an ``L == R`` equi conjunct,
    the [B, W] broadcast grid otherwise. ``SIDDHI_TPU_JOIN_KERNEL=
    grid|probe`` overrides (probe silently falls back to grid when no
    equi conjunct exists). The persisted PR 7 cost table
    (``.jax_cache/costs.json``, obs/costmodel.load_costs) is consulted:
    when a prior profile shows this join's grid centers dominating the
    app's measured step time, the probe pick is recorded as
    evidence-backed rather than heuristic.

    ``cause`` is a machine-readable slug — ``env-override`` /
    ``no-equi-conjunct`` / ``cost-evidence`` / ``no-cost-table`` /
    ``equi-default`` — so explain (obs/explain.py) never shows a
    decision without a cause, even when the cost table is absent."""
    env = os.environ.get(JOIN_KERNEL_ENV, "").strip().lower()
    eligible = cross.equi is not None
    if env == "grid":
        return "grid", "SIDDHI_TPU_JOIN_KERNEL=grid override", \
            "env-override"
    if env == "probe":
        if eligible:
            return "probe", "SIDDHI_TPU_JOIN_KERNEL=probe override", \
                "env-override"
        return "grid", ("SIDDHI_TPU_JOIN_KERNEL=probe requested but the "
                        "ON condition has no equi conjunct — grid "
                        "fallback"), "no-equi-conjunct"
    if not eligible:
        return "grid", ("no equi conjunct in ON condition (the banded "
                        "probe needs one)"), "no-equi-conjunct"
    try:
        from ..obs.costmodel import load_costs
        tbl = load_costs().get(app_name) or {}
    except Exception:  # noqa: BLE001 — costs are advisory
        tbl = {}
    if not tbl:
        return "probe", ("equi ON condition (banded searchsorted probe); "
                         "no cost table measured yet"), "no-cost-table"
    key, costs = max(tbl.items(),
                     key=lambda kv: kv[1].get("ms_total", 0.0))
    if key.startswith(f"join/{qname}.") and "[probe]" not in key:
        return "probe", (
            f"cost table: grid-dominated center {key} "
            f"({costs.get('ms_total', 0)} ms_total) — probe selected"), \
            "cost-evidence"
    return "probe", "equi ON condition (banded searchsorted probe)", \
        "equi-default"


def _donate(*argnums):
    """donate_argnums kwargs for the state-carrying arguments of a step:
    XLA aliases the output state buffers onto the input ones, so large
    window/NFA states update in place instead of copy-on-writing every
    chunk. Donated inputs are INVALID after the call — safe here because
    every step replaces the runtime's state references before releasing
    its lock, and snapshot/statistics reads take the same lock/barrier.
    SIDDHI_TPU_DONATE=0 opts out (debugging aid)."""
    if os.environ.get("SIDDHI_TPU_DONATE", "1") == "0":
        return {}
    return {"donate_argnums": argnums}


def _fresh_device(tree):
    """Fresh device buffers for restored state. Snapshot payloads hold
    numpy arrays (device_get), and jax may alias a numpy buffer
    ZERO-COPY on device_put — donating such an aliased buffer to a step
    (see _donate) would free memory numpy still owns. Every restore path
    copies through here before the state re-enters a donated step
    argument."""
    return jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True), tree)


def _as_current(batch: EventBatch) -> EventBatch:
    """Insert-into kind rewrite (InsertIntoStreamCallback.java:52-55):
    EXPIRED events become CURRENT on insert. Pure trace transform —
    usable both inside a fused chain step and under `_rewrite_current`."""
    return EventBatch(
        ts=batch.ts, cols=batch.cols, nulls=batch.nulls,
        kind=jnp.where(batch.valid, jnp.int32(CURRENT), batch.kind),
        valid=batch.valid)


# jitted wrapper for the UNFUSED hop path: one cached dispatch per hop
# instead of three eager ops (where + broadcast + convert)
_rewrite_current = jax.jit(_as_current)


def _chain_body(ops, has_timers: bool):
    """The traced body of one query's operator chain:
    (states, tstates, emitted, batch, now) ->
    (states', tstates', emitted', out, due). Shared by the per-query
    step compilers and the fused chain step."""

    def chain(states, tstates, emitted, batch, now):
        new_states = []
        for op, st in zip(ops, states):
            # op_scope is a nullcontext unless SIDDHI_TPU_PROFILE_SCOPES=1
            # (named scopes change lowered HLO -> compile-cache keys;
            # docs/observability.md)
            with op_scope(type(op).__name__):
                if op.needs_tables:
                    st, batch, tstates = op.step_tables(st, batch, now,
                                                        tstates)
                else:
                    st, batch = op.step(st, batch, now)
            new_states.append(st)
        if has_timers:
            dues = [op.next_due(st) for op, st in zip(ops, new_states)
                    if isinstance(op, WindowOp)]
            dues = [d for d in dues if d is not None]
            due = dues[0]
            for d in dues[1:]:
                due = jnp.minimum(due, d)
        else:
            due = jnp.asarray(POS_INF)
        emitted = emitted + batch.count().astype(jnp.int64)
        return tuple(new_states), tstates, emitted, batch, due

    return chain


def _build_packed_step(chain, schema: StreamSchema, enc: tuple,
                       capacity: int, sub_cap: Optional[int],
                       playback: bool) -> Callable:
    """Fused unpack + chain over a PackedChunk's single buffer. `chain`
    has the _chain_body signature; its states/emitted/due slots may be
    arbitrary pytrees (the fused chain threads tuples-per-query through
    the same builder). See QueryRuntime._packed_step_for for the
    sort-heavy scan rationale."""
    if sub_cap is not None and capacity > sub_cap:
        k = capacity // sub_cap

        def pstep(states, tstates, emitted, buf):
            batch, now = unpack_buffer(schema, enc, capacity, buf)
            subs = jax.tree_util.tree_map(
                lambda x: x.reshape((k, sub_cap) + x.shape[1:]),
                batch)

            def body(carry, sub):
                states, tstates, emitted, run_ts = carry
                if playback:
                    sub_now = jnp.maximum(run_ts, jnp.max(
                        jnp.where(sub.valid, sub.ts,
                                  jnp.asarray(NEG_INF))))
                else:
                    sub_now = now
                states, tstates, emitted, out, due = chain(
                    states, tstates, emitted, sub, sub_now)
                return ((states, tstates, emitted, sub_now),
                        (out, due))

            carry0 = (states, tstates, emitted,
                      jnp.asarray(NEG_INF))
            (states, tstates, emitted, _), (outs, dues) = \
                jax.lax.scan(body, carry0, subs)
            out = jax.tree_util.tree_map(
                lambda x: x.reshape((x.shape[0] * x.shape[1],)
                                    + x.shape[2:]), outs)
            due = jax.tree_util.tree_map(lambda d: d[-1], dues)
            return states, tstates, emitted, out, due
    else:
        def pstep(states, tstates, emitted, buf):
            batch, now = unpack_buffer(schema, enc, capacity, buf)
            return chain(states, tstates, emitted, batch, now)

    return jax.jit(pstep, **_donate(0, 1, 2))


class OutputHandler:
    def handle(self, timestamp: int, rows: list) -> None:
        raise NotImplementedError

    def handle_device_batch(self, out, timestamp: int,
                            current=None) -> bool:
        """Try to consume the DEVICE output batch without host row decode
        (device-to-device query chaining). Returns True when consumed —
        the row path is then skipped for this handler. ``current`` is a
        zero-arg memoized supplier of the CURRENT-kind-rewritten batch:
        the dispatching query builds it ONCE per emitted batch, so a
        fan-out of N insert-into handlers pays one jitted rewrite
        instead of N (docs/performance.md)."""
        return False


class InsertIntoStreamHandler(OutputHandler):
    """Publish query output into a stream junction; EXPIRED events become
    CURRENT on insert (InsertIntoStreamCallback.java:52-55).

    When every downstream receiver takes device batches, the output
    EventBatch is handed over directly — no host decode per hop
    (the reference's InsertIntoStreamCallback also stays in-memory;
    here 'in-memory' means on-device)."""

    def __init__(self, junction: StreamJunction, output_event_type: str):
        self.junction = junction
        self.output_event_type = output_event_type

    def handle_device_batch(self, out, timestamp: int,
                            current=None) -> bool:
        receivers = self.junction.receivers
        if not receivers:
            return True  # nobody listening — drop without decode
        if all(hasattr(r, "process_batch") for r in receivers):
            # kind rewrite runs as ONE jitted dispatch per emitted batch
            # — shared across every handler of the emitting query via
            # the memoized `current` supplier (fused segments do it
            # inside the chain trace instead)
            cur = current() if current is not None \
                else _rewrite_current(out)
            self.junction.publish_batch(cur, timestamp)
            return True
        return False

    def handle(self, timestamp, rows):
        events = [Event(timestamp=ts, data=vals) for ts, kind, vals in rows]
        self.junction.publish(events)


class InsertIntoWindowHandler(OutputHandler):
    """`insert into <named window>`: feed the shared window instance
    (query/output/callback/InsertIntoWindowCallback.java) — inserted
    events enter the window as fresh CURRENT arrivals."""

    def __init__(self, wq: "QueryRuntime"):
        self.wq = wq

    def handle_device_batch(self, out, timestamp, current=None):
        cur = current() if current is not None else _rewrite_current(out)
        self.wq.process_batch(cur, timestamp)
        return True

    def handle(self, timestamp, rows):
        self.wq.receive([Event(ts, vals) for ts, kind, vals in rows])


class WindowPublishHandler(OutputHandler):
    """Publish a named window's processed output — kinds preserved, so
    consuming queries see CURRENT/EXPIRED exactly as after an inline
    window (window/Window.java:65); the definition's output event type
    filters what subscribers observe."""

    def __init__(self, junction: StreamJunction, out_type: str):
        self.junction = junction
        self.out_type = out_type

    def _filtered(self, out):
        if self.out_type == "current":
            return out.mask(out.kind == CURRENT)
        if self.out_type == "expired":
            return out.mask(out.kind == EXPIRED)
        return out

    def handle_device_batch(self, out, timestamp, current=None):
        self.junction.publish_batch(self._filtered(out), timestamp)
        return True

    def handle(self, timestamp, rows):
        events = [Event(ts, vals, is_expired=(kind == EXPIRED))
                  for ts, kind, vals in rows
                  if self.out_type == "all" or
                  (self.out_type == "current" and kind == CURRENT) or
                  (self.out_type == "expired" and kind == EXPIRED)]
        self.junction.publish(events)


class TriggerRuntime:
    """`define trigger T at every 5 sec | at 'cron' | at 'start'`:
    publishes (triggered_time) events into stream T on schedule
    (trigger/{Periodic,Cron,Start}Trigger.java; PeriodicTrigger.java:73)."""

    def __init__(self, app, td, junction: StreamJunction):
        self.app = app
        self.td = td
        self.junction = junction
        self.cron = None
        if td.at_cron not in (None, "start"):
            from ..utils.cron import CronSchedule
            self.cron = CronSchedule(td.at_cron)

    def arm(self, base_ms: int) -> None:
        if self.td.at_cron == "start":
            self._fire(base_ms)
            return
        if self.cron is not None:
            due = self.cron.next_fire(base_ms)
        else:
            due = base_ms + self.td.at_every_ms
        self.app.scheduler.notify_at(due, self._on_timer)

    def _on_timer(self, due: int) -> None:
        if not self.app.running:
            return
        self._fire(due)
        self.arm(due)

    def _fire(self, ts: int) -> None:
        self.junction.publish([Event(ts, (ts,))])


class QueryCallbackHandler(OutputHandler):
    def __init__(self):
        self.callbacks: list[QueryCallback] = []

    def handle(self, timestamp, rows):
        if not self.callbacks:
            return
        in_events = [Event(ts, vals) for ts, kind, vals in rows
                     if kind == CURRENT]
        rm_events = [Event(ts, vals, is_expired=True)
                     for ts, kind, vals in rows if kind == EXPIRED]
        if not in_events and not rm_events:
            return
        for cb in self.callbacks:
            cb.receive(timestamp, in_events or None, rm_events or None)


def _timer_windows(operators) -> list:
    """Window ops that schedule timers (one init_state probe each)."""
    return [op for op in operators
            if isinstance(op, WindowOp) and
            op.next_due(op.init_state()) is not None]


def _all_host_due(timer_ops) -> bool:
    return bool(timer_ops) and all(
        getattr(op, "host_due_bound", None) is not None
        for op in timer_ops)


class QueryRuntime(Receiver):
    """One query: an operator chain jitted into a single device step."""

    # explicit packed-ingest capability (send_arrays gates on this, NOT on
    # hasattr(process_packed): subclasses that need dedicated per-stream
    # receivers override it back to False)
    supports_packed = True

    def __init__(self, name: str, operators: list[Operator],
                 in_schema: StreamSchema, app: "SiddhiAppRuntime"):
        self.name = name
        self.operators = operators
        self.in_schema = in_schema
        self.out_schema = operators[-1].out_schema
        self.app = app
        self.output_handlers: list[OutputHandler] = []
        self.callback_handler = QueryCallbackHandler()
        # raw device-batch observers (no host row decode) — the zero-copy
        # path used by bench.py and device-to-device chaining
        self.batch_callbacks: list[Callable] = []
        self.states = tuple(op.init_state() for op in operators)
        self.table_deps = sorted({t for op in operators
                                  for t in op.table_ids()})
        self.max_step_capacity = SORT_HEAVY_CAP if any(
            getattr(op, "sort_heavy", False) for op in operators) else None
        self._step: Optional[Callable] = None
        self._packed_steps: dict = {}  # (enc, capacity) -> jitted step
        # device-resident emitted-row counter: accumulated inside the
        # packed step (zero host syncs); read once via stats()
        self._emitted_dev = jnp.int64(0)
        self._lock = threading.Lock()
        # when EVERY timer window offers a host due bound, stream steps
        # schedule timers host-side with zero device readbacks
        self._timer_ops = _timer_windows(operators)
        self._has_timers = bool(self._timer_ops)
        self._host_due_all = _all_host_due(self._timer_ops)
        # host-computed schedules (cron windows: the next fire time cannot
        # come from device state)
        self._host_sched = [op.host_schedule for op in operators
                            if getattr(op, "host_schedule", None)]
        self._sched_due: Optional[int] = None
        # clock of the latest EVENT step (timers due at or before it are
        # subsumed by in-step expiry — see _schedule)
        self._last_now = -(2 ** 62)
        self._skip_past_dues = not any(
            getattr(op, "needs_catchup", False) for op in operators)
        self.rate_limiter = None
        self._qstats = None  # lazily created when statistics enabled
        # set on the HEAD query of a fusible insert-into segment
        # (SiddhiAppRuntime._build_fused_chains): batches entering this
        # query traverse the whole segment in one XLA program
        self._fused_chain: Optional["FusedChain"] = None
        # set when this query is a member of a fan-out fusion group
        # (plan/optimizer.py FanoutGroup) — the junction dispatches the
        # group once per chunk; this reference is explain evidence and
        # keeps direct sends/timers on the standalone step
        self._fanout_group = None
        # cost-evidence ingest chunk cap (plan/optimizer.py): consulted
        # by the send_arrays capacity negotiation when this query heads
        # a fused chain with measured per-capacity centers
        self.preferred_ingest_cap: Optional[int] = None
        # DETAIL latency probe sampling counter (see _lat_sample)
        self._lat_counter = 0

    # -- compile ---------------------------------------------------------
    def _make_step(self):
        return jax.jit(_chain_body(self.operators, self._has_timers),
                       **_donate(0, 1, 2))

    def _step_for(self, capacity: int) -> Callable:
        # one jit wrapper; XLA specializes per batch-capacity shape
        if self._step is None:
            self._step = self._make_step()
        return self._step

    def _packed_step_for(self, enc: tuple, capacity: int) -> Callable:
        """Fused unpack + operator chain over a PackedChunk's single buffer
        (the high-throughput ingest path, see core/ingest.py). One compile
        per (encoding tuple, capacity); encodings are sticky so this stays
        small.

        Sort-heavy queries (max_step_capacity set) do NOT shrink the
        transfer: the whole chunk still travels and dispatches once, and
        the step body runs a lax.scan over max_step_capacity-row
        sub-batches. XLA sort compile time grows superlinearly with row
        count (~169 s at 65k rows for a window+aggregate chain, measured),
        so the scan keeps the compiled sort width small while one dispatch
        covers the full chunk. Playback per-sub-batch time advances as the
        running max event time — the same clock the pre-scan split path
        derived per sub-chunk on the host."""
        fn = self._packed_steps.get((enc, capacity))
        if fn is None:
            fn = _build_packed_step(
                _chain_body(self.operators, self._has_timers),
                self.in_schema, enc, capacity, self.max_step_capacity,
                self.app._playback)
            self._packed_steps[(enc, capacity)] = fn
        return fn

    # sort-heavy queries cap the COMPILED sort width via the in-step scan
    # (see _packed_step_for), so the packed transfer chunk can be larger
    # than max_step_capacity — but not unbounded: XLA compile time of the
    # scanned step grows with total capacity (k=8 sub-steps: ~53 s;
    # k=128: ~452 s, measured), so packed chunks cap at 64k rows
    # (8 dispatches/1M events instead of 123, ~2x the throughput of
    # dispatch-per-8k with a first-compile cost that stays bounded)
    SCAN_CHUNK_CAP = 65536

    @property
    def max_packed_capacity(self):
        return None if self.max_step_capacity is None \
            else max(self.SCAN_CHUNK_CAP, self.max_step_capacity)

    def process_packed(self, chunk: PackedChunk) -> None:
        if self._fused_chain is not None:
            return self._fused_chain.process_packed(chunk)
        cost = self.app.cost
        probe = cost.probe("query", self.name) if cost.enabled else None
        with self.app.tracer.span("step", self.name, rows=chunk.n):
            lat = self._stats_mark(chunk.n)
            self._last_now = max(self._last_now, chunk.last_ts)
            with self._lock:
                step = self._packed_step_for(chunk.enc, chunk.capacity)
                with self._table_locks():
                    tstates = {t: self.app.tables[t].state
                               for t in self.table_deps}
                    (self.states, tstates, self._emitted_dev, out,
                     due) = step(self.states, tstates, self._emitted_dev,
                                 chunk.buf)
                    for t in self.table_deps:
                        self.app.tables[t].state = tstates[t]
            if lat is not None or probe is not None:
                # sampled branch only: the sync serializes the pipeline
                jax.block_until_ready(out.valid)
                if lat is not None:
                    lat.mark_out()
                if probe is not None:
                    probe.done(rows=chunk.n)
            if self._host_due_all and chunk.ts_min is not None:
                self._dispatch_output(out, chunk.last_ts)
                self._schedule(min(op.host_due_bound(chunk.ts_min)
                                   for op in self._timer_ops))
                return
            self._dispatch_output(out, chunk.last_ts,
                                  due=due if self._has_timers else None)

    def stats(self) -> dict:
        """Runtime counters (device-synced on read)."""
        with self._lock:  # vs restore_state rebinding the counter
            emitted = int(jax.device_get(self._emitted_dev))
        return {"emitted": emitted, "overflow": self.overflow_total()}

    # -- snapshot (SnapshotService state walk -> one device_get) ----------
    def snapshot_state(self) -> dict:
        with self._lock:
            snap = jax.device_get({"states": self.states,
                                   "emitted": self._emitted_dev})
            if self.rate_limiter is not None:
                snap["rate"] = self.rate_limiter.snapshot_state()
            return snap

    def restore_state(self, snap: dict) -> None:
        with self._lock:
            self.states = _fresh_device(snap["states"])
            self._emitted_dev = jnp.array(snap["emitted"], copy=True)
            self._sched_due = None
            if self.rate_limiter is not None and "rate" in snap:
                self.rate_limiter.restore_state(snap["rate"])

    def reschedule(self) -> None:
        """After restore: re-arm timers from the restored window states
        (the reference re-registers Schedulers on restore)."""
        if not self._has_timers:
            return
        with self._lock:  # restore_state rebinds the whole tuple
            states = self.states
        dues = [op.next_due(st) for op, st in zip(self.operators, states)
                if isinstance(op, WindowOp)]
        dues = [d for d in dues if d is not None]
        if dues:
            # one transfer for all window dues, not one sync per window
            self._schedule(min(int(d) for d in jax.device_get(dues)))

    def overflow_total(self) -> int:
        """Sum of overflow counters across operator states (windows etc.;
        the 'counted, never silent' contract). Walks nested state dicts
        — aggregator tables carry their own counters."""
        total = 0

        def walk(st):
            nonlocal total
            if isinstance(st, dict):
                for k, v in st.items():
                    if k == "overflow":
                        total += int(v)
                    else:
                        walk(v)
            elif isinstance(st, (tuple, list)):
                for v in st:
                    walk(v)

        with self._lock:  # vs restore_state rebinding mid-walk
            host = jax.device_get(self.states)
        walk(host)
        return total

    # -- runtime ---------------------------------------------------------
    @staticmethod
    def encode_chunks(schema: StreamSchema, events: list[Event],
                      max_cap: Optional[int] = None):
        """Yield (EventBatch, last_timestamp) bucketed device batches."""
        max_cap = max_cap or BATCH_BUCKETS[-1]
        for start in range(0, len(events), max_cap):
            chunk = events[start:start + max_cap]
            rows = [e.data for e in chunk]
            tss = [e.timestamp for e in chunk]
            kinds = [EXPIRED if e.is_expired else CURRENT for e in chunk]
            cap = bucket_capacity(len(chunk))
            yield (batch_from_rows(schema, rows, tss, cap, kinds),
                   chunk[-1].timestamp)

    def _qs(self):
        from .stats import QueryStats
        if self._qstats is None:
            self._qstats = QueryStats()
        return self._qstats

    def _lat_sample(self) -> bool:
        """DETAIL latency probes block_until_ready the step output, which
        serializes the async dispatch pipeline — so only every Nth chunk
        is measured (SIDDHI_TPU_LAT_EVERY, default 16; the first chunk
        always samples so short runs still report)."""
        n = self._lat_counter
        self._lat_counter = n + 1
        return n % self.app.lat_sample_every == 0

    def _stats_mark(self, n: int):
        """Ingest-boundary throughput (real event count) + DETAIL
        latency handle."""
        if self.app.stats_level <= 0:
            return None
        qs = self._qs()
        qs.throughput.mark(n)
        if self.app.stats_level >= 2 and self._lat_sample():
            qs.latency.mark_in()
            return qs.latency
        return None

    def _stats_lat(self):
        """DETAIL latency only (timer/internal batches: not traffic)."""
        if self.app.stats_level < 2 or not self._lat_sample():
            return None
        lat = self._qs().latency
        lat.mark_in()
        return lat

    def receive(self, events: list[Event]) -> None:
        dbg = self.app.debugger
        if dbg is not None:
            from .debugger import QueryTerminal
            dbg.check_break_point(self.name, QueryTerminal.IN, events)
        if self.app.stats_level > 0:
            self._qs().throughput.mark(len(events))
        for batch, last_ts in self.encode_chunks(self.in_schema, events,
                                                 self.max_step_capacity):
            self.process_batch(batch, last_ts)

    @staticmethod
    def split_batch(batch: EventBatch, cap: int):
        """Slice an oversized device batch into <=cap sub-batches (eager
        device slicing — used when device-to-device chaining hands a large
        batch to a capacity-capped query)."""
        B = batch.capacity
        for off in range(0, B, cap):
            yield jax.tree_util.tree_map(lambda x: x[off:off + cap], batch)

    def process_batch(self, batch: EventBatch, timestamp: int,
                      now: Optional[int] = None,
                      skip_due: bool = False) -> None:
        cap = self.max_step_capacity
        if cap is not None and batch.capacity > cap:
            for sub in self.split_batch(batch, cap):
                self.process_batch(sub, timestamp, now=now,
                                   skip_due=skip_due)
            return
        if self._fused_chain is not None:
            return self._fused_chain.process_batch(batch, timestamp,
                                                   now=now,
                                                   skip_due=skip_due)
        cost = self.app.cost
        probe = cost.probe("query", self.name) if cost.enabled else None
        with self.app.tracer.span("step", self.name,
                                  capacity=int(batch.capacity)):
            if now is None:
                now = self.app.current_time()
            lat = self._stats_lat()
            self._last_now = max(self._last_now, int(now))
            now_dev = jnp.asarray(now, dtype=jnp.int64)
            with self._lock:
                step = self._step_for(batch.capacity)
                with self._table_locks():
                    tstates = {t: self.app.tables[t].state
                               for t in self.table_deps}
                    (self.states, tstates, self._emitted_dev, out,
                     due) = step(self.states, tstates, self._emitted_dev,
                                 batch, now_dev)
                    for t in self.table_deps:
                        self.app.tables[t].state = tstates[t]
            if lat is not None or probe is not None:
                # sampled branch only: the sync serializes the pipeline
                jax.block_until_ready(out.valid)
                if lat is not None:
                    lat.mark_out()
                if probe is not None:
                    probe.done(rows=int(batch.capacity))
            self._dispatch_output(
                out, timestamp,
                due=due if (self._has_timers and not skip_due) else None)

    def _table_locks(self):
        stack = contextlib.ExitStack()
        for t in self.table_deps:  # sorted — consistent lock order
            stack.enter_context(self.app.tables[t].lock)
        return stack

    def set_rate_limiter(self, rl) -> None:
        """Install an output rate limiter: all row consumers (insert-into
        handlers + query/stream callbacks) see only what it emits.
        batch_callbacks stay a pre-limit device tap."""
        rl.emit = self._emit_limited
        rl.start(self.app)
        self.rate_limiter = rl
        # a limiter makes this query's hop unfusible — re-derive segments
        self.app._rebuild_fused_chains()

    def _emit_limited(self, timestamp: int, rows) -> None:
        for h in self.output_handlers:
            h.handle(timestamp, rows)
        self.callback_handler.handle(timestamp, rows)

    def _dispatch_output(self, out, timestamp: int, due=None) -> None:
        """Raw-batch observers, device-to-device chaining, timer
        scheduling, and (only when someone still needs rows) host decode +
        handler/callback delivery."""
        for cb in self.batch_callbacks:
            cb(out)
        # transfer + decode at most ONCE per emission: every delivery path
        # (debugger, rate limiter, handlers, callbacks) shares one host
        # copy and one decoded row list, so uuid() cells agree across
        # paths and no extra tunnel round-trips happen
        _host: list = []
        _decoded: list = []
        _current: list = []

        def current_once():
            # CURRENT-kind rewrite shared across ALL handlers of this
            # emission: one jitted dispatch per emitted batch, no matter
            # how many insert-into junctions the output fans out to
            if not _current:
                _current.append(_rewrite_current(out))
            return _current[0]

        def host_once():
            if not _host:
                _host.append(jax.device_get(out))
            return _host[0]

        def rows_once():
            if not _decoded:
                _decoded.append(rows_from_batch(self.out_schema.types,
                                                host_once()))
            return _decoded[0]

        dbg = self.app.debugger
        if dbg is not None:
            from .debugger import QueryTerminal
            if (self.name, QueryTerminal.OUT) in dbg._breakpoints:
                dbg.check_break_point(
                    self.name, QueryTerminal.OUT,
                    [Event(ts, vals, is_expired=(k == EXPIRED))
                     for ts, k, vals in rows_once()])
        if self.rate_limiter is not None:
            if due is not None:
                if _host:
                    due_host = jax.device_get(due)
                else:
                    out_host, due_host = jax.device_get((out, due))
                    _host.append(out_host)
                self._schedule(int(due_host))
            rows = rows_once()
            if rows:
                self.rate_limiter.process(timestamp, rows)
            return
        row_handlers = [h for h in self.output_handlers
                        if not h.handle_device_batch(
                            out, timestamp, current=current_once)]
        decode = bool(row_handlers or self.callback_handler.callbacks)
        if decode and due is not None:
            if _host:
                due_host = jax.device_get(due)
            else:
                out_host, due_host = jax.device_get((out, due))
                _host.append(out_host)
            self._schedule(int(due_host))
        elif decode:
            pass
        else:
            if due is not None:
                # NO sync here: device->host readback over the TPU tunnel
                # costs a full RTT (~70ms measured); start an async copy
                # and resolve the int right before the next clock advance
                # (app._resolve_dues) — by then the copy has landed
                self.app.defer_due(self, due)
            return
        out_rows = rows_once()
        if not out_rows:
            return
        out_rows = self._host_shape_rows(out_rows)
        # ingest->emit SLO mark (obs/slo.py): host rows for this query's
        # sinks/callbacks just materialized — the device_get above
        # already forced the sync, so the sample is honest. Fused
        # segments land here via the tail member (FusedChain delegates
        # its terminal delivery to tail._dispatch_output).
        slo = self.app.slo
        if slo is not None:
            slo.on_emit(self.name, rows=len(out_rows))
        for h in row_handlers:
            h.handle(timestamp, out_rows)
        self.callback_handler.handle(timestamp, out_rows)

    def _host_shape_rows(self, rows):
        """STRING order-by (+ its offset/limit) applied on decoded rows —
        the host edge of shape_output (batch_callbacks stay unordered,
        documented in ops/selector.compile_order_by)."""
        shape = getattr(self.operators[-1], "host_shape", None)
        if not shape:
            return rows
        order, offset, limit = shape
        for idx, direction in reversed(order):
            rows = sorted(rows,
                          key=lambda r: (r[2][idx] is None, r[2][idx]),
                          reverse=(direction == "desc"))
        if offset or limit:
            off = offset or 0
            rows = rows[off:off + limit] if limit is not None \
                else rows[off:]
        return rows

    # -- timers ----------------------------------------------------------
    def _schedule(self, due: int) -> None:
        if due >= int(POS_INF):
            return
        if due <= self._last_now and self._skip_past_dues \
                and self.app._columnar:
            # the event step that produced this due already processed
            # expiry/flush work up to its own clock — firing a timer for
            # an instant the step covered is a pure no-op dispatch
            # (windows expire at exact per-row points in-step). Ops that
            # genuinely need per-boundary catch-up (hopping) opt out via
            # needs_catchup.
            return
        if self._sched_due is not None and self._sched_due <= due:  # lint: disable=racy-attribute-read (arm-dedup heuristic only; a stale due costs one redundant no-op timer arm)
            return
        self._sched_due = due
        self.app.scheduler.notify_at(due, self._on_timer)

    def arm_host_timers(self, base_ms: int) -> None:
        """Schedule host-computed fires (cron windows) after base_ms."""
        for fn in self._host_sched:
            self._schedule(int(fn(base_ms)))

    def _on_timer(self, due: int) -> None:
        self._sched_due = None
        if not self.app.running:
            return
        now = max(due, self.app.current_time())
        # the TIMER row carries the ADVANCED clock, not the scheduled due:
        # window expiry compares buffered rows against the timer row's ts,
        # and the reference's playback clock has already advanced when a
        # timer fires — one fire drains every pending expiry (per-due rows
        # would re-arm a timer per expiry instant and cascade)
        if self._host_due_all and self.app._playback:
            # host-bounded timers: skip the device due readback entirely
            # and re-arm at now+1 — at most one (cheap, 16-row) timer
            # step per clock advance, zero tunnel round-trips
            self.process_batch(_timer_batch(self.in_schema, now), due,
                               now=now, skip_due=True)
            self._schedule(now + 1)
        else:
            self.process_batch(_timer_batch(self.in_schema, now), due,
                               now=now)
        if self._host_sched:
            self.arm_host_timers(due)


class FusedChain:
    """A fusible linear `insert into` segment [Q1 -> Q2 -> ... -> Qk]
    compiled into ONE jitted chain step
    (statesQ1..Qk, tstates, emittedQ1..Qk, batch, now) ->
    (states', tstates', emitted', out, dues) — a micro-batch traverses
    the whole segment in a single XLA program with the insert-into
    CURRENT-kind rewrite done inside the trace, instead of one jit
    dispatch plus three eager ops per hop.

    Eligibility is decided by SiddhiAppRuntime._fusible_next (see
    docs/performance.md); the HEAD query's process_batch/process_packed
    delegate here. Member queries keep their own per-query steps for
    everything else (their timers, direct sends to the intermediate
    streams), so fused and unfused execution interleave safely: every
    path updates `q.states` under `q._lock`, and the fused step takes
    the member locks in segment order before running."""

    def __init__(self, app: "SiddhiAppRuntime", queries: list,
                 schedule: Optional[list] = None):
        self.app = app
        self.queries = list(queries)
        self.head = self.queries[0]
        self.tail = self.queries[-1]
        self.name = "+".join(q.name for q in self.queries)
        self.table_deps = sorted({t for q in self.queries
                                  for t in q.table_deps})
        # execution schedule (plan/optimizer.py): member ops + per-member
        # emitted-count boundaries + hop rewrites. The optimizer's filter
        # pushdown hands a reordered schedule; None keeps declaration
        # order (bit-identical to the pre-schedule nested composition).
        from ..plan.optimizer import natural_schedule
        self.schedule = schedule or natural_schedule(self.queries)
        self._chain = self._make_chain()
        self._step: Optional[Callable] = None
        self._packed_steps: dict = {}

    def _make_chain(self):
        queries = self.queries
        schedule = self.schedule

        def chain(states, tstates, emitteds, batch, now):
            cur = batch
            new_states = [list(st) for st in states]
            new_emitted = list(emitteds)
            for entry in schedule:
                kind = entry[0]
                if kind == "op":
                    _, mi, oi = entry
                    op = queries[mi].operators[oi]
                    st = new_states[mi][oi]
                    with op_scope(type(op).__name__):
                        if op.needs_tables:
                            st, cur, tstates = op.step_tables(
                                st, cur, now, tstates)
                        else:
                            st, cur = op.step(st, cur, now)
                    new_states[mi][oi] = st
                elif kind == "count":
                    mi = entry[1]
                    new_emitted[mi] = emitteds[mi] + \
                        cur.count().astype(jnp.int64)
                else:  # insert-into hop, in-trace
                    cur = _as_current(cur)
            dues = []
            for mi, q in enumerate(queries):
                if q._has_timers:
                    ds = [op.next_due(st) for op, st in
                          zip(q.operators, new_states[mi])
                          if isinstance(op, WindowOp)]
                    ds = [d for d in ds if d is not None]
                    due = ds[0]
                    for d in ds[1:]:
                        due = jnp.minimum(due, d)
                else:
                    due = jnp.asarray(POS_INF)
                dues.append(due)
            return (tuple(tuple(s) for s in new_states), tstates,
                    tuple(new_emitted), cur, tuple(dues))

        return chain

    # -- locks -----------------------------------------------------------
    def _locks(self):
        stack = contextlib.ExitStack()
        for q in self.queries:  # segment order; no path takes them in
            stack.enter_context(q._lock)  # reverse, so no deadlock
        return stack

    def _table_locks(self):
        stack = contextlib.ExitStack()
        for t in self.table_deps:  # sorted — consistent lock order
            stack.enter_context(self.app.tables[t].lock)
        return stack

    # -- compile ---------------------------------------------------------
    def _step_for(self) -> Callable:
        if self._step is None:
            self._step = jax.jit(self._chain, **_donate(0, 1, 2))
        return self._step

    def _packed_step_for(self, enc: tuple, capacity: int) -> Callable:
        fn = self._packed_steps.get((enc, capacity))
        if fn is None:
            fn = _build_packed_step(self._chain, self.head.in_schema,
                                    enc, capacity,
                                    self.head.max_step_capacity,
                                    self.app._playback)
            self._packed_steps[(enc, capacity)] = fn
        return fn

    # -- runtime ---------------------------------------------------------
    def _run(self, step, *args):
        """Execute the fused step under segment + table locks and write
        every member query's state back (donated inputs are replaced
        before the locks release, so snapshot/restore and statistics —
        which take the same locks/barrier — always see live buffers)."""
        with self._locks():
            with self._table_locks():
                tstates = {t: self.app.tables[t].state
                           for t in self.table_deps}
                states = tuple(q.states for q in self.queries)
                emitted = tuple(q._emitted_dev for q in self.queries)
                states, tstates, emitted, out, dues = step(
                    states, tstates, emitted, *args)
                for t in self.table_deps:
                    self.app.tables[t].state = tstates[t]
            for q, st, em in zip(self.queries, states, emitted):
                q.states = st
                q._emitted_dev = em
        return out, dues

    def process_packed(self, chunk: PackedChunk) -> None:
        # ONE span per fused segment (the segment IS one XLA program);
        # member queries are named in args instead of per-hop spans —
        # and ONE cost center, for the same reason (obs/costmodel.py)
        cost = self.app.cost
        # cap rides the probe: per-capacity centers (chain/<n>@<cap>)
        # are the optimizer's chunk-size evidence (plan/optimizer.py)
        probe = cost.probe("chain", self.name, cap=chunk.capacity) \
            if cost.enabled else None
        with self.app.tracer.span("chain", self.name, rows=chunk.n,
                                  members=[q.name for q in self.queries]):
            lat = self.head._stats_mark(chunk.n)
            for q in self.queries:
                q._last_now = max(q._last_now, chunk.last_ts)
            out, dues = self._run(
                self._packed_step_for(chunk.enc, chunk.capacity),
                chunk.buf)
            if lat is not None or probe is not None:
                jax.block_until_ready(out.valid)
                if lat is not None:
                    lat.mark_out()
                if probe is not None:
                    probe.done(rows=chunk.n)
            self._schedule_dues(dues, chunk.ts_min)
            self.tail._dispatch_output(out, chunk.last_ts)

    def process_batch(self, batch: EventBatch, timestamp: int,
                      now: Optional[int] = None,
                      skip_due: bool = False) -> None:
        cost = self.app.cost
        probe = cost.probe("chain", self.name) if cost.enabled else None
        with self.app.tracer.span("chain", self.name,
                                  members=[q.name for q in self.queries]):
            if now is None:
                now = self.app.current_time()
            lat = self.head._stats_lat()
            for q in self.queries:
                q._last_now = max(q._last_now, int(now))
            now_dev = jnp.asarray(now, dtype=jnp.int64)
            out, dues = self._run(self._step_for(), batch, now_dev)
            if lat is not None or probe is not None:
                jax.block_until_ready(out.valid)
                if lat is not None:
                    lat.mark_out()
                if probe is not None:
                    probe.done(rows=int(batch.capacity))
            self._schedule_dues(dues, None, skip_head_due=skip_due)
            self.tail._dispatch_output(out, timestamp)

    def _schedule_dues(self, dues, ts_min,
                       skip_head_due: bool = False) -> None:
        """Per-member timer scheduling: host-bounded windows schedule
        with zero readbacks; device dues resolve asynchronously
        (app.defer_due) like the no-row-consumer single-query path."""
        for i, (q, due) in enumerate(zip(self.queries, dues)):
            if not q._has_timers or (skip_head_due and i == 0):
                continue
            if q._host_due_all and ts_min is not None:
                q._schedule(min(op.host_due_bound(ts_min)
                                for op in q._timer_ops))
            else:
                self.app.defer_due(q, due)


class StreamCallbackReceiver(Receiver):
    def __init__(self, callback: StreamCallback):
        self.callback = callback

    def receive(self, events):
        self.callback.receive(events)


class PatternStreamReceiver(Receiver):
    """Junction subscriber feeding one stream of a pattern query
    (= PatternMultiProcessStreamReceiver, .../state/receiver/*.java:29)."""

    supports_packed = True

    def __init__(self, runtime: "PatternQueryRuntime", stream_id: str):
        self.runtime = runtime
        self.stream_id = stream_id

    @property
    def max_step_capacity(self):
        return self.runtime.max_step_capacity

    def receive(self, events):
        self.runtime.process_stream_events(self.stream_id, events)

    def process_batch(self, batch, last_ts):
        self.runtime.process_pattern_batch(self.stream_id, batch, last_ts)

    def process_packed(self, chunk):
        self.runtime.process_pattern_packed(self.stream_id, chunk)


class PatternQueryRuntime(QueryRuntime):
    """Pattern/sequence query: the NFA engine feeds the selector chain.
    One receiver per distinct input stream; all share the pending-match
    table (reference: StateStreamRuntime + per-state processors).

    The base-class `states` tuple holds the selector operator states; the
    NFA pending table lives in `nfa_state`."""

    supports_packed = False  # consumes via PatternStreamReceivers only

    def __init__(self, name: str, engine: NfaEngine,
                 sel_ops: list[Operator], app: "SiddhiAppRuntime"):
        super().__init__(name, sel_ops, engine.match_schema, app)
        self.engine = engine
        self.nfa_state = engine.init_state()
        self._stream_steps: dict = {}
        self._timer_step: Optional[Callable] = None
        self._due_fn: Optional[Callable] = None
        self._arm_start_fn: Optional[Callable] = None

    def receive(self, events: list[Event]) -> None:
        raise RuntimeError(
            "pattern runtimes consume via per-stream PatternStreamReceivers")

    def overflow_total(self) -> int:
        """Include the NFA pending-table overflow counter."""
        total = super().overflow_total()
        return total + int(jax.device_get(self.nfa_state["overflow"]))

    def snapshot_state(self) -> dict:
        with self._lock:
            return jax.device_get({"states": self.states,
                                   "emitted": self._emitted_dev,
                                   "nfa": self.nfa_state})

    def restore_state(self, snap: dict) -> None:
        with self._lock:
            self.states = _fresh_device(snap["states"])
            self._emitted_dev = jnp.array(snap["emitted"], copy=True)
            self.nfa_state = _fresh_device(snap["nfa"])
            self._sched_due = None

    def reschedule(self) -> None:
        self._schedule_absent()

    def arm_start_deadlines(self, ts: int) -> None:
        """Base start-state absent deadlines at app start time
        (AbsentStreamPreStateProcessor.partitionCreated:291-308)."""
        with self._lock:
            if self._arm_start_fn is None:
                self._arm_start_fn = jax.jit(self.engine.arm_start)
            self.nfa_state = self._arm_start_fn(self.nfa_state,
                                                np.int64(ts))
        self._schedule_absent()

    # -- absent-pattern timers -------------------------------------------
    def _due_fn_for(self) -> Callable:
        if self._due_fn is None:
            self._due_fn = jax.jit(self.engine.next_due)
        return self._due_fn

    def _schedule_absent(self) -> None:
        """After a step: schedule a wakeup at the earliest live absent
        deadline (AbsentStreamPreStateProcessor's scheduler role)."""
        if not getattr(self.engine, "has_absent", False):
            return
        due = int(jax.device_get(self._due_fn_for()(self.nfa_state)))
        self._schedule(due)

    def _timer_step_for(self) -> Callable:
        """The absent-deadline timer step, built once and cached on the
        instance (the compile service AOT-warms it at start)."""
        if self._timer_step is None:
            tstep = self.engine.make_timer_step()
            sel_ops = self.operators

            def full(nfa_state, sel_states, emitted, now):
                nfa_state, match = tstep(nfa_state, now)
                new_sel = []
                for op, st in zip(sel_ops, sel_states):
                    st, match = op.step(st, match, now)
                    new_sel.append(st)
                emitted = emitted + match.count().astype(jnp.int64)
                return nfa_state, tuple(new_sel), emitted, match

            self._timer_step = jax.jit(full, **_donate(0, 1, 2))
        return self._timer_step

    def _on_timer(self, due: int) -> None:
        self._sched_due = None
        if not self.app.running:
            return
        cost = self.app.cost
        probe = cost.probe("pattern", f"{self.name}.timer") \
            if cost.enabled else None
        self._timer_step_for()
        with self._lock:
            (self.nfa_state, self.states, self._emitted_dev,
             out) = self._timer_step(self.nfa_state, self.states,
                                     self._emitted_dev, np.int64(due))
        if probe is not None:
            # sampled branch only: the sync serializes the pipeline
            jax.block_until_ready(out.valid)
            probe.done()
        self._dispatch_output(out, due)
        self._schedule_absent()

    def _step_for_stream(self, stream_id: str,
                         packed_key=None) -> Callable:
        key = (stream_id, packed_key)
        fn = self._stream_steps.get(key)
        if fn is None:
            nfa_step = self.engine.make_stream_step(stream_id)
            sel_ops = self.operators
            schema = self.app.schemas[stream_id]

            def run(nfa_state, sel_states, tstates, batch, now):
                nfa_state, match = nfa_step(nfa_state, batch, now)
                new_sel = []
                for op, st in zip(sel_ops, sel_states):
                    if op.needs_tables:
                        st, match, tstates = op.step_tables(st, match, now,
                                                            tstates)
                    else:
                        st, match = op.step(st, match, now)
                    new_sel.append(st)
                return nfa_state, tuple(new_sel), tstates, match

            if packed_key is not None:
                enc, capacity = packed_key

                def step(nfa_state, sel_states, tstates, emitted, buf):
                    batch, now = unpack_buffer(schema, enc, capacity, buf)
                    nfa_state, sel, tstates, match = run(
                        nfa_state, sel_states, tstates, batch, now)
                    emitted = emitted + match.count().astype(jnp.int64)
                    return nfa_state, sel, tstates, emitted, match
            else:
                def step(nfa_state, sel_states, tstates, emitted, batch,
                         now):
                    nfa_state, sel, tstates, match = run(
                        nfa_state, sel_states, tstates, batch, now)
                    emitted = emitted + match.count().astype(jnp.int64)
                    return nfa_state, sel, tstates, emitted, match
            fn = jax.jit(step, **_donate(0, 1, 2, 3))
            self._stream_steps[key] = fn
        return fn

    def process_pattern_packed(self, stream_id: str,
                               chunk: PackedChunk) -> None:
        cost = self.app.cost
        probe = cost.probe("pattern", f"{self.name}.{stream_id}") \
            if cost.enabled else None
        self._last_now = max(self._last_now, chunk.last_ts)
        with self._lock:
            step = self._step_for_stream(stream_id,
                                         (chunk.enc, chunk.capacity))
            with self._table_locks():
                tstates = {t: self.app.tables[t].state
                           for t in self.table_deps}
                (self.nfa_state, self.states, tstates, self._emitted_dev,
                 out) = step(self.nfa_state, self.states, tstates,
                             self._emitted_dev, chunk.buf)
                for t in self.table_deps:
                    self.app.tables[t].state = tstates[t]
        if probe is not None:
            # sampled branch only: the sync serializes the pipeline
            jax.block_until_ready(out.valid)
            probe.done(rows=chunk.n)
        self._dispatch_output(out, chunk.last_ts)
        self._schedule_absent()

    def process_stream_events(self, stream_id: str, events) -> None:
        schema = self.app.schemas[stream_id]
        for batch, last_ts in self.encode_chunks(schema, events,
                                                 self.max_step_capacity):
            self.process_pattern_batch(stream_id, batch, last_ts)

    def process_pattern_batch(self, stream_id: str, batch: EventBatch,
                              timestamp: int) -> None:
        cap = self.max_step_capacity
        if cap is not None and batch.capacity > cap:
            for sub in self.split_batch(batch, cap):
                self.process_pattern_batch(stream_id, sub, timestamp)
            return
        now_host = self.app.current_time()
        cost = self.app.cost
        probe = cost.probe("pattern", f"{self.name}.{stream_id}") \
            if cost.enabled else None
        self._last_now = max(self._last_now, int(now_host))
        now = jnp.asarray(now_host, dtype=jnp.int64)
        with self._lock:
            step = self._step_for_stream(stream_id)
            with self._table_locks():
                tstates = {t: self.app.tables[t].state
                           for t in self.table_deps}
                (self.nfa_state, self.states, tstates, self._emitted_dev,
                 out) = step(self.nfa_state, self.states, tstates,
                             self._emitted_dev, batch, now)
                for t in self.table_deps:
                    self.app.tables[t].state = tstates[t]
        if probe is not None:
            # sampled branch only: the sync serializes the pipeline
            jax.block_until_ready(out.valid)
            probe.done(rows=int(batch.capacity))
        self._dispatch_output(out, timestamp)
        # arm the scheduler at the earliest live absent deadline so the
        # pattern fires on clock advance even when no further events come
        # (AbsentStreamPreStateProcessor's scheduler role); costs one
        # device readback per step, only for has_absent engines
        self._schedule_absent()


class JoinStreamReceiver(Receiver):
    supports_packed = True

    def __init__(self, runtime: "JoinQueryRuntime", side: str):
        self.runtime = runtime
        self.side = side

    @property
    def max_step_capacity(self):
        return self.runtime.max_step_capacity

    def receive(self, events):
        self.runtime.process_side_events(self.side, events)

    def process_batch(self, batch, last_ts):
        self.runtime.process_side_batch(self.side, batch, last_ts)

    def process_packed(self, chunk):
        self.runtime.process_side_packed(self.side, chunk)


class JoinQueryRuntime(QueryRuntime):
    """Two-stream windowed join (JoinStreamRuntime + cross-wired
    JoinProcessors in the reference). Each side runs [filters..., window];
    the window output crosses the opposite window's findable buffer."""

    supports_packed = False  # consumes via JoinStreamReceivers only

    def __init__(self, name: str, left_ops, right_ops, crosses,
                 sel_ops, in_schemas, out_schema_override, app,
                 side_tables=None):
        super().__init__(name, sel_ops, out_schema_override, app)
        self.out_schema = sel_ops[-1].out_schema if sel_ops \
            else out_schema_override
        self.side_ops = {"L": left_ops, "R": right_ops}
        self.crosses = crosses  # {"L": JoinCross|None, "R": ...}
        self.in_schemas = in_schemas  # {"L": schema, "R": schema}
        self.side_tables = side_tables or {}  # {"L"/"R": TableRuntime}
        self.side_states = {
            s: tuple(op.init_state() for op in ops)
            for s, ops in self.side_ops.items()}
        self.table_deps = sorted(set(self.table_deps) | {
            t.table_id for t in self.side_tables.values()})
        self._side_steps: dict = {}
        self._join_timer_ops = _timer_windows(
            [op for ops in self.side_ops.values() for op in ops])
        self._has_timers = bool(self._join_timer_ops)
        self._join_host_due = _all_host_due(self._join_timer_ops)
        self._overflow_dev = jnp.int64(0)
        if any(getattr(op, "sort_heavy", False)
               for ops in self.side_ops.values() for op in ops):
            self.max_step_capacity = SORT_HEAVY_CAP

    def receive(self, events):
        raise RuntimeError("join runtimes consume via JoinStreamReceivers")

    @property
    def overflow(self) -> int:
        """Total join pairs dropped at the join_cap limit so far."""
        return int(jax.device_get(self._overflow_dev))

    def overflow_total(self) -> int:
        """Selector + both side-chains' window overflow + join-cap drops."""
        total = super().overflow_total()
        for states in jax.device_get(self.side_states).values():
            for st in states:
                if isinstance(st, dict) and "overflow" in st:
                    total += int(st["overflow"])
        return total + self.overflow

    def snapshot_state(self) -> dict:
        with self._lock:
            return jax.device_get({"states": self.states,
                                   "emitted": self._emitted_dev,
                                   "sides": self.side_states,
                                   "join_overflow": self._overflow_dev})

    def restore_state(self, snap: dict) -> None:
        with self._lock:
            self.states = _fresh_device(snap["states"])
            self._emitted_dev = jnp.array(snap["emitted"], copy=True)
            self.side_states = _fresh_device(snap["sides"])
            self._overflow_dev = jnp.array(snap["join_overflow"],
                                           copy=True)
            self._sched_due = None

    def reschedule(self) -> None:
        if not self._has_timers:
            return
        dues = []
        for side, ops in self.side_ops.items():
            for op, st in zip(ops, self.side_states[side]):
                if isinstance(op, WindowOp):
                    d = op.next_due(st)
                    if d is not None:
                        dues.append(d)
        if dues:
            # both sides' dues come back in one pytree transfer
            self._schedule(min(int(d) for d in jax.device_get(dues)))

    def _step_for_side(self, side: str, packed_key=None) -> Callable:
        fn = self._side_steps.get((side, packed_key))
        if fn is None:
            my_ops = self.side_ops[side]
            opp = "R" if side == "L" else "L"
            opp_window = self.side_ops[opp][-1] \
                if self.side_ops[opp] else None  # table side: no window
            cross = self.crosses[side]
            sel_ops = self.operators
            has_timers = self._has_timers

            opp_table = self.side_tables.get(opp)
            # captured at compile time: columnar apps coalesce timer
            # fires, so crosses gate pairs by opposite-row liveness
            gate_alive = self.app._columnar

            def step(my_states, opp_states, sel_states, tstates, batch,
                     now):
                new_my = []
                for op, st in zip(my_ops, my_states):
                    st, batch = op.step(st, batch, now)
                    new_my.append(st)
                if cross is not None:
                    if opp_table is not None:
                        opp_buf = opp_table.buffer(
                            tstates[opp_table.table_id])
                    else:
                        opp_buf = opp_window.findable_buffer(opp_states[-1])
                    joined, lost = cross.cross(batch, opp_buf,
                                               gate_alive=gate_alive)
                else:
                    cap = 16
                    sch = combined_schema("#j", self.in_schemas["L"],
                                          self.in_schemas["R"])
                    joined = EventBatch.empty(sch, cap)
                    lost = jnp.int64(0)
                new_sel = []
                for op, st in zip(sel_ops, sel_states):
                    if op.needs_tables:
                        st, joined, tstates = op.step_tables(
                            st, joined, now, tstates)
                    else:
                        st, joined = op.step(st, joined, now)
                    new_sel.append(st)
                if has_timers:
                    dues = [op.next_due(st) for op, st in
                            zip(my_ops, new_my) if isinstance(op, WindowOp)]
                    dues = [d for d in dues if d is not None]
                    due = dues[0] if dues else jnp.asarray(POS_INF)
                    for d in dues[1:]:
                        due = jnp.minimum(due, d)
                else:
                    due = jnp.asarray(POS_INF)
                return (tuple(new_my), tuple(new_sel), tstates, joined,
                        lost, due)

            if packed_key is not None:
                my_schema = self.in_schemas[side]
                enc, capacity = packed_key

                def pstep(my_states, opp_states, sel_states, tstates,
                          emitted, buf):
                    batch, now = unpack_buffer(my_schema, enc, capacity,
                                               buf)
                    my, sel, tstates, joined, lost, due = step(
                        my_states, opp_states, sel_states, tstates, batch,
                        now)
                    emitted = emitted + joined.count().astype(jnp.int64)
                    return my, sel, tstates, emitted, joined, lost, due

                # opp_states (arg 1) is read-only and NOT returned — the
                # opposite side keeps referencing it, so never donate it
                fn = jax.jit(pstep, **_donate(0, 2, 3, 4))
            else:
                def ustep(my_states, opp_states, sel_states, tstates,
                          emitted, batch, now):
                    my, sel, tstates, joined, lost, due = step(
                        my_states, opp_states, sel_states, tstates, batch,
                        now)
                    emitted = emitted + joined.count().astype(jnp.int64)
                    return my, sel, tstates, emitted, joined, lost, due

                fn = jax.jit(ustep, **_donate(0, 2, 3, 4))
            self._side_steps[(side, packed_key)] = fn
        return fn

    _SIDE_NAMES = {"L": "left", "R": "right"}

    def _side_center(self, side: str) -> str:
        """Cost-center name for one side step: ``<q>.left[probe]`` —
        the kernel suffix makes the persisted cost table name WHICH
        join kernel was measured (the planner's cost-table consultation
        reads it back; tools/profile_report.py asserts it)."""
        nm = f"{self.name}.{self._SIDE_NAMES[side]}"
        cross = self.crosses.get(side)
        if cross is not None:
            nm += f"[{cross.kernel}]"
        return nm

    def process_side_packed(self, side: str, chunk: PackedChunk) -> None:
        opp = "R" if side == "L" else "L"
        cost = self.app.cost
        probe = cost.probe("join", self._side_center(side)) \
            if cost.enabled else None
        self._last_now = max(self._last_now, chunk.last_ts)
        with self._lock:
            step = self._step_for_side(side, (chunk.enc, chunk.capacity))
            with self._table_locks():
                tstates = {t: self.app.tables[t].state
                           for t in self.table_deps}
                (my, sel, tstates, self._emitted_dev, out, lost,
                 due) = step(self.side_states[side], self.side_states[opp],
                             self.states, tstates, self._emitted_dev,
                             chunk.buf)
                for t in self.table_deps:
                    self.app.tables[t].state = tstates[t]
            self.side_states[side] = my
            self.states = sel
            self._overflow_dev = self._overflow_dev + lost
        if probe is not None:
            # sampled branch only: the sync serializes the pipeline
            jax.block_until_ready(out.valid)
            probe.done(rows=chunk.n)
        if self._join_host_due and chunk.ts_min is not None:
            self._dispatch_output(out, chunk.last_ts)
            self._schedule(min(op.host_due_bound(chunk.ts_min)
                               for op in self._join_timer_ops))
            return
        self._dispatch_output(out, chunk.last_ts,
                              due=due if self._has_timers else None)

    def process_side_events(self, side: str, events) -> None:
        for batch, last_ts in self.encode_chunks(self.in_schemas[side],
                                                 events,
                                                 self.max_step_capacity):
            self.process_side_batch(side, batch, last_ts)

    def process_side_batch(self, side: str, batch: EventBatch,
                           timestamp: int, now: Optional[int] = None,
                           skip_due: bool = False) -> None:
        cap = self.max_step_capacity
        if cap is not None and batch.capacity > cap:
            for sub in self.split_batch(batch, cap):
                self.process_side_batch(side, sub, timestamp, now=now,
                                        skip_due=skip_due)
            return
        if batch.kind is not None and not bool(np.any(
                np.asarray(batch.kind) == TIMER)):
            # only EVENT steps advance the due-subsumption clock — timer
            # fires must not suppress their own follow-up dues
            self._last_now = max(self._last_now, int(timestamp))
        if now is None:
            now = self.app.current_time()
        cost = self.app.cost
        probe = cost.probe("join", self._side_center(side)) \
            if cost.enabled else None
        now_dev = jnp.asarray(now, dtype=jnp.int64)
        opp = "R" if side == "L" else "L"
        with self._lock:
            step = self._step_for_side(side)
            with self._table_locks():
                tstates = {t: self.app.tables[t].state
                           for t in self.table_deps}
                (my, sel, tstates, self._emitted_dev, out, lost,
                 due) = step(self.side_states[side], self.side_states[opp],
                             self.states, tstates, self._emitted_dev,
                             batch, now_dev)
                for t in self.table_deps:
                    self.app.tables[t].state = tstates[t]
            self.side_states[side] = my
            self.states = sel
            # join pairs beyond join_cap are dropped by JoinCross.cross —
            # counted here, never silent (join.py design contract)
            self._overflow_dev = self._overflow_dev + lost
        if probe is not None:
            # sampled branch only: the sync serializes the pipeline
            jax.block_until_ready(out.valid)
            probe.done(rows=int(batch.capacity))
        self._dispatch_output(
            out, timestamp,
            due=due if (self._has_timers and not skip_due) else None)

    def _on_timer(self, due: int) -> None:
        self._sched_due = None
        if not self.app.running:
            return
        now = max(due, self.app.current_time())
        skip = self._join_host_due and self.app._playback
        for side in ("L", "R"):
            # TIMER rows carry the advanced clock (see QueryRuntime
            # ._on_timer): one fire drains all pending window expiries
            batch = _timer_batch(self.in_schemas[side], now)
            self.process_side_batch(side, batch, due, now=now,
                                    skip_due=skip)
        if skip:
            self._schedule(now + 1)


def _timer_batch(schema: StreamSchema, due: int) -> EventBatch:
    from .event import TIMER
    cap = BATCH_BUCKETS[0]
    batch = batch_from_rows(schema, [], [], cap)
    ts = np.zeros((cap,), dtype=np.int64)
    ts[0] = due
    kind = np.zeros((cap,), dtype=np.int32)
    kind[0] = TIMER
    valid = np.zeros((cap,), dtype=np.bool_)
    valid[0] = True
    return EventBatch(ts=ts, cols=batch.cols, nulls=batch.nulls,
                      kind=kind, valid=valid)


class SiddhiAppRuntime:
    """Per-app container: junctions, query runtimes, handlers, lifecycle
    (reference SiddhiAppRuntimeImpl: start/shutdown :440-655,
    persist/restore :677-755)."""

    def __init__(self, app_ast: A.SiddhiApp, manager=None,
                 partition_mesh=None):
        self.ast = app_ast
        self.manager = manager
        self.name = app_ast.name or f"app_{id(self):x}"
        self.junctions: dict[str, StreamJunction] = {}
        self.schemas: dict[str, StreamSchema] = {}
        self.input_handlers: dict[str, InputHandler] = {}
        self.queries: dict[str, QueryRuntime] = {}
        # planner's per-join-side kernel picks: {"<q>.left": {"kernel":
        # "grid"|"probe", "reason": ...}} — statistics()['compile']
        self._join_kernels: dict[str, dict] = {}
        # plan-optimizer decision record (plan/optimizer.py build_plan,
        # set at start()): rides ExplainReport.decisions['optimizer']
        # so every transformation flip moves plan_hash
        self._opt_decisions: Optional[dict] = None
        # per-stream bounded-lateness reorder buffers keyed by stream id
        # (resilience/ordering.py, wired by the planner from @watermark
        # annotations); non-empty => watermark mode: the virtual clock
        # advances on watermark progress and never regresses
        self._reorder: dict = {}
        self.tables: dict[str, TableRuntime] = {}
        self.record_tables: dict = {}  # tid -> RecordTableRuntime (@Store)
        self.named_windows: dict[str, QueryRuntime] = {}
        self.triggers: dict[str, TriggerRuntime] = {}
        self.sources: list = []
        self.sinks: list = []
        self.aggregations: dict = {}  # id -> AggregationRuntime
        self.partitions: dict = {}  # name -> PartitionBlockRuntime
        # jax.sharding.Mesh: when set, partition blocks shard their key-slot
        # axis over the mesh's first axis via the PARTITION_STATE_RULES
        # regex table (parallel/partition.py + parallel/sharding.py);
        # `mesh` is the forward-facing name, partition_mesh the original
        self.partition_mesh = partition_mesh
        self.mesh = partition_mesh
        self.running = False
        self._playback = False
        self._playback_time: Optional[int] = None
        # set once columnar ingest (send_arrays) is used: timer dues
        # subsumed by event steps are then skipped (_schedule) — the
        # row path keeps per-boundary timer fidelity
        self._columnar = False
        # @app:playback(idle.time, increment): auto-advance parameters
        self._playback_idle_ms: Optional[int] = None
        self._playback_increment_ms: Optional[int] = None
        self._last_ingest_wall = 0.0
        self._idle_thread: Optional[threading.Thread] = None
        self._local_store = None  # fallback store when manager is None
        self._local_error_store = None  # ditto for the error store
        # per-stream junction/sink error counters (core/stats.py) — always
        # on; junctions get a reference through junction_for
        from .stats import StreamErrorStats
        self.error_stats = StreamErrorStats()
        self._cron_armed = False
        self._due_pending: list = []
        self._due_lock = threading.Lock()
        self.stats_level = 0      # OFF; see core/stats.py
        # DETAIL latency probe sampling stride (QueryRuntime._lat_sample)
        self.lat_sample_every = max(
            1, int(os.environ.get("SIDDHI_TPU_LAT_EVERY", "16") or 16))
        self.debugger = None
        # app-wide quiesce barrier (= ThreadBarrier): ingest and wall-clock
        # timer dispatch hold it; snapshot/restore take it exclusively
        self.barrier = threading.RLock()
        self.scheduler = Scheduler(playback=False, barrier=self.barrier)
        self.scheduler.resolve_hook = self._resolve_dues
        # observability (siddhi_tpu/obs/): metrics registry + chunk-span
        # tracer. The registry fills at COLLECTION time (scrape /
        # reporter tick / statistics() call) via _collect_observability;
        # the per-chunk path records only into the existing host-side
        # trackers, so BASIC-level metrics stay sync-free.
        from ..obs.costmodel import CostProfiler
        from ..obs.metrics import MetricsRegistry
        from ..obs.tracing import ChunkTracer
        self.metrics = MetricsRegistry()
        self.tracer = ChunkTracer()
        # sampled per-step cost attribution (obs/costmodel.py): default
        # OFF — every dispatch site pays one attribute check; enabled
        # via cost_start() / SIDDHI_TPU_COST_PROFILE=1 it syncs every
        # SIDDHI_TPU_COST_EVERY'th chunk per step to measure wall ms
        self.cost = CostProfiler(self)
        # SLO engine (obs/slo.py): None unless @app:slo is configured —
        # the disabled path costs one attribute check per dispatch site
        # (the CostProfiler contract); the Planner wires it below
        self.slo = None
        self.metrics.register_collector(
            lambda: self._collect_observability()[0])
        self._checkpoint_supervisor = None  # wired by CheckpointSupervisor
        self._stats_reporter_conf = None    # (reporter, interval_ms, path)
        self._reporter = None
        self._skip_start_warmup = False     # set for async-warm deploys
        Planner(self).plan()
        # AOT compile service (core/compile.py): warmup() lowers and
        # compiles every step program in parallel; start() triggers it
        # for the buckets configured via SIDDHI_TPU_WARM_BUCKETS
        from .compile import CompileService
        self.compile_service = CompileService(self)
        # flight-recorder identity: every artifact this app's recorder
        # dumps carries {app, pool, plan_hash} so a PAGE dump is
        # attributable to a plan change (obs/slo.py; the hash is
        # computed lazily at dump time — dumps are rare, plans can
        # change on live graph edits)
        if self.slo is not None and self.slo.recorder is not None:
            self.slo.recorder.identity_fn = lambda: {
                "app": self.name, "plan_hash": self.plan_hash()}
        self.scheduler.playback = self._playback
        # start-state absent deadlines are based at app start, not the
        # first event (AbsentStreamPreStateProcessor.partitionCreated);
        # under playback the base is the first observed virtual tick
        self._unarmed_patterns = [
            q for q in self.queries.values()
            if getattr(getattr(q, "engine", None),
                       "needs_start_arm", False)]
        # record which queries compiled device reads against a @Cache
        # table — losing cache completeness must be surfaced to them
        # (the device join cannot fall back to the store mid-jit)
        for q in self.queries.values():
            for t in getattr(q, "table_deps", ()):
                rt = self.record_tables.get(t)
                if rt is not None and hasattr(rt, "compiled_readers"):
                    rt.compiled_readers.add(q.name)

    # -- time ------------------------------------------------------------
    def current_time(self) -> int:
        # the playback clock is ingest-thread-owned; background writers
        # (idle advance, restore) serialize against each other via the
        # barrier, and a clock read one write stale is by-design here
        if self._playback and self._playback_time is not None:  # lint: disable=racy-attribute-read (ingest-thread-owned clock)
            return self._playback_time  # lint: disable=racy-attribute-read (ingest-thread-owned clock)
        return int(time.time() * 1000)

    def on_ingest(self, stream_id: str, events: list[Event]) -> None:
        if events:
            self.on_ingest_ts(events[-1].timestamp, events[0].timestamp)

    def defer_due(self, q, due_arr) -> None:
        """Queue a device-resident timer due for async host resolution
        (avoids one tunnel round-trip per step)."""
        try:
            due_arr.copy_to_host_async()
        except Exception:  # noqa: BLE001 — platform-dependent API
            pass
        with self._due_lock:
            self._due_pending.append((q, due_arr))

    def _resolve_dues(self) -> None:
        if not self._due_pending:
            return
        with self._due_lock:
            pending, self._due_pending = self._due_pending, []
        # the copy_to_host_async above staged these; collect them in one
        # transfer instead of a sync per queued due
        dues = jax.device_get([arr for _, arr in pending])
        for (q, _), due in zip(pending, dues):
            q._schedule(int(due))

    def on_ingest_ts(self, last_ts: int,
                     first_ts: Optional[int] = None) -> None:
        """Advance the playback clock (and due timers) to an ingested
        timestamp — shared by the row and columnar ingest paths."""
        self._resolve_dues()
        if self._playback:
            if self._unarmed_patterns:
                base = first_ts if first_ts is not None else last_ts
                pats, self._unarmed_patterns = self._unarmed_patterns, []
                for q in pats:
                    q.arm_start_deadlines(base)
            if not self._cron_armed:
                # playback cron schedules anchor at the first event time
                self._cron_armed = True
                base = (first_ts if first_ts is not None else last_ts) - 1
                self._arm_cron(base)
            if self._reorder and self._playback_time is not None:  # lint: disable=racy-attribute-read (ingest-thread-owned clock)
                # watermark mode: PROCESS-policy late events and replay
                # re-injection carry old timestamps — the watermark
                # clock never regresses
                last_ts = max(last_ts, self._playback_time)  # lint: disable=racy-attribute-read (ingest-thread-owned clock)
            self._playback_time = last_ts
            self._last_ingest_wall = time.monotonic()
            self.scheduler.advance_to(last_ts)

    def on_ingest_span(self, first_ts: int, last_ts: int) -> None:
        """Columnar-chunk variant: fire only timers due STRICTLY BEFORE
        the chunk's span, then advance the clock to its end. In-span
        window expiry happens inside the chunk's own jitted step (exact
        per-row expiry points), so pre-firing intermediate timers would
        only add tunnel dispatches; the caller runs a catch-up
        advance_to(last_ts) after publishing."""
        self._resolve_dues()
        if self._playback:
            if self._unarmed_patterns:
                pats, self._unarmed_patterns = self._unarmed_patterns, []
                for q in pats:
                    q.arm_start_deadlines(first_ts)
            if not self._cron_armed:
                self._cron_armed = True
                self._arm_cron(first_ts - 1)
            self.scheduler.advance_to(first_ts - 1)
            if self._reorder and self._playback_time is not None:  # lint: disable=racy-attribute-read (ingest-thread-owned clock)
                last_ts = max(last_ts, self._playback_time)  # lint: disable=racy-attribute-read (ingest-thread-owned clock)
            self._playback_time = last_ts
            self._last_ingest_wall = time.monotonic()

    def on_event_time(self, target_ms: int) -> None:
        """Watermark-driven clock (resilience/ordering.py): advance the
        virtual clock and due timers monotonically to the global
        watermark, so windows/joins/patterns fire on watermark progress
        instead of raw arrival — and never backwards. Idempotent for
        targets at or behind the current clock."""
        self._resolve_dues()
        if not self._playback:
            return
        cur = self._playback_time
        if cur is not None and target_ms <= cur:
            return
        if self._unarmed_patterns:
            pats, self._unarmed_patterns = self._unarmed_patterns, []
            for q in pats:
                q.arm_start_deadlines(target_ms)
        if not self._cron_armed:
            self._cron_armed = True
            self._arm_cron(target_ms - 1)
        self._playback_time = target_ms
        self._last_ingest_wall = time.monotonic()
        self.scheduler.advance_to(target_ms)

    def global_watermark(self) -> Optional[int]:
        """Min watermark across watermarked streams (streams that have
        not observed any event yet do not hold the watermark back — the
        idle-source caveat, docs/resilience.md). None before any
        watermarked stream has seen traffic."""
        wms = [b.watermark for b in self._reorder.values()
               if b.watermark is not None]
        return min(wms) if wms else None

    def flush_watermarks(self, final: bool = False) -> None:
        """Release reorder-buffered events (resilience/ordering.py): up
        to each stream's current watermark, or EVERYTHING when
        ``final`` (the shutdown path). A final flush also advances the
        clock to the observed event-time frontier so trailing window
        boundaries and pattern deadlines fire exactly where an
        unbuffered run's would."""
        if not self._reorder:
            return
        with self.barrier:
            for buf in self._reorder.values():
                buf.flush(final=final)
            if final:
                fronts = [b.max_ts for b in self._reorder.values()
                          if b.max_ts is not None]
                if fronts:
                    self.on_event_time(max(fronts))
            else:
                wm = self.global_watermark()
                if wm is not None:
                    self.on_event_time(wm)

    def _arm_cron(self, base_ms: int) -> None:
        for q in self.queries.values():
            if getattr(q, "_host_sched", None):
                q.arm_host_timers(base_ms)
        for t in self.triggers.values():
            t.arm(base_ms)

    # -- on-demand (store) queries (OnDemandQueryParser.java:87) ----------
    def query(self, q):
        """Execute an on-demand query string/AST against tables / named
        windows; returns result rows (SELECT) or the affected-row count
        (writes)."""
        from .ondemand import OnDemandExecutor
        with self.barrier:
            return OnDemandExecutor(self).execute(q)

    # -- chain fusion (docs/performance.md) -------------------------------
    def _fusible_next(self, q) -> Optional["QueryRuntime"]:
        return self._fusible_next_info(q)[0]

    def _fusible_next_info(self, q) -> tuple:
        """``(next, reason)``: the single downstream QueryRuntime the
        hop q -> next can fuse into (reason None), or (None, slug)
        naming WHY the hop broke the chain — the machine-readable
        fusion evidence explain surfaces (obs/explain.py). Fusible
        means: q is a plain single-stream query whose ONLY output is
        `insert into` a synchronous junction with exactly one
        subscriber that is itself a plain QueryRuntime taking device
        batches — no row-level consumers (query callbacks, rate
        limiters, device taps) on q, no @Async/@OnError machinery on
        the intermediate stream, and no sort-heavy capacity cap
        downstream (capped queries re-split batches on the host, which
        a fused trace cannot do)."""
        if type(q) is not QueryRuntime:
            return None, "not-plain-query"
        if q.rate_limiter is not None:
            return None, "rate-limiter"
        if q.callback_handler.callbacks:
            return None, "row-callbacks"
        if q.batch_callbacks:
            return None, "device-taps"
        if len(q.output_handlers) != 1:
            return None, "fan-out" if len(q.output_handlers) > 1 \
                else "no-insert-into-output"
        h = q.output_handlers[0]
        if type(h) is not InsertIntoStreamHandler:
            return None, "non-stream-output"
        j = h.junction
        if j.async_conf is not None:
            return None, "async-junction"
        if j.fault_junction is not None or j.on_error_action != "LOG":
            return None, "on-error-machinery"
        if len(j.receivers) != 1:
            return None, "multi-subscriber" if len(j.receivers) > 1 \
                else "no-subscriber"
        r = j.receivers[0]
        if type(r) is not QueryRuntime:
            return None, "downstream-not-plain-query"
        if r is q:
            return None, "self-loop"
        if r.max_step_capacity is not None:
            return None, "downstream-capacity-capped"
        return r, None

    def _fusion_enabled(self) -> bool:
        """Whether segment derivation runs at all (explain evidence):
        off under SIDDHI_TPU_FUSE=0 or an attached debugger."""
        return os.environ.get("SIDDHI_TPU_FUSE", "1") != "0" \
            and self.debugger is None

    def _build_fused_chains(self) -> None:
        """Derive the executable plan over the junction graph
        (plan/optimizer.py build_plan): maximal fusible linear segments
        compile into FusedChains on their head queries, fan-out
        junctions into FanoutGroups, with CSE prefix sharing, filter
        pushdown and cost-driven selection per the SIDDHI_TPU_OPT*
        switches. Cleared and re-derived whenever the graph changes
        (new subscriber, callback, rate limiter, debugger).
        SIDDHI_TPU_FUSE=0 keeps per-query dispatch; attaching a
        debugger does too (row breakpoints need per-query delivery)."""
        for q in self.queries.values():
            if type(q) is QueryRuntime:
                q._fused_chain = None
                q._fanout_group = None
                q.preferred_ingest_cap = None
        for j in self.junctions.values():
            j.fanout = None
        self._opt_decisions = None
        if not self._fusion_enabled():
            self._opt_decisions = {"enabled": False,
                                   "cause": "fusion-disabled"}
            return
        from ..plan.optimizer import build_plan
        build_plan(self)

    def _rebuild_fused_chains(self) -> None:
        if self.running:
            with self.barrier:  # quiesce in-flight fused dispatch
                self._build_fused_chains()

    # -- wiring ----------------------------------------------------------
    def junction_for(self, stream_id: str,
                     schema: Optional[StreamSchema] = None) -> StreamJunction:
        j = self.junctions.get(stream_id)
        if j is None:
            if schema is None:
                raise CompileError(f"undefined stream '{stream_id}'")
            j = StreamJunction(stream_id, schema)
            j.app = self
            j.error_stats = self.error_stats
            self.junctions[stream_id] = j
            self.schemas[stream_id] = schema
        elif schema is not None and schema.types != j.schema.types:
            raise CompileError(
                f"output schema {list(schema.types)} does not match existing "
                f"definition of stream '{stream_id}' {list(j.schema.types)} "
                "(reference rejects mismatched insert-into at deploy time)")
        return j

    # -- public API (= SiddhiAppRuntime) ---------------------------------
    def get_input_handler(self, stream_id: str) -> InputHandler:
        h = self.input_handlers.get(stream_id)
        if h is None:
            raise KeyError(f"no input handler for stream '{stream_id}' "
                           f"(defined streams: {list(self.input_handlers)})")
        return h

    def add_callback(self, target, callback) -> None:
        """StreamCallback on a stream id, or QueryCallback on a query name."""
        if isinstance(callback, QueryCallback):
            q = self.queries.get(target)
            if q is None:
                raise KeyError(f"no query named '{target}'")
            q.callback_handler.callbacks.append(callback)
            self._rebuild_fused_chains()
        else:
            j = self.junctions.get(target)
            if j is None:
                raise KeyError(f"no stream '{target}' to subscribe to")
            j.subscribe(StreamCallbackReceiver(callback))
            self._rebuild_fused_chains()

    def set_statistics_level(self, level) -> None:
        """OFF/BASIC/DETAIL at runtime
        (SiddhiAppRuntimeImpl.setStatisticsLevel:859)."""
        from .stats import parse_level
        self.stats_level = parse_level(level) \
            if isinstance(level, str) else int(level)

    def statistics(self) -> dict:
        """Per-query throughput/latency/memory/overflow report
        (util/statistics trackers) — a VIEW over the metrics registry's
        collection walk (obs/metrics.py): ``GET /metrics``, periodic
        reporters and bench dumps read the same numbers as dotted
        gauges (docs/observability.md)."""
        return self._collect_observability()[1]

    def _collect_observability(self) -> tuple[dict, dict]:
        """ONE walk over the runtime, shared by every observability
        surface. Returns ``(flat, report)``: ``flat`` is the registry
        snapshot of dotted metrics (``siddhi.<app>.query.<q>.emitted``,
        ``siddhi.<app>.stream.<sid>.throughput``, ...) and ``report``
        is the nested ``statistics()`` view. Device reads are batched
        into single pytree transfers under the app barrier; this never
        runs on the per-chunk path."""
        from .stats import pytree_nbytes
        p = f"siddhi.{self.name}"
        flat: dict = {}
        report: dict = {}
        # barrier: with donated state buffers a concurrent step would
        # invalidate the arrays mid-read; the barrier quiesces ingest and
        # timer dispatch for the walk (same guard snapshot() uses)
        with self.barrier:
            states_host = jax.device_get(
                {n: q.states for n, q in self.queries.items()
                 if hasattr(q, "states")})
            stats_host = {n: dict(q.stats())
                          for n, q in self.queries.items()
                          if hasattr(q, "stats")}
        for n, q in self.queries.items():
            entry = stats_host.get(n, {})
            qs = getattr(q, "_qstats", None)
            if qs is not None:
                eps = qs.throughput.events_per_sec()
                if eps is not None:
                    entry["throughput_eps"] = round(eps, 1)
                lat = qs.latency.summary()
                if lat is not None:
                    entry["latency"] = lat
            if n in states_host:
                entry["state_bytes"] = pytree_nbytes(states_host[n])
            report[n] = entry
            base = f"{p}.query.{n}"
            for key, metric in (("emitted", "emitted"),
                                ("overflow", "overflow"),
                                ("throughput_eps", "throughput"),
                                ("state_bytes", "state.bytes")):
                v = entry.get(key)
                if isinstance(v, (int, float)):
                    flat[f"{base}.{metric}"] = v
            for k, v in (entry.get("latency") or {}).items():
                flat[f"{base}.latency.{k}"] = v
        # per-stream gauges: ingest throughput (host boundary, free),
        # @Async queue depth/backpressure, junction error counters
        for sid, j in self.junctions.items():
            sbase = f"{p}.stream.{sid}"
            tput = getattr(j, "throughput", None)
            if tput is not None:
                flat[f"{sbase}.events"] = tput.count
                eps = tput.events_per_sec()
                if eps is not None:
                    flat[f"{sbase}.throughput"] = round(eps, 1)
            if j.async_conf is not None and j._queue is not None:
                flat[f"{sbase}.async.depth"] = j._queue.qsize()
                flat[f"{sbase}.async.pending"] = j._pending
                flat[f"{sbase}.async.capacity"] = j.async_conf[0]
            # event-time robustness gauges (resilience/ordering.py):
            # watermark position/lag, reorder-buffer depth and the
            # late/dropped/duplicate/forced counters
            buf = self._reorder.get(sid)
            if buf is not None:
                wm = buf.watermark
                flat[f"{sbase}.watermark"] = -1 if wm is None else int(wm)
                flat[f"{sbase}.watermark.lag_ms"] = buf.lag_ms
                flat[f"{sbase}.reorder.depth"] = buf.depth
                for k, v in buf.counters.items():
                    flat[f"{sbase}.reorder.{k}"] = v
            # ingest-path zero-copy + pipeline-overlap counters
            # (core/stream.py InputHandler.ingest_stats): coercion
            # copies and encode/device overlap are regressions/wins the
            # bench gates on (tools/bench_diff.py)
            h = self.input_handlers.get(sid)
            ing = h.ingest_stats() if h is not None else None
            if ing:
                report.setdefault("ingest", {})[sid] = ing
                for k, v in ing.items():
                    if isinstance(v, (int, float)):
                        flat[f"{sbase}.ingest.{k}"] = v
        if self._reorder:
            report["reorder"] = {
                sid: {"watermark": b.watermark, "lag_ms": b.lag_ms,
                      "depth": b.depth, **b.counters}
                for sid, b in self._reorder.items()}
        errors = self.error_stats.snapshot()
        if errors:
            report["stream_errors"] = errors
            for sid, c in errors.items():
                flat[f"{p}.stream.{sid}.errors"] = c
        for tid, rt in self.record_tables.items():
            if hasattr(rt, "cache_complete"):
                report[f"store:{tid}"] = {
                    "cache_complete": bool(rt.cache_complete),
                    "completeness_losses": rt.completeness_losses,
                    "compiled_readers": sorted(rt.compiled_readers),
                }
                flat[f"{p}.store.{tid}.cache_complete"] = \
                    int(bool(rt.cache_complete))
                flat[f"{p}.store.{tid}.completeness_losses"] = \
                    rt.completeness_losses
        # error-store backlog (resilience): events awaiting replay
        try:
            flat[f"{p}.errorstore.backlog"] = \
                self._error_store().size(self.name)
        except Exception:  # noqa: BLE001 — store backends may be remote
            pass
        # checkpoint freshness (resilience/supervisor.py), when supervised
        sup = self._checkpoint_supervisor
        if sup is not None:
            flat[f"{p}.checkpoint.count"] = sup.checkpoints
            flat[f"{p}.checkpoint.failures"] = sup.failures
            if sup.last_checkpoint_wall is not None:
                flat[f"{p}.checkpoint.age_ms"] = round(
                    (time.time() - sup.last_checkpoint_wall) * 1000.0, 1)
        # scheduler timer backlog / lag
        flat[f"{p}.scheduler.pending"] = self.scheduler.pending()
        flat[f"{p}.scheduler.lag_ms"] = \
            self.scheduler.lag_ms(self.current_time())
        # mesh placement (multi-chip partition execution): which devices
        # carry how many key slots, as a `device=` labeled gauge family
        if self.mesh is not None and self.partitions:
            axis = self.mesh.axis_names[0]
            n = int(self.mesh.shape[axis])
            slots_per_dev = [0] * n
            mesh_rep = {"axis": axis, "n_devices": n, "partitions": {}}
            for name, blk in self.partitions.items():
                mesh_rep["partitions"][name] = {
                    "slots": blk.K, "slots_per_device": blk.K // n}
                for d in range(n):
                    slots_per_dev[d] += blk.K // n
            for d in range(n):
                self.metrics.labeled_gauge(
                    f"{p}.mesh.slots_placed", {"device": str(d)},
                    dotted=f"{p}.mesh.device.{d}.slots_placed",
                    help="partition key slots placed on one mesh "
                    "device").set(slots_per_dev[d])
            report["mesh"] = mesh_rep
            flat[f"{p}.mesh.n_devices"] = n
        # AOT compile telemetry (once a warmup ran OR the static program
        # auditor stored its summary): program count, compile wall ms,
        # persistent-cache hits/misses; DETAIL level adds the per-step
        # timing list (view only)
        comp: dict = {}
        if self.compile_service.warmups or self.compile_service.audit:
            comp = self.compile_service.summary(
                detail=self.stats_level >= 2)
            for k in ("warmups", "programs", "compile_ms", "cache_hits",
                      "cache_misses"):
                flat[f"{p}.compile.{k}"] = comp[k]
        if self._join_kernels:
            # the planner's grid-vs-probe picks per join side, with the
            # reason (env override / equi heuristic / cost-table
            # evidence) — docs/performance.md "join kernels"
            comp = {**comp, "join_kernels": {
                k: dict(v) for k, v in sorted(self._join_kernels.items())}}
        if comp:
            report["compile"] = comp
        # sampled per-step cost attribution (obs/costmodel.py): the
        # step_ms histograms live natively in the registry; the ranked
        # rollup rides the statistics() view like 'compile'. Also shown
        # when the optimizer's staleness guard dropped centers at load
        # (stale evidence in costs.json — counted, never silent)
        if self.cost.samples or (self.cost.stale_centers or 0) > 0:
            report["cost"] = self.cost.report()
        # SLO view (obs/slo.py): ingest->emit latency scopes, burn-rate
        # states and saturation signals; labeled p99/burn/state gauge
        # families land in the registry for /metrics
        if self.slo is not None:
            sat = self._slo_saturation()
            report["slo"] = self.slo.evaluate(saturation=sat)
            self.slo.publish(self.metrics, f"{p}.slo")
            for k, v in sat.items():
                if isinstance(v, (int, float)):
                    flat[f"{p}.saturation.{k}"] = v
        flat[f"{p}.app.running"] = int(self.running)
        flat[f"{p}.app.ready"] = int(self.ready)
        return flat, report

    def explain(self, live: bool = True) -> dict:
        """The full plan-explain document (obs/explain.py,
        docs/observability.md "Explain"): junction dataflow graph,
        every planner decision with its machine-readable reason
        (fusion segments + break causes, join kernel picks + evidence,
        window compaction variant, watermark/late-policy config, SLO
        objectives, mesh placement per state leaf), the AOT program
        inventory, and live edge annotations. ``plan_hash`` is stable
        across deploys of the same plan; assembly compiles nothing and
        reads nothing off-device (tested in tests/test_explain.py)."""
        from ..obs.explain import ExplainReport
        return ExplainReport.from_runtime(self, live=live).as_dict()

    def plan_hash(self) -> str:
        """Stable content hash of the compiled plan (decisions + graph
        only, never live stats). Stamped into flight-recorder artifacts
        so a PAGE dump is attributable to a plan change."""
        from ..obs.explain import (compute_plan_hash, runtime_decisions,
                                   runtime_graph)
        return compute_plan_hash(runtime_graph(self),
                                 runtime_decisions(self))

    def slo_report(self) -> Optional[dict]:
        """The SLO/burn-rate view on its own (``GET /siddhi/slo``);
        None when no ``@app:slo`` objective is configured."""
        if self.slo is None:
            return None
        return self.slo.evaluate(saturation=self._slo_saturation())

    def _slo_saturation(self) -> dict:
        """Host-side pressure signals for the SLO report and flight
        recorder: timer/scheduler lag, @Async queue depth, watermark
        lag (event-time apps), error-store backlog. No device reads."""
        sat: dict = {
            "scheduler_pending": self.scheduler.pending(),
            "scheduler_lag_ms": self.scheduler.lag_ms(
                self.current_time()),
        }
        depths = [j._queue.qsize() for j in self.junctions.values()
                  if j.async_conf is not None and j._queue is not None]
        if depths:
            sat["async_depth_max"] = max(depths)
        if self._reorder:
            sat["watermark_lag_ms_max"] = max(
                b.lag_ms for b in self._reorder.values())
            sat["reorder_depth_total"] = sum(
                b.depth for b in self._reorder.values())
        try:
            sat["errorstore_backlog"] = self._error_store().size(self.name)
        except Exception:  # noqa: BLE001 — store backends may be remote
            pass
        return sat

    def debug(self):
        """Attach a step debugger (SiddhiAppRuntimeImpl.debug():657)."""
        from .debugger import SiddhiDebugger
        self.debugger = SiddhiDebugger(self)
        # row breakpoints need per-query delivery — drop fused segments
        self._build_fused_chains()
        return self.debugger

    # -- AOT compile (core/compile.py, docs/compile_cache.md) -------------
    def warmup(self, buckets=None, samples=None, workers=None) -> dict:
        """Ahead-of-time compile every step program for the given ingest
        buckets (default: SIDDHI_TPU_WARM_BUCKETS; with no buckets
        configured only the cap-16 timer-batch shapes compile).
        Lowering/compiling runs concurrently on a thread pool — XLA
        releases the GIL — so wall time is the slowest single compile,
        not the sum. `samples` maps stream ids to (ts, cols) arrays so
        packed steps compile for the encoding real traffic settles on.
        Returns telemetry: programs, compile_ms, cache_hits/misses,
        per-step timings (also surfaced via statistics()['compile'])."""
        if not self.running:
            # segments must exist before enumeration so the warmed steps
            # are the ones traffic will dispatch
            self._build_fused_chains()
        return self.compile_service.warmup(buckets=buckets,
                                           samples=samples,
                                           workers=workers)

    def audit_programs(self, buckets=None, samples=None, **kw) -> dict:
        """Static audit of every step program warmup() would compile
        (analysis/programs.py): abstract-trace each spec and verify
        donation aliasing, host-callback freedom, dtype stability and
        the @app:cap(program.mb=) budget — ZERO executions, zero device
        work, zero new compiles. The summary is stored on the compile
        service and rides statistics()['compile']['audit'] and the
        explain report's programs section (never hashed)."""
        from ..analysis.programs import audit_runtime
        return audit_runtime(self, buckets=buckets,
                             samples=samples, **kw).summary()

    def warmup_async(self, buckets=None, samples=None, workers=None):
        """warmup() on a daemon thread; readiness (`self.ready`,
        service ``GET /ready``) flips False before this returns and
        True when the compiles land — deploys return immediately while
        the load balancer holds traffic (docs/observability.md)."""
        if not self.running:
            self._build_fused_chains()
        return self.compile_service.warmup_async(
            buckets=buckets, samples=samples, workers=workers)

    @property
    def ready(self) -> bool:
        """Load-balancer readiness: running AND no AOT warmup in
        flight (core/compile.py)."""
        return self.running and self.compile_service.ready

    def _maybe_aot_warmup(self) -> None:
        if self._skip_start_warmup:
            # an async warmup was (or will be) launched by the deployer
            # (core/service.py): don't also compile inline
            return
        from .compile import warm_buckets_from_env
        buckets = warm_buckets_from_env()
        if buckets:
            self.compile_service.warmup(buckets=buckets)

    # -- tracing / profiling (siddhi_tpu/obs/, docs/observability.md) -----
    def trace_start(self) -> None:
        """Start recording chunk spans (ingest -> junction -> step ->
        sink) into the tracer ring buffer."""
        self.tracer.start()

    def trace_stop(self) -> None:
        self.tracer.stop()

    def trace_export(self, path: str) -> str:
        """Write buffered chunk spans as Chrome ``trace_event`` JSON
        (chrome://tracing / Perfetto loadable), timestamp-ordered and —
        when the cost profiler has samples — annotated with measured
        per-step device time (``cost_ms_per_event`` etc. in span args);
        returns ``path``."""
        return self.tracer.export(
            path, annotations=self.cost.trace_annotations())

    # -- cost profiling (obs/costmodel.py, docs/observability.md) ---------
    def cost_start(self, every: Optional[int] = None) -> None:
        """Enable sampled per-step cost attribution: every Nth chunk per
        step is timed synchronously (``block_until_ready`` on the
        sampled branch only — the same serialization caveat as DETAIL
        latency probes). Zero jit-option changes: compile-cache keys are
        identical with profiling on or off."""
        self.cost.start(every=every)

    def cost_stop(self) -> None:
        self.cost.stop()

    def cost_report(self) -> dict:
        """Ranked per-step cost table (ms/event, share of total,
        queue-depth trend -> bottleneck verdict) from the sampled
        timings accumulated since ``cost_start()``."""
        return self.cost.report()

    def cost_save(self, path: Optional[str] = None) -> str:
        """Persist the measured cost table into
        ``<SIDDHI_TPU_CACHE_DIR>/costs.json`` (merge-on-write; the DAG
        optimizer's planned input). Centers from renamed/deleted plan
        units are pruned on save (``_cost_center_valid``) so the
        optimizer never feeds on stale evidence. Returns the path
        written."""
        return self.cost.save(path)

    def _cost_center_valid(self, key: str) -> bool:
        """Whether a persisted cost-center key names a unit of THIS
        app's current plan — the save-time pruning predicate and the
        ``load_costs_for`` staleness guard (obs/costmodel.py). Keys may
        carry a per-capacity ``@<cap>`` suffix; unknown kinds are kept
        (forward compatibility — costs are advisory)."""
        base = key.split("@", 1)[0]
        kind, _, name = base.partition("/")
        if kind == "query":
            return name in self.queries or any(
                wq.name == name for wq in self.named_windows.values())
        if kind == "chain":
            parts = name.split("+")
            return len(parts) > 1 and all(p in self.queries
                                          for p in parts)
        if kind == "fanout":
            return name in self.junctions
        if kind in ("join", "pattern"):
            return name.split(".", 1)[0] in self.queries
        if kind == "partition":
            return name in self.partitions
        return True

    def profile(self, path: str):
        """Context manager capturing a device profile of the enclosed
        block via ``jax.profiler.start_trace/stop_trace``::

            with rt.profile('/tmp/prof'):
                handler.send_arrays(ts, cols)
        """
        from ..obs.profiler import profile
        return profile(path)

    def _start_reporter(self) -> None:
        """Launch the @app:statistics periodic reporter, if configured."""
        if self._stats_reporter_conf is None or self.stats_level <= 0 \
                or self._reporter is not None:
            return
        from ..obs.reporters import build_reporter
        name, interval_ms, path = self._stats_reporter_conf
        self._reporter = build_reporter(self, name, interval_ms,
                                        path=path).start()

    def _stop_reporter(self) -> None:
        if self._reporter is not None:
            self._reporter.stop()
            self._reporter = None

    def start(self) -> None:
        self.running = True
        self._build_fused_chains()
        # compile every step program for the configured ingest buckets
        # BEFORE sources connect: traffic arriving the moment the app
        # deploys hits ready executables instead of a serial lazy
        # compile queue (north star: start in seconds, not minutes)
        self._maybe_aot_warmup()
        self._start_reporter()
        self.scheduler.start()
        self._start_record_tables()
        for s in self.sources:
            s.connect_with_retry()
        for s in self.sinks:
            s.connect()
        if not self._playback:
            self._arm_cron(self.current_time())
            if self._unarmed_patterns:
                now = self.current_time()
                pats, self._unarmed_patterns = self._unarmed_patterns, []
                for q in pats:
                    q.arm_start_deadlines(now)
        elif self._playback_idle_ms is not None:
            # @app:playback(idle.time, increment): a wall-clock watcher
            # advances the virtual clock by `increment` whenever no
            # events arrive for `idle.time`
            # (EventTimeBasedMillisTimestampGenerator's scheduled task)
            def idle_advance():
                idle_s = self._playback_idle_ms / 1000.0
                while self.running:
                    time.sleep(idle_s)
                    if not self.running:
                        return
                    with self.barrier:
                        if self._playback_time is None:
                            continue
                        idle_for = time.monotonic() - self._last_ingest_wall
                        if idle_for < idle_s:
                            continue
                        nxt = self._playback_time + \
                            self._playback_increment_ms
                        self._playback_time = nxt
                        self.scheduler.advance_to(nxt)

            self._idle_thread = threading.Thread(
                target=idle_advance, name="playback-idle", daemon=True)
            self._idle_thread.start()

    def _start_record_tables(self) -> None:
        from .store import CacheTableRuntime
        for rt in self.record_tables.values():
            rt.store.connect()
            if isinstance(rt, CacheTableRuntime):
                rt.now_fn = self.current_time  # event-time in playback
                now = self.current_time()
                rt.preload(now)
                interval = getattr(rt, "purge_interval_ms", None)
                if interval:
                    def fire(due, rt=rt, interval=interval):
                        if not self.running:
                            return
                        rt.purge_expired(due)
                        self.scheduler.notify_at(due + interval, fire)
                    self.scheduler.notify_at(now + interval, fire)

    def start_without_sources(self) -> None:
        """Lifecycle split (SiddhiAppRuntimeImpl.startWithoutSources
        :495): run queries but keep sources disconnected."""
        self.running = True
        self._build_fused_chains()
        self._maybe_aot_warmup()
        self._start_reporter()
        self.scheduler.start()
        self._start_record_tables()
        if not self._playback:
            self._arm_cron(self.current_time())

    def start_sources(self) -> None:
        for s in self.sources:
            s.connect_with_retry()
        for s in self.sinks:
            s.connect()

    # -- checkpoint / restore (SiddhiAppRuntimeImpl.java:677-755) ---------
    def _persistence_store(self):
        from .persistence import InMemoryPersistenceStore
        if self.manager is not None:
            if self.manager.persistence_store is None:
                self.manager.persistence_store = InMemoryPersistenceStore()
            return self.manager.persistence_store
        if self._local_store is None:
            self._local_store = InMemoryPersistenceStore()
        return self._local_store

    def _error_store(self):
        """The app's error store (resilience/errorstore.py): the
        manager's shared store when one is registered (survives app
        restarts, like the persistence store), else a runtime-local
        in-memory fallback."""
        from ..resilience.errorstore import InMemoryErrorStore
        if self.manager is not None:
            if getattr(self.manager, "error_store", None) is None:
                self.manager.error_store = InMemoryErrorStore()
            return self.manager.error_store
        if self._local_error_store is None:
            self._local_error_store = InMemoryErrorStore()
        return self._local_error_store

    def replay_error_store(self) -> int:
        """Re-inject the error-store backlog through the normal
        junctions (at-least-once); returns events replayed."""
        from ..resilience.errorstore import replay
        return replay(self, self._error_store())

    def snapshot(self) -> bytes:
        """Full state snapshot as bytes (SnapshotService.fullSnapshot).
        Every query/table/partition state is a pytree of device arrays —
        one device_get each, then pickle (see core/persistence.py).
        The app barrier quiesces ingest + timers for the whole walk so
        chained queries are captured consistently (the reference's
        ThreadBarrier in SnapshotService.java:99-100)."""
        from .persistence import dump_strings, serialize
        with self.barrier:
            return self._snapshot_locked(dump_strings, serialize)

    def _snapshot_locked(self, dump_strings, serialize) -> bytes:
        payload = {
            "app": self.name,
            "playback_time": self._playback_time,
            "queries": {n: q.snapshot_state()
                        for n, q in self.queries.items()
                        if hasattr(q, "snapshot_state")},
            "windows": {n: w.snapshot_state()
                        for n, w in self.named_windows.items()},
            "tables": jax.device_get(
                {tid: t.state for tid, t in self.tables.items()}),
            "partitions": {n: b.snapshot_state()
                           for n, b in self.partitions.items()},
            "aggregations": {n: a.snapshot_state()
                             for n, a in self.aggregations.items()},
            # reorder-buffered events are accepted-but-unreleased state:
            # a crash between checkpoint and flush must not lose them
            "reorder": {sid: b.snapshot_state()
                        for sid, b in self._reorder.items()},
            "strings": dump_strings(),
        }
        return serialize(payload)

    def restore(self, data: bytes) -> None:
        """Restore a snapshot() payload bit-exact and re-arm timers."""
        from .persistence import deserialize, load_strings
        with self.barrier:
            self._restore_locked(deserialize(data), load_strings)

    def _restore_locked(self, payload, load_strings) -> None:
        load_strings(payload["strings"])
        self._playback_time = payload["playback_time"]
        for n, snap in payload["queries"].items():
            q = self.queries.get(n)
            if q is None or not hasattr(q, "restore_state"):
                continue
            q.restore_state(snap)
        for n, snap in payload.get("windows", {}).items():
            w = self.named_windows.get(n)
            if w is not None:
                w.restore_state(snap)
        for tid, tstate in payload["tables"].items():
            if tid in self.tables:
                # fresh buffers: table states feed donated step args
                self.tables[tid].state = _fresh_device(tstate)
        for n, snap in payload["partitions"].items():
            if n in self.partitions:
                self.partitions[n].restore_state(snap)
        for n, snap in payload.get("aggregations", {}).items():
            if n in self.aggregations:
                self.aggregations[n].restore_state(snap)
        for sid, snap in payload.get("reorder", {}).items():
            buf = self._reorder.get(sid)
            if buf is not None:
                buf.restore_state(snap)
        for q in self.queries.values():
            if hasattr(q, "reschedule"):
                q.reschedule()
        for w in self.named_windows.values():
            w.reschedule()
        for b in self.partitions.values():
            b.reschedule()

    def persist(self) -> str:
        """Snapshot to the manager's persistence store; returns the
        revision id. Sources pause around the capture
        (SiddhiAppRuntimeImpl.persist:677-693)."""
        from .persistence import new_revision
        store = self._persistence_store()
        rev = new_revision(self.name)
        for s in self.sources:
            s.pause()
        try:
            # drain @Async buffers so queued events land in the snapshot
            for j in self.junctions.values():
                if j.async_conf is not None:
                    j.flush_async()
            store.save(self.name, rev, self.snapshot())
        finally:
            for s in self.sources:
                s.resume()
        return rev

    def restore_revision(self, revision: str) -> None:
        store = self._persistence_store()
        data = store.load(self.name, revision)
        if data is None:
            raise KeyError(f"no revision '{revision}' for app "
                           f"'{self.name}'")
        self.restore(data)

    def restore_last_revision(self) -> Optional[str]:
        store = self._persistence_store()
        rev = store.get_last_revision(self.name)
        if rev is None:
            return None
        self.restore_revision(rev)
        return rev

    def clear_all_revisions(self) -> None:
        self._persistence_store().clear_all_revisions(self.name)

    # camelCase aliases mirroring the reference API surface
    restoreRevision = restore_revision
    restoreLastRevision = restore_last_revision
    clearAllRevisions = clear_all_revisions

    def shutdown(self) -> None:
        self.running = False  # reject new sends before draining
        self._stop_reporter()
        if self._reorder:
            # release everything still held in reorder buffers so an
            # accepted event is never silently lost at shutdown
            try:
                self.flush_watermarks(final=True)
            except Exception:  # noqa: BLE001 — shutdown must finish
                logging.getLogger("siddhi_tpu.runtime").exception(
                    "app '%s': reorder-buffer final flush failed",
                    self.name)
        flush_errors = []
        for j in self.junctions.values():
            if j.async_conf is not None:
                try:
                    j.flush_async()
                except Exception as e:  # noqa: BLE001 — shutdown must finish
                    flush_errors.append((j.stream_id, e))
                finally:
                    j.stop_async()
        if flush_errors:
            logging.getLogger("siddhi_tpu.runtime").error(
                "app '%s': async streams did not drain cleanly on "
                "shutdown: %s", self.name, flush_errors)
        for h in self.input_handlers.values():
            h.close()  # join ingest pipeline workers
        self._resolve_dues()
        for s in self.sources:
            s.disconnect()
        for s in self.sinks:
            s.disconnect()
        self.scheduler.shutdown()
        for rt in self.record_tables.values():
            rt.store.disconnect()
        for q in self.queries.values():
            if hasattr(q, "_sched_due") and isinstance(
                    getattr(q, "_sched_due"), (int, type(None))):
                q._sched_due = None
        for blk in self.partitions.values():
            for qn in blk._sched_due:
                blk._sched_due[qn] = None


class Planner:
    """AST -> runtime graph (= SiddhiAppParser + QueryParser +
    SingleInputStreamParser + SelectorParser + OutputParser)."""

    DEFAULT_TIME_CAP = 4096

    def __init__(self, app: SiddhiAppRuntime):
        self.app = app
        self.ast = app.ast
        from .extension import build_function_table
        self.functions = build_function_table(app)
        mgr = app.manager
        self.extensions = {k.lower(): v for k, v in
                           (getattr(mgr, "extensions", {}) or {}).items()} \
            if mgr is not None else {}

    DEFAULT_TABLE_CAP = 8192

    def plan(self) -> None:
        app, ast = self.app, self.ast
        # 1. defined streams -> junctions + input handlers
        for sid, sd in ast.stream_definitions.items():
            schema = StreamSchema(sid, tuple(
                Attribute(a.name, a.type) for a in sd.attributes))
            j = app.junction_for(sid, schema)
            app.input_handlers[sid] = InputHandler(sid, j, app)
            asy = A.find_annotation(sd.annotations, "Async")
            if asy is not None:
                # @Async(buffer.size, workers, batch.size.max)
                # (StreamJunction.java:101-131; batch.size.max is the
                # reference's latency/throughput dial, ours too)
                def async_int(key, default):
                    v = asy.element(key)
                    if v is None:
                        return default
                    try:
                        n = int(v)
                    except ValueError:
                        n = 0
                    if n <= 0:
                        raise CompileError(
                            f"stream '{sid}': @Async {key}='{v}' must be "
                            "a positive integer")
                    return n

                buf = async_int("buffer.size", 1024)
                batch_max = async_int("batch.size.max", buf)
                j.enable_async(app, buf, batch_max)
            oe = A.find_annotation(sd.annotations, "OnError")
            if oe is not None:
                action = (oe.element("action") or "LOG").upper()
                if action not in ("LOG", "STREAM", "STORE"):
                    # the static validator rejects this at parse time;
                    # planner backstop for validate=False / built ASTs
                    raise CompileError(
                        f"stream '{sid}': unknown @OnError action "
                        f"'{action}' (expected LOG, STREAM or STORE)")
                j.on_error_action = action
                if action == "STREAM":
                    # shadow fault stream !sid: original attrs + _error
                    fschema = StreamSchema("!" + sid, schema.attributes + (
                        Attribute("_error", AttrType.STRING),))
                    j.fault_junction = app.junction_for("!" + sid, fschema)
        # 1a2. event-time watermarks + bounded-lateness reorder buffers
        # (resilience/ordering.py; docs/resilience.md). Validated at
        # parse time by the `watermark-config` plan rule — this is the
        # planner backstop for validate=False / hand-built ASTs.
        self.plan_watermarks()
        # 1b. defined tables (@PrimaryKey -> upsert semantics);
        # @Store tables become host-side record tables, with an optional
        # device-resident @Cache front registered under the table id so
        # joins/filters run on-device against the cache (core/store.py)
        from .store import CacheTableRuntime, build_record_table
        for tid, td in ast.table_definitions.items():
            schema = StreamSchema(tid, tuple(
                Attribute(a.name, a.type) for a in td.attributes))
            sa = A.find_annotation(td.annotations, "Store")
            if sa is not None:
                rt = build_record_table(tid, schema, sa, self.extensions)
                app.record_tables[tid] = rt
                if isinstance(rt, CacheTableRuntime):
                    app.tables[tid] = rt.cache
                continue
            pk = []
            pka = A.find_annotation(td.annotations, "PrimaryKey")
            if pka is not None:
                for nm in pka.positional or list(pka.elements.values()):
                    pk.append(schema.index_of(nm.strip("'\"")))
            idxs = []
            ia = A.find_annotation(td.annotations, "Index")
            if ia is not None:
                for nm in ia.positional or list(ia.elements.values()):
                    idxs.append(schema.index_of(nm.strip("'\"")))
            cap_a = A.find_annotation(td.annotations, "cap")
            tcap = int(cap_a.element()) if cap_a is not None \
                else self.DEFAULT_TABLE_CAP
            app.tables[tid] = TableRuntime(tid, schema,
                                           capacity=tcap,
                                           pk_indices=pk,
                                           index_indices=idxs)
        # 1c. named windows: one shared window instance per definition
        # (window/Window.java:65); queries consume from its junction,
        # insert-into feeds the instance
        for wid, wd in ast.window_definitions.items():
            schema = StreamSchema(wid, tuple(
                Attribute(a.name, a.type) for a in wd.attributes))
            fo = wd.window
            if fo is None:
                raise CompileError(f"window '{wid}' needs a window type")
            h = A.WindowHandler(namespace=fo.namespace, name=fo.name,
                                parameters=fo.parameters)
            op = self.make_window(h, schema, expired_enabled=True)
            wq = QueryRuntime(f"__window__{wid}", [op], schema, app)
            out_j = app.junction_for(wid, schema)
            wq.output_handlers.append(
                WindowPublishHandler(out_j, wd.output_event_type))
            app.named_windows[wid] = wq
        # 1c2. incremental aggregations (AggregationParser.java:93)
        from .aggregation import AggregationRuntime
        for aid, ad in ast.aggregation_definitions.items():
            sid = ad.input.stream_id
            schema = app.schemas.get(sid)
            if schema is None:
                raise CompileError(
                    f"aggregation '{aid}': undefined stream '{sid}'")
            ar = AggregationRuntime(app, ad, schema)
            app.junctions[sid].subscribe(ar)
            app.aggregations[aid] = ar
        # 1d. triggers: scheduled event publishers into stream <tid>
        for tid, td in ast.trigger_definitions.items():
            schema = StreamSchema(tid, (
                Attribute("triggered_time", AttrType.LONG),))
            tj = app.junction_for(tid, schema)
            app.triggers[tid] = TriggerRuntime(app, td, tj)
        # @app:statistics(level, reporter, interval, file)
        # (SiddhiAppParser.java:116-141: level + Dropwizard reporter
        # config; statics validated at parse time by plan_rules
        # `statistics-reporter`/`statistics-interval`, planner backstop
        # here for validate=False / hand-built ASTs)
        sa = A.find_annotation(ast.annotations, "statistics")
        if sa is not None:
            from .stats import parse_level
            from ..obs.reporters import DEFAULT_INTERVAL_MS, REPORTER_NAMES
            config_keys = ("reporter", "interval", "file")
            lvl = sa.element("level")
            if lvl is None and sa.positional:
                lvl = sa.positional[0]
            if lvl is None and len(sa.elements) == 1 and not any(
                    k.lower() in config_keys for k in sa.elements):
                lvl = next(iter(sa.elements.values()))
            app.stats_level = parse_level(lvl or "BASIC")
            rep = sa.element("reporter")
            interval = sa.element("interval")
            if rep is not None:
                rname = rep.strip("'\"").lower()
                if rname not in REPORTER_NAMES:
                    raise CompileError(
                        f"unknown @app:statistics reporter '{rep}' "
                        f"(expected one of {', '.join(REPORTER_NAMES)})")
            elif interval is not None:
                rname = "console"  # interval alone: reference default
            else:
                rname = None
            if rname is not None:
                ms = _time_str_ms(interval, "@app:statistics interval") \
                    if interval is not None else DEFAULT_INTERVAL_MS
                app._stats_reporter_conf = (rname, ms, sa.element("file"))
        # @app:slo(p99=..., target=..., window=..., fast=..., every=...)
        # -> ingest->emit latency objective + burn-rate states
        # (obs/slo.py; validated at parse time by the `slo-config` plan
        # rule — planner backstop for validate=False / hand-built ASTs)
        slo_ann = A.find_annotation(ast.annotations, "slo")
        if slo_ann is not None:
            from ..obs.slo import (FlightRecorder, SLOEngine,
                                   config_from_annotation)
            try:
                objective = config_from_annotation(slo_ann)
            except ValueError as e:
                raise CompileError(str(e))
            app.slo = SLOEngine(
                app.name, objective=objective,
                recorder=FlightRecorder(app.name),
                context_fn=app._slo_saturation)
        # playback mode (+ optional idle-advance: SiddhiAppParser.java
        # :171-210 wires EventTimeBasedMillisTimestampGenerator so the
        # virtual clock advances by `increment` whenever sources stay
        # idle for `idle.time` of wall time)
        pb = A.find_annotation(ast.annotations, "playback")
        if pb is not None:
            app._playback = True
            idle = pb.element("idle.time")
            inc = pb.element("increment")
            if (idle is None) != (inc is None):
                raise CompileError(
                    "@app:playback needs BOTH idle.time and increment "
                    "(or neither)")
            if idle is not None:
                app._playback_idle_ms = _time_str_ms(
                    idle, "@app:playback idle.time")
                app._playback_increment_ms = _time_str_ms(
                    inc, "@app:playback increment")
        # 2. queries in order; inferred output streams defined as we go
        qcount = 0
        pcount = 0
        for el in ast.execution_elements:
            if isinstance(el, A.Query):
                qcount += 1
                self.plan_query(el, default_name=f"query_{qcount}")
            elif isinstance(el, A.Partition):
                pcount += 1
                qcount = self.plan_partition(el, qcount, pcount)
        # 3. sources/sinks from @source/@sink annotations
        from .io import build_io
        build_io(app, self.extensions)

    def plan_watermarks(self) -> None:
        """``@app:watermark(...)`` / per-stream ``@watermark(...)`` ->
        ReorderBuffer per configured stream, wired onto the ingest path
        (InputHandler). App-level without ``stream=`` applies to every
        defined stream; ``stream='S'`` targets one; a definition-level
        annotation overrides both. Any watermark config switches the
        app to event-time processing (playback semantics): the virtual
        clock advances on watermark progress."""
        from ..resilience.ordering import (ReorderBuffer,
                                           config_from_annotation)
        app, ast = self.app, self.ast
        wm_default = None
        wm_streams: dict = {}
        for ann in ast.annotations:
            if ann.name.lower() != "watermark":
                continue
            try:
                conf = config_from_annotation(ann)
            except ValueError as e:
                raise CompileError(f"@app:watermark: {e}")
            tgt = ann.element("stream")
            if tgt is None:
                wm_default = conf
            else:
                tgt = str(tgt).strip().strip("'\"")
                if tgt not in ast.stream_definitions:
                    raise CompileError(
                        f"@app:watermark targets undefined stream "
                        f"'{tgt}'")
                wm_streams[tgt] = conf
        for sid, sd in ast.stream_definitions.items():
            wa = A.find_annotation(sd.annotations, "watermark")
            if wa is not None:
                try:
                    conf = config_from_annotation(wa)
                except ValueError as e:
                    raise CompileError(f"stream '{sid}': @watermark: {e}")
            else:
                conf = wm_streams.get(sid) or wm_default
            if conf is None:
                continue
            buf = ReorderBuffer(sid, app.schemas[sid], conf)
            buf.handler = app.input_handlers[sid]
            if conf.policy == "STREAM":
                lt = conf.late_stream
                lsd = ast.stream_definitions.get(lt)
                if lsd is None:
                    raise CompileError(
                        f"stream '{sid}': @watermark late.stream '{lt}' "
                        "is not a defined stream")
                if [a.type for a in lsd.attributes] != \
                        [a.type for a in sd.attributes]:
                    raise CompileError(
                        f"stream '{sid}': @watermark late.stream '{lt}' "
                        "schema does not match the source stream "
                        "(late events re-publish with the original "
                        "attributes)")
                lschema = StreamSchema(lt, tuple(
                    Attribute(a.name, a.type) for a in lsd.attributes))
                buf.late_junction = app.junction_for(lt, lschema)
            app._reorder[sid] = buf
        if app._reorder:
            # watermarks define event time: windows/joins/patterns fire
            # on watermark progress (implies @app:playback semantics)
            app._playback = True

    # -- partitions ------------------------------------------------------
    DEFAULT_PARTITION_SLOTS = 32

    def plan_partition(self, part: A.Partition, qcount: int,
                       pcount: int) -> int:
        """`partition with (...) begin ... end` -> PartitionBlockRuntime
        (reference: PartitionParser.java:46 + PartitionRuntimeImpl.java:75).
        See siddhi_tpu/parallel/partition.py for the slot-vmap design."""
        from ..parallel.partition import (BlockQueryPlan, BlockStreamReceiver,
                                          PartitionBlockRuntime)
        app = self.app
        # 1. key specs per partitioned stream (shared instance space)
        key_specs: dict = {}
        label_slots: dict[str, int] = {}
        has_value = False
        for pt in part.partition_types:
            schema = app.schemas.get(pt.stream_id)
            if schema is None:
                raise CompileError(
                    f"partition: undefined stream '{pt.stream_id}'")
            scope = SingleStreamScope(schema)
            if isinstance(pt, A.ValuePartitionType):
                has_value = True
                key_specs[pt.stream_id] = (
                    "value", compile_expression(pt.expression, scope))
            elif isinstance(pt, A.RangePartitionType):
                conds = []
                for expr, label in pt.ranges:
                    ce = compile_expression(expr, scope)
                    if ce.type is not AttrType.BOOL:
                        raise CompileError(
                            "partition range condition must be BOOL")
                    if label not in label_slots:
                        label_slots[label] = len(label_slots)
                    conds.append((ce, label_slots[label]))
                key_specs[pt.stream_id] = ("range", conds)
            else:
                raise CompileError(
                    f"unknown partition type {type(pt).__name__}")
        # slot capacity: ranges are exactly the label count; value keys get
        # a bounded first-seen table (@partition slots='N' overrides)
        n_slots = len(label_slots) if (label_slots and not has_value) \
            else max(self.DEFAULT_PARTITION_SLOTS, len(label_slots))
        sa = A.find_annotation(part.annotations, "slots")
        if sa is not None:
            n_slots = int(sa.element())
        if len(label_slots) > n_slots:
            raise CompileError(
                f"partition has {len(label_slots)} range labels but only "
                f"{n_slots} slots; @slots must be >= the label count")
        mesh = getattr(app, "partition_mesh", None)
        if mesh is not None:
            n = mesh.shape[mesh.axis_names[0]]
            n_slots = ((n_slots + n - 1) // n) * n

        # 2. queries, in order; inner-stream (#S) schemas register as their
        # producers are planned
        inner_schemas: dict[str, StreamSchema] = {}
        plans: list[BlockQueryPlan] = []
        block_names: set[str] = set()
        for q in part.queries:
            qcount += 1
            name = q.name or f"query_{qcount}"
            if name in app.queries or name in block_names:
                raise CompileError(f"duplicate query name '{name}'")
            block_names.add(name)
            if isinstance(q.input, A.StateInputStream):
                plan = self._plan_partition_pattern(q, name, key_specs)
                if plan.inner_target:
                    prev = inner_schemas.get(plan.target)
                    if prev is not None and \
                            prev.types != plan.out_schema.types:
                        raise CompileError(
                            f"inner stream '{plan.target}' schema "
                            "mismatch between producers")
                    inner_schemas[plan.target] = plan.out_schema
                plans.append(plan)
                continue
            if not isinstance(q.input, A.SingleInputStream):
                raise CompileError(
                    f"query '{name}': only single-stream and pattern/"
                    "sequence queries are supported inside partitions "
                    "(joins in partitions are a later stage)")
            sin = q.input
            if sin.is_inner:
                input_id = "#" + sin.stream_id
                schema = inner_schemas.get(input_id)
                if schema is None:
                    raise CompileError(
                        f"query '{name}': inner stream '{input_id}' has no "
                        "producer earlier in this partition")
            else:
                input_id = sin.stream_id
                schema = app.schemas.get(sin.stream_id)
                if schema is None:
                    raise CompileError(f"query '{name}': undefined stream "
                                       f"'{sin.stream_id}'")
                if sin.stream_id not in key_specs:
                    raise CompileError(
                        f"query '{name}': stream '{sin.stream_id}' is not "
                        "partitioned (no 'partition with' clause names it)")
            out = q.output
            if not isinstance(out, (A.InsertIntoStream, A.ReturnStream)):
                raise CompileError(
                    f"query '{name}': table output inside partitions not "
                    "yet supported")
            out_type = out.output_event_type
            inner_target = bool(getattr(out, "is_inner", False))
            raw_target = getattr(out, "target", None) or name
            target = ("#" + raw_target) if inner_target else raw_target
            scope = SingleStreamScope(schema, aliases=(sin.alias,))
            operators = self.build_single_chain(
                q, name, schema, sin, scope, target,
                current_on=out_type in ("current", "all"),
                expired_on=out_type in ("expired", "all"),
                allow_tables=False)
            if any(getattr(op, "host_schedule", None) for op in operators):
                raise CompileError(
                    f"query '{name}': cron windows inside partitions are "
                    "not supported")
            plan = BlockQueryPlan(name, input_id, schema, operators,
                                  target, inner_target, out_type)
            if inner_target:
                prev = inner_schemas.get(target)
                if prev is not None and prev.types != plan.out_schema.types:
                    raise CompileError(
                        f"inner stream '{target}' schema mismatch between "
                        "producers")
                inner_schemas[target] = plan.out_schema
            plans.append(plan)

        block = PartitionBlockRuntime(
            app, f"partition_{pcount}", n_slots, key_specs, plans,
            mesh=mesh)
        app.partitions[block.name] = block

        # 3. wiring: subscribe consumed outer streams; wire outer outputs
        consumed = sorted(
            {sid for p in plans
             for sid in getattr(p, "input_ids", {p.input_id})
             if not sid.startswith("#")})
        for sid in consumed:
            app.junctions[sid].subscribe(BlockStreamReceiver(block, sid))
        for q, plan in zip(part.queries, plans):
            port = block.ports[plan.name]
            app.queries[plan.name] = port
            if not plan.inner_target and isinstance(
                    q.output, A.InsertIntoStream):
                tj = app.junction_for(plan.target, plan.out_schema)
                if plan.target not in app.input_handlers:
                    app.input_handlers[plan.target] = InputHandler(
                        plan.target, tj, app)
                port.output_handlers.append(
                    InsertIntoStreamHandler(tj, plan.out_type))
            self.attach_rate_limiter(port, q, plan.name)
        return qcount

    # -- windows ---------------------------------------------------------
    def window_class(self, h: A.WindowHandler):
        name = h.name if h.namespace is None else f"{h.namespace}:{h.name}"
        cls = WINDOW_CLASSES.get(name.lower())
        if cls is None:
            ext = self.extensions.get(name.lower())
            if isinstance(ext, type) and issubclass(ext, WindowOp):
                return ext
            raise CompileError(f"window '{name}' not yet supported")
        return cls

    def make_window(self, h: A.WindowHandler, schema: StreamSchema,
                    expired_enabled: bool,
                    cap_override: Optional[int] = None) -> WindowOp:
        name = h.name if h.namespace is None else f"{h.namespace}:{h.name}"
        params = []
        for p in h.parameters:
            if isinstance(p, A.Constant):
                params.append(p.value)
            elif isinstance(p, A.Variable):
                params.append(p)  # attribute params (externalTime, sort...)
            else:
                raise CompileError(
                    f"window '{name}' parameters must be constants or "
                    "attributes")
        key = name.lower()
        time_cap = cap_override or self.DEFAULT_TIME_CAP

        def attr_idx(p, role):
            if not isinstance(p, A.Variable):
                raise CompileError(
                    f"window '{name}' {role} must be a stream attribute")
            try:
                return schema.index_of(p.attribute)
            except (KeyError, ValueError):
                raise CompileError(
                    f"window '{name}': '{p.attribute}' is not an "
                    "attribute of the input stream")

        def const_of(p, role):
            if isinstance(p, A.Variable):
                raise CompileError(
                    f"window '{name}' {role} must be a constant")
            return p
        if key == "time":
            _expect(params, 1, name)
            return TimeWindowOp(schema, _ms(params[0], name),
                                cap=time_cap,
                                expired_enabled=expired_enabled)
        if key == "length":
            _expect(params, 1, name)
            return LengthWindowOp(schema, int(const_of(params[0], 'length')),
                                  expired_enabled=expired_enabled)
        if key == "lengthbatch":
            if len(params) not in (1, 2):
                raise CompileError(f"{name} takes 1-2 parameters")
            stream_cur = bool(const_of(params[1], 'mode')) \
                if len(params) == 2 else False
            return LengthBatchWindowOp(schema,
                                       int(const_of(params[0], 'length')),
                                       expired_enabled=expired_enabled,
                                       stream_current=stream_cur)
        if key in ("hopping", "hoping"):
            _expect(params, 2, name)
            return HoppingWindowOp(schema, _ms(params[0], name),
                                   _ms(params[1], name),
                                   cap=time_cap,
                                   expired_enabled=expired_enabled)
        if key == "timebatch":
            if len(params) not in (1, 2, 3):
                raise CompileError(f"{name} takes 1-3 parameters")
            start = None
            stream_cur = False
            if len(params) >= 2:
                p1 = const_of(params[1], 'start time / mode')
                if isinstance(p1, bool):
                    stream_cur = p1
                    if len(params) == 3:
                        raise CompileError(
                            f"{name}: bool mode must be the last "
                            "parameter")
                elif isinstance(p1, int):
                    start = int(p1)
                else:
                    raise CompileError(
                        f"window '{name}' start time must be int/long")
            if len(params) == 3:
                mode = const_of(params[2], 'mode')
                if not isinstance(mode, bool):
                    raise CompileError(
                        f"window '{name}' stream.current.event mode must "
                        "be a bool constant")
                stream_cur = mode
            return TimeBatchWindowOp(schema, _ms(params[0], name),
                                     start_time=start,
                                     cap=time_cap,
                                     expired_enabled=expired_enabled,
                                     stream_current=stream_cur)
        if key == "externaltimebatch":
            if len(params) not in (2, 3, 4, 5):
                raise CompileError(f"{name} takes 2-5 parameters")
            ti = attr_idx(params[0], "timestamp parameter")
            if schema.attributes[ti].type is not AttrType.LONG:
                raise CompileError(
                    f"window '{name}' timestamp attribute must be LONG")
            start = None
            start_attr = None
            if len(params) >= 3:
                if isinstance(params[2], A.Variable):
                    start_attr = attr_idx(params[2], "start time")
                else:
                    start = int(const_of(params[2], 'start time'))
            timeout = _ms(params[3], name) if len(params) >= 4 else None
            replace = bool(const_of(params[4], 'replace flag')) \
                if len(params) == 5 else False
            return ExternalTimeBatchWindowOp(
                schema, ti, _ms(params[1], name), start_time=start,
                cap=time_cap, expired_enabled=expired_enabled,
                start_attr=start_attr, timeout_ms=timeout,
                replace_ts=replace)
        if key == "externaltime":
            _expect(params, 2, name)
            ti = attr_idx(params[0], "timestamp parameter")
            if schema.attributes[ti].type is not AttrType.LONG:
                raise CompileError(
                    f"window '{name}' timestamp attribute must be LONG")
            return ExternalTimeWindowOp(schema, ti, _ms(params[1], name),
                                        cap=time_cap,
                                        expired_enabled=expired_enabled)
        if key == "timelength":
            _expect(params, 2, name)
            return TimeLengthWindowOp(schema, _ms(params[0], name),
                                      int(const_of(params[1], 'length')),
                                      expired_enabled=expired_enabled)
        if key == "delay":
            _expect(params, 1, name)
            return DelayWindowOp(schema, _ms(params[0], name),
                                 cap=time_cap,
                                 expired_enabled=expired_enabled)
        if key == "batch":
            if len(params) > 1:
                raise CompileError(f"{name} takes 0-1 parameters")
            length = int(const_of(params[0], 'length')) if params else 0
            return BatchWindowOp(schema, length, cap=time_cap,
                                 expired_enabled=expired_enabled)
        if key == "cron":
            _expect(params, 1, name)
            if not isinstance(params[0], str):
                raise CompileError(
                    f"window '{name}' takes a cron expression string")
            from ..utils.cron import CronError
            try:
                return CronWindowOp(schema, params[0],
                                    cap=time_cap,
                                    expired_enabled=expired_enabled)
            except CronError as e:
                raise CompileError(f"window '{name}': {e}")
        if key == "session":
            if len(params) not in (1, 2):
                raise CompileError(
                    f"{name} takes 1-2 parameters (allowedLatency is not "
                    "supported)")
            ki = None
            if len(params) == 2:
                ki = attr_idx(params[1], "session key")
                if schema.attributes[ki].type is not AttrType.STRING:
                    raise CompileError(
                        f"window '{name}' session key must be STRING")
            return SessionWindowOp(schema, _ms(params[0], name), ki,
                                   expired_enabled=expired_enabled)
        if key == "sort":
            if not params:
                raise CompileError(f"{name} needs a length parameter")
            keys = []
            i = 1
            while i < len(params):
                ki = attr_idx(params[i], "sort attribute")
                order = 1
                if i + 1 < len(params) and isinstance(params[i + 1], str):
                    d = params[i + 1].lower()
                    if d not in ("asc", "desc"):
                        raise CompileError(
                            f"{name}: order must be 'asc' or 'desc'")
                    order = 1 if d == "asc" else -1
                    i += 1
                keys.append((ki, order))
                i += 1
            if not keys:
                raise CompileError(f"{name} needs at least one sort "
                                   "attribute")
            return SortWindowOp(schema,
                                int(const_of(params[0], 'length')), keys,
                                expired_enabled=expired_enabled)
        if key == "frequent":
            if not params:
                raise CompileError(f"{name} needs a count parameter")
            idxs = [attr_idx(p, "key attribute") for p in params[1:]]
            return FrequentWindowOp(schema,
                                    int(const_of(params[0], 'count')),
                                    idxs,
                                    expired_enabled=expired_enabled)
        if key == "lossyfrequent":
            if not params:
                raise CompileError(f"{name} needs a support parameter")
            error = None
            rest = params[1:]
            if rest and not isinstance(rest[0], A.Variable):
                error = float(const_of(rest[0], 'error'))
                rest = rest[1:]
            idxs = [attr_idx(p, "key attribute") for p in rest]
            return LossyFrequentWindowOp(schema,
                                         float(const_of(params[0],
                                                        'support')), error,
                                         idxs,
                                         expired_enabled=expired_enabled)
        ext = self.extensions.get(key)
        if isinstance(ext, type) and issubclass(ext, WindowOp):
            return ext(schema, params, expired_enabled=expired_enabled)
        raise CompileError(f"window '{name}' not yet supported")

    def plan_query(self, q: A.Query, default_name: str) -> None:
        app = self.app
        name = q.name or default_name
        if isinstance(q.input, A.StateInputStream):
            return self.plan_pattern_query(q, name)
        if isinstance(q.input, A.JoinInputStream):
            return self.plan_join_query(q, name)
        if not isinstance(q.input, A.SingleInputStream):
            raise CompileError(
                f"query '{name}': only single-stream, join, and pattern "
                "queries supported in this stage")
        sin = q.input
        if getattr(sin, "is_fault", False):
            sin = dataclasses.replace(sin, stream_id="!" + sin.stream_id,
                                      is_fault=False)
        schema = app.schemas.get(sin.stream_id)
        if schema is None:
            raise CompileError(f"query '{name}': undefined stream "
                               f"'{sin.stream_id}'")
        scope = SingleStreamScope(schema, aliases=(sin.alias,))

        out = q.output
        if isinstance(out, (A.InsertIntoStream, A.ReturnStream,
                            A.DeleteStream, A.UpdateStream,
                            A.UpdateOrInsertStream)):
            out_type = out.output_event_type
        else:
            raise CompileError(f"query '{name}': unsupported output "
                               f"{type(out).__name__}")
        target = getattr(out, "target", None) or name
        current_on = out_type in ("current", "all")
        expired_on = out_type in ("expired", "all")
        operators = self.build_single_chain(
            q, name, schema, sin, scope, target, current_on, expired_on,
            allow_tables=True)
        self.append_table_output(operators, out, name)

        if name in app.queries:
            raise CompileError(f"duplicate query name '{name}'")
        qr = QueryRuntime(name, operators, schema, app)
        app.junctions[sin.stream_id].subscribe(qr)
        app.queries[name] = qr
        self.wire_stream_output(qr, out, out_type)
        self.attach_rate_limiter(qr, q, name)

    def attach_rate_limiter(self, qr, q: A.Query, name: str) -> None:
        """`output <all|first|last> every N events / T` and
        `output snapshot every T` -> a host-side limiter gating the row
        path (reference: OutputParser rate selection +
        query/output/ratelimit/)."""
        rate = q.output_rate
        if rate is None:
            return
        from .ratelimit import build_rate_limiter
        key_fn = None
        needs_key = (isinstance(rate, (A.EventOutputRate,
                                       A.TimeOutputRate))
                     and rate.type in ("first", "last")) or \
            isinstance(rate, A.SnapshotOutputRate)
        gb = q.selector.group_by or []
        if needs_key and gb:
            idxs = []
            for g in gb:
                col = None
                for i, oa in enumerate(q.selector.attributes):
                    e = oa.expression
                    if isinstance(e, A.Variable) and \
                            e.attribute == g.attribute:
                        col = i
                        break
                if col is None:
                    try:
                        col = qr.out_schema.index_of(g.attribute)
                    except KeyError:
                        raise CompileError(
                            f"query '{name}': group-by rate limiting "
                            f"needs '{g.attribute}' in the projection")
                idxs.append(col)

            def key_fn(row, _idxs=tuple(idxs)):
                return tuple(row[2][i] for i in _idxs)

        qr.set_rate_limiter(build_rate_limiter(rate, key_fn))

    def build_single_chain(self, q: A.Query, name: str,
                           schema: StreamSchema, sin: A.SingleInputStream,
                           scope, target: str, current_on: bool,
                           expired_on: bool,
                           allow_tables: bool = True) -> list:
        """Handler chain + selector for a single-stream query — shared by
        plan_query and partitioned block planning
        (= SingleInputStreamParser.parseInputStream + SelectorParser)."""
        app = self.app
        needs_agg = selector_needs_aggregation(q.selector)
        cap_window, _, _ = self._cap_annotation(q)
        operators: list[Operator] = []
        window_op: Optional[WindowOp] = None
        for h in sin.handlers:
            if isinstance(h, A.Filter):
                # filters may appear before AND after the window
                # (SingleInputStreamParser.java:202-243 chains handlers in
                # declaration order; FilterProcessor evaluates its condition
                # on every non-TIMER event kind)
                if expr_mentions_table(h.expression):
                    if not allow_tables:
                        raise CompileError(
                            f"query '{name}': table references inside "
                            "partitions not yet supported")
                    operators.append(TableFilterOp(
                        h.expression, schema, app.tables, scope))
                    continue
                cond = compile_expression(h.expression, scope,
                                          self.functions)
                if cond.type is not AttrType.BOOL:
                    raise CompileError(f"query '{name}': filter must be BOOL")
                fop = FilterOp(cond, schema,
                               tparams=collect_template_params(
                                   h.expression))
                # plan-optimizer evidence (plan/canon.py): canonical
                # signature for CSE prefix sharing, referenced-column
                # set for pushdown legality
                from ..plan.canon import canonical_expr, filter_ref_names
                fop.plan_sig = "filter:" + canonical_expr(h.expression)
                fop.ref_names = filter_ref_names(h.expression)
                operators.append(fop)
            elif isinstance(h, A.WindowHandler):
                if window_op is not None:
                    raise CompileError(
                        f"query '{name}': multiple windows on one stream")
                cls = self.window_class(h)
                # sliding windows must feed EXPIRED events to aggregating
                # selectors (subtract-on-expire); batch windows only emit
                # expired when the output asks for them
                # (outputExpectsExpiredEvents in the reference)
                expired_enabled = expired_on if cls.is_batch \
                    else (expired_on or needs_agg)
                window_op = self.make_window(h, schema, expired_enabled,
                                             cap_override=cap_window)
                operators.append(window_op)
            else:
                from ..ops.streamfn import make_stream_function
                op = make_stream_function(h, schema, scope,
                                          self.functions,
                                          self.extensions, name)
                operators.append(op)
                if op.out_schema.types != schema.types:
                    schema = op.out_schema
                    scope = SingleStreamScope(schema,
                                              aliases=(sin.alias,))

        batch_mode = window_op is not None and window_op.is_batch
        src_window = None if sin.is_inner else \
            app.named_windows.get(sin.stream_id)
        expired_possible = (window_op is not None
                            and window_op.expired_enabled) or \
            src_window is not None

        if needs_agg:
            if collect_template_params(
                    *[oa.expression for oa in q.selector.attributes],
                    q.selector.having):
                # planner backstop; the template-binding plan rule
                # reports this with anchors at parse time
                raise CompileError(
                    f"query '{name}': template params are not supported "
                    "in aggregating selectors")
            operators.append(AggregateOp(
                q.selector, schema, target, scope,
                functions=self.functions,
                batch_mode=batch_mode, expired_possible=expired_possible,
                current_on=current_on, expired_on=expired_on,
                fifo_expiry=(window_op.fifo_expiry
                             if window_op is not None else
                             (src_window.operators[0].fifo_expiry
                              if src_window is not None else True))))
        else:
            pop = ProjectOp(
                q.selector, schema, target, scope,
                functions=self.functions,
                current_on=current_on, expired_on=expired_on)
            # plan-optimizer evidence: projection signature (CSE) and
            # the output names that pass through as identity variables
            # (pushdown legality — a downstream filter may hoist across
            # this projection only for columns it leaves untouched)
            from ..plan.canon import selector_sig
            pop.plan_sig = (f"project:{current_on}:{expired_on}:"
                            + selector_sig(q.selector))
            if q.selector.select_all:
                idn = frozenset(schema.names)
            else:
                idn = frozenset(
                    out_name
                    for i, oa in enumerate(q.selector.attributes)
                    if isinstance(oa.expression, A.Variable)
                    and oa.expression.index is None
                    and oa.expression.function_ref is None
                    and (out_name := output_attribute_name(oa, i))
                    == oa.expression.attribute)
            pop.identity_names = idn
            operators.append(pop)
        return operators

    def _plan_partition_pattern(self, q, name: str, key_specs: dict):
        """A pattern/sequence query inside a partition: the scan-engine
        NFA runs per key slot under the block vmap
        (PartitionRuntimeImpl.java:75 clones state runtimes per key)."""
        import dataclasses
        from ..ops.nfa import (MatchScope, NfaCompiler, NfaEngine,
                               rewrite_last_refs, rewrite_oob_refs)
        from ..parallel.partition import BlockPatternPlan
        app = self.app
        sin = q.input
        out = q.output
        if not isinstance(out, (A.InsertIntoStream, A.ReturnStream)):
            raise CompileError(
                f"query '{name}': table output inside partitions not "
                "yet supported")
        out_type = out.output_event_type
        inner_target = bool(getattr(out, "is_inner", False))
        raw_target = getattr(out, "target", None) or name
        target = ("#" + raw_target) if inner_target else raw_target

        compiler = NfaCompiler(app.schemas, sin.state_type)
        slots, states = compiler.compile(sin.state)
        sel = q.selector
        if sel.attributes:
            sel.attributes = [
                dataclasses.replace(
                    oa, expression=rewrite_oob_refs(
                        rewrite_last_refs(oa.expression, slots), slots))
                for oa in sel.attributes]
        if sel.having is not None:
            sel.having = rewrite_oob_refs(
                rewrite_last_refs(sel.having, slots), slots)
        # per-slot pending tables stay modest: K instances multiply
        engine = NfaEngine(slots, states, sin.state_type, sin.within_ms,
                           capacity=32, out_capacity=64)
        scope = MatchScope(slots, engine.col_index)
        input_ids = {s.stream_id for s in slots}
        for sid in sorted(input_ids):
            if sid not in key_specs:
                raise CompileError(
                    f"query '{name}': pattern stream '{sid}' is not "
                    "partitioned (no 'partition with' clause names it)")
        current_on = out_type in ("current", "all")
        expired_on = out_type in ("expired", "all")
        if selector_needs_aggregation(q.selector):
            sel_ops: list[Operator] = [AggregateOp(
                q.selector, engine.match_schema, target, scope,
                batch_mode=False, expired_possible=False,
                current_on=current_on, expired_on=expired_on)]
        else:
            sel_ops = [ProjectOp(
                q.selector, engine.match_schema, target, scope,
                current_on=current_on, expired_on=expired_on,
                having_in_scope=scope)]
        in_schema = app.schemas[sorted(input_ids)[0]]
        return BlockPatternPlan(name, engine, sel_ops, input_ids,
                                in_schema, target, inner_target, out_type)

    def append_table_output(self, operators: list, out, name: str) -> None:
        """Insert/delete/update/update-or-insert into a table becomes a
        terminal TableOutputOp (reference: OutputParser table callbacks)."""
        from ..ops.selector import OutputScope
        app = self.app
        sel_schema = operators[-1].out_schema
        escope = OutputScope(sel_schema)
        target = getattr(out, "target", None)
        if target in app.record_tables:
            # wired as a StoreOutputHandler: a host IO boundary, so
            # host-shaped (STRING-ordered) rows reach it correctly
            return
        if target in app.tables and \
                getattr(operators[-1], "host_shape", None):
            raise CompileError(
                "order by on a STRING attribute shapes rows at the host "
                "boundary and cannot feed a device table output (tables "
                "insert inside the jitted step)")
        if isinstance(out, A.InsertIntoStream) and out.target in app.tables:
            operators.append(TableOutputOp(
                "insert", app.tables[out.target], None, None, escope,
                sel_schema))
        elif isinstance(out, (A.DeleteStream, A.UpdateStream,
                              A.UpdateOrInsertStream)):
            tr = app.tables.get(out.target)
            if tr is None:
                raise CompileError(
                    f"query '{name}': '{out.target}' is not a defined "
                    "table")
            kind = {"DeleteStream": "delete", "UpdateStream": "update",
                    "UpdateOrInsertStream": "update_or_insert"}[
                type(out).__name__]
            set_clause = getattr(out, "set_clause", None)
            if kind != "delete" and not set_clause:
                # no SET: every table attribute updated from the same-named
                # output attribute (UpdateTableCallback default)
                set_clause = [
                    (A.Variable(attribute=att.name),
                     A.Variable(attribute=att.name))
                    for att in tr.schema.attributes
                    if att.name in sel_schema.names]
            operators.append(TableOutputOp(
                kind, tr, out.on, set_clause, escope, sel_schema))

    def wire_stream_output(self, qr, out, out_type: str) -> None:
        app = self.app
        target = getattr(out, "target", None)
        if target in app.record_tables:
            from .store import StoreOutputHandler
            kind = {"InsertIntoStream": "insert", "DeleteStream": "delete",
                    "UpdateStream": "update",
                    "UpdateOrInsertStream": "update_or_insert"}[
                type(out).__name__]
            set_clause = getattr(out, "set_clause", None)
            if kind in ("update", "update_or_insert") and not set_clause:
                rt = app.record_tables[target]
                set_clause = [
                    (A.Variable(attribute=att.name),
                     A.Variable(attribute=att.name))
                    for att in rt.schema.attributes
                    if att.name in qr.out_schema.names]
            qr.output_handlers.append(StoreOutputHandler(
                app.record_tables[target], kind, getattr(out, "on", None),
                set_clause, qr.out_schema))
            return
        if isinstance(out, A.InsertIntoStream) and \
                out.target in app.named_windows:
            qr.output_handlers.append(
                InsertIntoWindowHandler(app.named_windows[out.target]))
            return
        if isinstance(out, A.InsertIntoStream) and \
                out.target not in app.tables:
            tj = app.junction_for(out.target, qr.out_schema)
            if out.target not in app.input_handlers:
                app.input_handlers[out.target] = InputHandler(out.target, tj,
                                                              app)
            qr.output_handlers.append(
                InsertIntoStreamHandler(tj, out_type))

    # -- join queries ----------------------------------------------------
    @staticmethod
    def _cap_annotation(q: A.Query):
        """`@cap(window.size='N', join.pairs='M')` — bounded-state tuning
        knob (the reference's queues are unbounded; ours are static-shape
        device buffers, so capacity is an explicit per-query dial).
        window.size: rows a time-based window retains; join.pairs: max
        joined pairs emitted per step (overflow is counted, never
        silent); join.candidates: probe-kernel band-candidate expansion
        capacity before residual filtering (default 4x join.pairs)."""
        ca = A.find_annotation(q.annotations, "cap")
        if ca is None:
            return None, None, None

        def to_int(v, key):
            if v is None:
                return None
            try:
                n = int(v)
            except ValueError:
                raise CompileError(
                    f"@cap({key}='{v}'): expected a positive integer")
            if n <= 0:
                raise CompileError(
                    f"@cap({key}='{v}'): expected a positive integer")
            return n

        return (to_int(ca.element("window.size"), "window.size"),
                to_int(ca.element("join.pairs"), "join.pairs"),
                to_int(ca.element("join.candidates"), "join.candidates"))

    def plan_join_query(self, q: A.Query, name: str) -> None:
        app = self.app
        jin: A.JoinInputStream = q.input
        out = q.output
        cap_window, cap_pairs, cap_cands = self._cap_annotation(q)
        if isinstance(out, (A.InsertIntoStream, A.ReturnStream)):
            out_type = out.output_event_type
        else:
            raise CompileError(f"query '{name}': table output not yet "
                               "supported")
        target = out.target if isinstance(out, A.InsertIntoStream) else name
        current_on = out_type in ("current", "all")
        expired_on = out_type in ("expired", "all")
        needs_agg = selector_needs_aggregation(q.selector)

        def side_chain(sin: A.SingleInputStream, side_name: str):
            schema = app.schemas.get(sin.stream_id)
            if schema is None:
                raise CompileError(
                    f"query '{name}': undefined stream '{sin.stream_id}'")
            scope = SingleStreamScope(schema, aliases=(sin.alias,))
            ops: list[Operator] = []
            window = None
            for h in sin.handlers:
                if isinstance(h, A.Filter):
                    cond = compile_expression(h.expression, scope)
                    ops.append(FilterOp(cond, schema))
                elif isinstance(h, A.WindowHandler):
                    if window is not None:
                        raise CompileError(
                            f"query '{name}': multiple windows on one "
                            "join side")
                    cls = self.window_class(h)
                    expired_enabled = expired_on if cls.is_batch \
                        else True  # joins need expired pairs for aggregates
                    window = self.make_window(h, schema, expired_enabled,
                                              cap_override=cap_window)
                    ops.append(window)
                else:
                    raise CompileError(
                        f"query '{name}': stream function in join not "
                        "supported")
            if window is None:
                # default-window insertion (JoinInputStreamParser.java:416)
                window = EmptyWindowOp(schema, expired_enabled=True)
                ops.append(window)
            return schema, ops

        # stream-table joins: a side naming a table contributes its
        # columnar buffer as the findable content and never triggers
        # (JoinInputStreamParser's table branch; the runtime's
        # side_tables machinery reads the live table state per step)
        side_tables = {}

        def table_side(sin: A.SingleInputStream, key: str):
            t = app.tables[sin.stream_id]
            if sin.handlers:
                raise CompileError(
                    f"query '{name}': windows/filters on the table side "
                    "of a join are not supported")
            side_tables[key] = t
            return t.schema, []

        for side_id in (jin.left.stream_id, jin.right.stream_id):
            if side_id in app.record_tables and side_id not in app.tables:
                raise CompileError(
                    f"query '{name}': joining @Store table '{side_id}' "
                    "requires @Cache(...) — the device join step reads "
                    "the cache buffer; an uncached store cannot be "
                    "called from inside the jitted step")
        l_is_table = jin.left.stream_id in app.tables
        r_is_table = jin.right.stream_id in app.tables
        if l_is_table and r_is_table:
            raise CompileError(
                f"query '{name}': joining two tables needs an on-demand "
                "query, not a stream join")
        if (l_is_table or r_is_table) and jin.unidirectional:
            raise CompileError(
                f"query '{name}': 'unidirectional' with a table side is "
                "redundant (tables never trigger) and would silence the "
                "stream side")
        l_schema, l_ops = table_side(jin.left, "L") if l_is_table \
            else side_chain(jin.left, "L")
        r_schema, r_ops = table_side(jin.right, "R") if r_is_table \
            else side_chain(jin.right, "R")
        side_scope = JoinSideScope(l_schema, jin.left.alias,
                                   r_schema, jin.right.alias)
        if q.selector.select_all:
            dup = set(l_schema.names) & set(r_schema.names)
            if dup:
                raise CompileError(
                    f"query '{name}': select * over a join with "
                    f"duplicate attribute(s) {sorted(dup)} — alias the "
                    "outputs (the reference rejects duplicate output "
                    "attributes)")
        jschema = combined_schema(target, l_schema, r_schema)
        crosses = {"L": None, "R": None}
        join_cap = cap_pairs or 1024
        def _win_ms(ops):
            if ops and isinstance(ops[-1], TimeWindowOp):
                return ops[-1].T
            return None

        if jin.unidirectional != "right" and not l_is_table:
            crosses["L"] = JoinCross(True, l_schema, r_schema, jin.on,
                                     side_scope, jin.join_type,
                                     join_cap=join_cap,
                                     opp_window_ms=_win_ms(r_ops),
                                     cand_cap=cap_cands)
        if jin.unidirectional != "left" and not r_is_table:
            crosses["R"] = JoinCross(False, l_schema, r_schema, jin.on,
                                     side_scope, jin.join_type,
                                     join_cap=join_cap,
                                     opp_window_ms=_win_ms(l_ops),
                                     cand_cap=cap_cands)
        # kernel selection (docs/performance.md "join kernels"): banded
        # searchsorted probe for equi joins, [B,W] grid otherwise;
        # SIDDHI_TPU_JOIN_KERNEL overrides, the PR 7 cost table backs
        # the pick with measured evidence when present
        for key, side_name in (("L", "left"), ("R", "right")):
            cross = crosses[key]
            if cross is None:
                continue
            kernel, reason, cause = _pick_join_kernel(app.name, name,
                                                      cross)
            cross.kernel = kernel
            # the cause slug guarantees explain never shows a kernel
            # pick without a machine-readable reason (obs/explain.py)
            app._join_kernels[f"{name}.{side_name}"] = {
                "kernel": kernel, "reason": reason, "cause": cause}

        sel_scope = JoinCombinedScope(side_scope, len(l_schema.types))
        if needs_agg:
            sel_ops: list[Operator] = [AggregateOp(
                q.selector, jschema, target, sel_scope,
                batch_mode=False, expired_possible=True,
                current_on=current_on, expired_on=expired_on,
                fifo_expiry=False)]
        else:
            sel_ops = [ProjectOp(q.selector, jschema, target, sel_scope,
                                 current_on=current_on,
                                 expired_on=expired_on)]

        if name in app.queries:
            raise CompileError(f"duplicate query name '{name}'")
        qr = JoinQueryRuntime(name, l_ops, r_ops, crosses, sel_ops,
                              {"L": l_schema, "R": r_schema}, jschema, app,
                              side_tables=side_tables)
        # cron windows on join sides are host-scheduled like single-stream
        # ones; their fires reach both sides as TIMER batches
        qr._host_sched.extend(
            op.host_schedule for op in l_ops + r_ops
            if getattr(op, "host_schedule", None))
        if not l_is_table:
            app.junctions[jin.left.stream_id].subscribe(
                JoinStreamReceiver(qr, "L"))
        if not r_is_table:
            app.junctions[jin.right.stream_id].subscribe(
                JoinStreamReceiver(qr, "R"))
        app.queries[name] = qr
        if isinstance(out, A.InsertIntoStream):
            tj = app.junction_for(out.target, qr.out_schema)
            if out.target not in app.input_handlers:
                app.input_handlers[out.target] = InputHandler(
                    out.target, tj, app)
            qr.output_handlers.append(InsertIntoStreamHandler(tj, out_type))
        self.attach_rate_limiter(qr, q, name)

    # -- pattern / sequence queries --------------------------------------
    def plan_pattern_query(self, q: A.Query, name: str) -> None:
        app = self.app
        sin: A.StateInputStream = q.input
        out = q.output
        if isinstance(out, (A.InsertIntoStream, A.ReturnStream)):
            out_type = out.output_event_type
        else:
            raise CompileError(f"query '{name}': table output not yet "
                               "supported")
        target = out.target if isinstance(out, A.InsertIntoStream) else name
        current_on = out_type in ("current", "all")
        expired_on = out_type in ("expired", "all")

        compiler = NfaCompiler(app.schemas, sin.state_type)
        slots, states = compiler.compile(sin.state)
        # e[last] / e[last - k] select refs -> ifThenElse chains over the
        # slot's copy columns (nfa.rewrite_last_refs)
        from ..ops.nfa import rewrite_last_refs, rewrite_oob_refs
        sel = q.selector
        if sel.attributes:
            sel.attributes = [
                dataclasses.replace(
                    oa, expression=rewrite_oob_refs(
                        rewrite_last_refs(oa.expression, slots), slots))
                for oa in sel.attributes]
        if sel.having is not None:
            sel.having = rewrite_oob_refs(
                rewrite_last_refs(sel.having, slots), slots)
        if parallel_supported(slots, states, sin.state_type):
            # the TPU-shaped round-parallel engine (larger pending table —
            # its grids are cheap; the scan engine stays small)
            engine = ParallelNfaEngine(slots, states, sin.state_type,
                                       sin.within_ms, capacity=4096,
                                       out_capacity=16384)
        else:
            engine = NfaEngine(slots, states, sin.state_type,
                               sin.within_ms)
        scope = MatchScope(slots, engine.col_index)

        sel_ops: list[Operator] = []
        if selector_needs_aggregation(q.selector):
            sel_ops.append(AggregateOp(
                q.selector, engine.match_schema, target, scope,
                batch_mode=False, expired_possible=False,
                current_on=current_on, expired_on=expired_on))
        else:
            sel_ops.append(ProjectOp(
                q.selector, engine.match_schema, target, scope,
                current_on=current_on, expired_on=expired_on,
                having_in_scope=scope))

        if name in app.queries:
            raise CompileError(f"duplicate query name '{name}'")
        qr = PatternQueryRuntime(name, engine, sel_ops, app)
        for sid in sorted({s.stream_id for s in slots}):
            app.junctions[sid].subscribe(PatternStreamReceiver(qr, sid))
        app.queries[name] = qr
        if isinstance(out, A.InsertIntoStream):
            tj = app.junction_for(out.target, qr.out_schema)
            if out.target not in app.input_handlers:
                app.input_handlers[out.target] = InputHandler(
                    out.target, tj, app)
            qr.output_handlers.append(InsertIntoStreamHandler(tj, out_type))
        self.attach_rate_limiter(qr, q, name)


def _expect(params, n, name):
    if len(params) != n:
        raise CompileError(f"window '{name}' takes {n} parameter(s), got "
                           f"{len(params)}")


def _time_str_ms(s, role: str) -> int:
    """'100 millisecond' / '2 sec' / bare ms int -> milliseconds."""
    s = str(s).strip()
    m = re.fullmatch(
        r"(\d+)\s*(millisecond|milliseconds|ms|sec|second|seconds|s|"
        r"min|minute|minutes|hour|hours|h)?", s)
    if not m:
        raise CompileError(f"{role}: cannot parse time '{s}'")
    n = int(m.group(1))
    unit = m.group(2) or "ms"
    mult = {"millisecond": 1, "milliseconds": 1, "ms": 1,
            "sec": 1000, "second": 1000, "seconds": 1000, "s": 1000,
            "min": 60_000, "minute": 60_000, "minutes": 60_000,
            "hour": 3_600_000, "hours": 3_600_000, "h": 3_600_000}[unit]
    return n * mult


def _ms(v, name) -> int:
    if not isinstance(v, int):
        raise CompileError(f"window '{name}' duration must be int/time, got "
                           f"{v!r}")
    return int(v)
