"""REST microservice: deploy / undeploy SiddhiQL apps over HTTP.

Reference mapping: modules/siddhi-service/ —
- POST /siddhi/artifact/deploy            (body: SiddhiQL text)
- GET  /siddhi/artifact/undeploy/{app}
(SiddhiApi.java:31,37-52; impl SiddhiApiServiceImpl.java:51,100)
plus GET /siddhi/artifacts (list deployed app names).

Multi-tenant front door (docs/serving.md):
- POST /siddhi/tenant/deploy — JSON {template, tenant, bindings?,
  shared?, pool?}: registers the template (hash-keyed), creates/reuses
  the ONE TenantPool per (template, shared) pair, AOT-warms it before
  the first tenant, and admits the tenant into a slot. Admission
  control answers 429 + reason when pool slots or the per-tenant state
  quota are exhausted.
- POST /siddhi/tenant/ingest/{pool}/{tenant} — JSON {ts, rows}: queue
  one chunk; the pool's fair round-robin worker batches it with every
  other tenant's traffic (one hot tenant cannot starve the rest).
- GET  /siddhi/tenant/undeploy/{pool}/{tenant}
- GET  /siddhi/tenant/stats/{pool}[/{tenant}] — per-tenant isolated
  statistics (siddhi.<pool>.tenant.<id>.* namespace).

Observability endpoints (docs/observability.md):
- GET /metrics — Prometheus text exposition over every deployed app's
  MetricsRegistry (auth-protected when a token is set: metric names
  describe app internals).
- GET /health — liveness: 200 whenever the service loop is up. Never
  auth-protected (load-balancer probes don't carry tokens).
- GET /ready  — readiness: 200 only when every deployed app is running
  AND its CompileService has no AOT warmup in flight; 503 otherwise.
  With SIDDHI_TPU_WARM_BUCKETS configured, deploy() returns
  immediately and compiles in the background — the LB holds traffic on
  503 until the step programs are executable (PR 5 warmup wired into
  rollout semantics). Never auth-protected.

A stdlib http.server on a daemon thread fronting a SiddhiManager — the
reference uses MSF4J, the role is identical: remote lifecycle control.

Security: deployed SiddhiQL can contain `define function f[python]`
bodies that are evaluated at plan time (core/extension.py), so deploy is
code execution by design. The service binds 127.0.0.1 by default; for
any other host an `auth_token` is REQUIRED and checked against the
`Authorization: Bearer <token>` header on every request, and script
function definitions are rejected for service-deployed apps unless
`allow_scripts=True` is passed explicitly."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class DuplicateAppError(ValueError):
    """Deploy of an app name that is already running (HTTP 409)."""


class SiddhiService:
    def __init__(self, manager=None, host: str = "127.0.0.1",
                 port: int = 0, auth_token: Optional[str] = None,
                 allow_scripts: bool = False, warm_async: bool = True):
        from .manager import SiddhiManager
        if host not in ("127.0.0.1", "localhost") and not auth_token:
            raise ValueError(
                "binding a non-loopback host requires auth_token= "
                "(deploy evaluates script functions: code execution)")
        self.manager = manager or SiddhiManager()
        self.auth_token = auth_token
        self.allow_scripts = allow_scripts
        # warm_async: with SIDDHI_TPU_WARM_BUCKETS set, deploy() compiles
        # in the background and GET /ready gates traffic instead of the
        # deploy call blocking for the whole AOT phase
        self.warm_async = warm_async
        self._deployed: dict = {}
        # multi-tenant serving (siddhi_tpu/serving/): hash-keyed template
        # registry; one TenantPool (= one compiled program set) per
        # (template, shared-bindings) pair
        from ..serving import TemplateRegistry
        self.templates = TemplateRegistry(self.manager)
        # deploy-failure flight recorder (obs/slo.py): every failed
        # deploy dumps a bounded ring of recent deploy/undeploy events
        # so a broken rollout is diagnosable after the fact
        from ..obs.slo import FlightRecorder
        self.flight = FlightRecorder("service")
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: dict, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _send_429(self, exc):
                """Admission rejection: the body carries the
                machine-readable saturation cause (which resource,
                pressure signals) and Retry-After hints the backlog
                drain estimate (docs/serving.md)."""
                sat = dict(getattr(exc, "saturation", None) or {})
                headers = {}
                ra = sat.get("retry_after_ms")
                if ra:
                    headers["Retry-After"] = max(1, -(-int(ra) // 1000))
                return self._send(429, {"error": exc.reason,
                                        "reason": exc.reason,
                                        "saturation": sat}, headers)

            def _send_text(self, code: int, text: str):
                body = text.encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if service.auth_token is None:
                    return True
                got = self.headers.get("Authorization", "")
                return got == f"Bearer {service.auth_token}"

            def _json_body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n).decode()
                body = json.loads(raw) if raw else {}
                if not isinstance(body, dict):
                    raise ValueError("expected a JSON object body")
                return body

            def do_POST(self):
                from ..serving import AdmissionError
                if not self._authorized():
                    return self._send(401, {"error": "unauthorized"})
                if self.path == "/siddhi/tenant/deploy":
                    try:
                        return self._send(200, service.tenant_deploy(
                            self._json_body()))
                    except AdmissionError as e:
                        # admission control: slots / state quota
                        # exhausted -> 429 + saturation cause
                        return self._send_429(e)
                    except Exception as e:  # noqa: BLE001 — to client
                        return self._send(400, {"error": str(e)})
                if self.path.startswith("/siddhi/tenant/ingest/"):
                    parts = self.path.split("/")
                    if len(parts) != 6:
                        return self._send(404, {"error": "not found"})
                    try:
                        return self._send(200, service.tenant_ingest(
                            parts[4], parts[5], self._json_body()))
                    except AdmissionError as e:
                        # per-tenant backlog backpressure OR the QoS
                        # rate limiter -> 429 with the saturation cause
                        # + Retry-After estimate (cause `rate-limited`
                        # carries the token bucket's own accrual time)
                        return self._send_429(e)
                    except KeyError as e:
                        return self._send(404, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — to client
                        return self._send(400, {"error": str(e)})
                if self.path.startswith("/siddhi/tenant/replay/"):
                    # re-deliver a pool's error-store backlog through
                    # the owning slots, original-timestamp order
                    # (docs/resilience.md "Pool recovery")
                    parts = self.path.split("/")
                    if len(parts) not in (5, 6):
                        return self._send(404, {"error": "not found"})
                    try:
                        return self._send(200, service.tenant_replay(
                            parts[4],
                            parts[5] if len(parts) == 6 else None))
                    except KeyError as e:
                        return self._send(404, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — to client
                        return self._send(400, {"error": str(e)})
                if self.path.startswith("/siddhi/tenant/recover/"):
                    # crash recovery hook: newest restorable revision
                    # onto the pool + error-backlog replay
                    parts = self.path.split("/")
                    if len(parts) != 5:
                        return self._send(404, {"error": "not found"})
                    try:
                        return self._send(200,
                                          service.tenant_recover(parts[4]))
                    except KeyError as e:
                        return self._send(404, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — to client
                        return self._send(400, {"error": str(e)})
                if self.path.startswith("/siddhi/tenant/migrate/"):
                    # live slot migration: {"device": N} moves one
                    # tenant between mesh devices at the next round
                    # boundary (docs/serving.md)
                    parts = self.path.split("/")
                    if len(parts) != 6:
                        return self._send(404, {"error": "not found"})
                    try:
                        return self._send(200, service.tenant_migrate(
                            parts[4], parts[5], self._json_body()))
                    except AdmissionError as e:
                        return self._send_429(e)
                    except KeyError as e:
                        return self._send(404, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — to client
                        return self._send(400, {"error": str(e)})
                if self.path.startswith("/siddhi/tenant/evacuate/"):
                    # device-loss recovery: lost slots restore from the
                    # newest pool checkpoint onto surviving devices
                    # (docs/resilience.md "Device evacuation")
                    parts = self.path.split("/")
                    if len(parts) != 5:
                        return self._send(404, {"error": "not found"})
                    try:
                        return self._send(
                            200, service.tenant_evacuate(parts[4]))
                    except KeyError as e:
                        return self._send(404, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — to client
                        return self._send(400, {"error": str(e)})
                if self.path != "/siddhi/artifact/deploy":
                    return self._send(404, {"error": "not found"})
                n = int(self.headers.get("Content-Length", 0))
                text = self.rfile.read(n).decode()
                try:
                    name = service.deploy(text)
                except DuplicateAppError as e:
                    return self._send(409, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — surface to client
                    return self._send(400, {"error": str(e)})
                rt = service._deployed.get(name)
                # per-artifact readiness in the deploy response: with an
                # async warm the app is visible-but-cold until its AOT
                # compiles land (poll /ready, or redeploy-time tooling
                # can branch on warm/cold directly)
                self._send(200, {"status": "deployed", "app": name,
                                 "ready": bool(rt and rt.ready)})

            def do_GET(self):
                # LB probes first: liveness/readiness carry no secrets
                # and no tokens
                if self.path == "/health":
                    return self._send(200, {"status": "up",
                                            "apps": len(service._deployed)})
                if self.path == "/ready":
                    ready, apps = service.readiness()
                    return self._send(200 if ready else 503,
                                      {"ready": ready, "apps": apps})
                if not self._authorized():
                    return self._send(401, {"error": "unauthorized"})
                if self.path == "/metrics":
                    return self._send_text(200, service.metrics_text())
                if self.path == "/siddhi/slo":
                    # the SLO/burn-rate view over every deployed app
                    # with an objective + every tenant pool
                    # (docs/observability.md "SLO engine")
                    return self._send(200, service.slo_report())
                if self.path == "/siddhi/explain":
                    # the plan-explain view: every deployed app's and
                    # pool's full decision document with its stable
                    # plan_hash (docs/observability.md "Explain");
                    # auth-protected — the plan describes app internals
                    return self._send(200, service.explain_report())
                if self.path.startswith("/siddhi/artifact/undeploy/"):
                    name = self.path.rsplit("/", 1)[-1]
                    if service.undeploy(name):
                        return self._send(200, {"status": "undeployed",
                                                "app": name})
                    return self._send(404, {"error": f"no app '{name}'"})
                if self.path.startswith("/siddhi/tenant/undeploy/"):
                    parts = self.path.split("/")
                    if len(parts) == 6:
                        if service.tenant_undeploy(parts[4], parts[5]):
                            return self._send(
                                200, {"status": "undeployed",
                                      "pool": parts[4],
                                      "tenant": parts[5]})
                    return self._send(404, {"error": "not found"})
                if self.path.startswith("/siddhi/tenant/stats/"):
                    parts = self.path.split("/")
                    try:
                        if len(parts) == 5:
                            return self._send(
                                200, service.tenant_stats(parts[4]))
                        if len(parts) == 6:
                            return self._send(
                                200, service.tenant_stats(parts[4],
                                                          parts[5]))
                    except KeyError as e:
                        return self._send(404, {"error": str(e)})
                    return self._send(404, {"error": "not found"})
                if self.path == "/siddhi/artifacts":
                    # per-artifact readiness alongside the name list so
                    # deploy tooling can see warm/cold without a probe
                    # per app
                    return self._send(200, {
                        "apps": sorted(service._deployed),
                        "ready": {name: rt.ready for name, rt
                                  in list(service._deployed.items())},
                        "pools": sorted(p.name for p in
                                        service.templates.pools.values()),
                    })
                self._send(404, {"error": "not found"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="siddhi-service")
        self._thread.start()

    def stop(self) -> None:
        for name in list(self._deployed):
            self.undeploy(name)
        self.templates.shutdown()
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- observability -----------------------------------------------------
    def readiness(self) -> tuple:
        """(all_ready, {app: ready}) — an app is ready when running and
        its CompileService has no warmup in flight (core/compile.py);
        tenant pools report as ``pool:<name>`` and gate /ready the same
        way while their vmapped program set is compiling.
        Snapshots the deploy map first: probes race deploy/undeploy."""
        apps = {name: rt.ready
                for name, rt in list(self._deployed.items())}
        for pool in self.templates.pools.values():
            apps[f"pool:{pool.name}"] = pool.ready
        return all(apps.values()), apps

    def metrics_text(self) -> str:
        """One Prometheus scrape over every deployed app's registry plus
        every tenant pool's (labeled ``tenant=`` sample families)."""
        parts = [rt.metrics.prometheus_text()
                 for rt in list(self._deployed.values())]
        parts += [pool.metrics.prometheus_text()
                  for pool in self.templates.pools.values()]
        text = "".join(p for p in parts if p)
        return text or "# no metrics (no apps deployed)\n"

    def slo_report(self) -> dict:
        """``GET /siddhi/slo``: per-scope latency/burn-rate states for
        every deployed app carrying an ``@app:slo`` objective and every
        tenant pool (pools always track; objectives optional). The
        worst state rides at the top so a probe can alert on one
        field."""
        apps: dict = {}
        worst = "OK"
        order = {"OK": 0, "WARN": 1, "PAGE": 2}
        for name, rt in list(self._deployed.items()):
            rep = rt.slo_report() if hasattr(rt, "slo_report") else None
            if rep is not None:
                apps[name] = rep
                st = rep.get("state")
                if st in order and order[st] > order[worst]:
                    worst = st
        pools: dict = {}
        for pool in self.templates.pools.values():
            rep = pool.slo_report()
            pools[pool.name] = rep
            st = rep.get("state")
            if st in order and order[st] > order[worst]:
                worst = st
        return {"state": worst, "apps": apps, "pools": pools}

    def explain_report(self) -> dict:
        """``GET /siddhi/explain``: the full plan-explain document for
        every deployed app and tenant pool, keyed by name, each with
        its stable ``plan_hash`` (docs/observability.md "Explain").
        Assembly is a host-side view — no compiles, no device reads —
        so probing this endpoint is always safe on a serving box."""
        apps = {}
        for name, rt in list(self._deployed.items()):
            try:
                apps[name] = rt.explain()
            except Exception as e:  # noqa: BLE001 — one broken app must
                apps[name] = {"error": str(e)}  # not kill the probe
        pools = {}
        for pool in list(self.templates.pools.values()):
            try:
                pools[pool.name] = pool.explain()
            except Exception as e:  # noqa: BLE001 — ditto
                pools[pool.name] = {"error": str(e)}
        return {"apps": apps, "pools": pools}

    # -- tenant operations (serving/, docs/serving.md) ---------------------
    def tenant_deploy(self, body: dict) -> dict:
        """Template + bindings -> pool slot. The FIRST deploy of a
        (template, shared) pair creates the pool and AOT-warms its
        vmapped program set; every later tenant is pure slot assignment
        against the already-compiled programs."""
        template = body.get("template")
        tenant = body.get("tenant")
        if not template or not tenant:
            raise ValueError(
                "tenant deploy body needs 'template' (text or "
                "registered name) and 'tenant' (id)")
        pool_conf = dict(body.get("pool") or {})
        pool_kwargs = {k: pool_conf[k] for k in
                       ("slots", "max_tenants", "state_quota_bytes",
                        "batch_max", "pending_cap", "slo", "qos",
                        "device_round_cap")
                       if k in pool_conf}
        pool = self.templates.pool(template,
                                   shared=body.get("shared"),
                                   **pool_kwargs)
        pool.start()   # fair-batching drain worker (idempotent)
        # body `qos`: per-tenant dials (weight / priority / rate_eps /
        # burst) merged over the pool defaults (docs/serving.md)
        slot = pool.add_tenant(str(tenant), body.get("bindings"),
                               qos=body.get("qos"))
        return {"status": "deployed", "app": pool.name,
                "tenant": str(tenant), "slot": slot,
                "template": pool.template.key, "ready": pool.ready,
                "pool": {"slots": pool.slots,
                         "active": len(pool._tenants),
                         "max_tenants": pool.max_tenants}}

    def _pool(self, pool_name: str):
        for pool in self.templates.pools.values():
            if pool.name == pool_name:
                return pool
        raise KeyError(f"no tenant pool '{pool_name}'")

    def tenant_undeploy(self, pool_name: str, tenant: str) -> bool:
        try:
            pool = self._pool(pool_name)
        except KeyError:
            return False
        return pool.remove_tenant(tenant)

    def tenant_ingest(self, pool_name: str, tenant: str,
                      body: dict) -> dict:
        """JSON chunk -> pool queue: {"ts": [...], "rows": [[...], ...]}
        (row-major; STRING cells as text). The fair-batching worker
        dispatches it with the rest of the round."""
        import numpy as np
        from .types import AttrType, GLOBAL_STRINGS, np_dtype
        pool = self._pool(pool_name)
        rows = body.get("rows") or []
        if not rows:
            return {"accepted": 0}
        schema = pool.proto.junctions[pool.ingest_stream].schema
        if any(len(r) != len(schema.types) for r in rows):
            raise ValueError(
                f"rows must have {len(schema.types)} columns "
                f"(stream '{pool.ingest_stream}')")
        ts = body.get("ts")
        if ts is None:
            import time as _t
            base = int(_t.time() * 1000)
            ts = [base + i for i in range(len(rows))]
        cols = []
        for i, t in enumerate(schema.types):
            vals = [r[i] for r in rows]
            if t is AttrType.STRING:
                vals = [GLOBAL_STRINGS.encode(str(v)) for v in vals]
            cols.append(np.asarray(vals, dtype=np_dtype(t)))
        pool.send(tenant, np.asarray(ts, dtype=np.int64), cols)
        return {"accepted": len(rows)}

    def tenant_replay(self, pool_name: str,
                      tenant: Optional[str] = None) -> dict:
        """``POST /siddhi/tenant/replay/<pool>[/<tid>]``: drain the
        pool's (or one tenant's) error-store partitions and re-deliver
        through the owning slots in original-timestamp order
        (TenantPool.replay_errors; the PR 9 replay contract)."""
        pool = self._pool(pool_name)
        replayed = pool.replay_errors(tenant)
        return {"status": "replayed", "pool": pool_name,
                "replayed": replayed,
                "total": sum(replayed.values())}

    def tenant_recover(self, pool_name: str) -> dict:
        """``POST /siddhi/tenant/recover/<pool>``: restore the newest
        restorable whole-pool revision from the persistence store, then
        replay the error backlog (resilience/supervisor.py
        PoolCheckpointSupervisor.recover)."""
        from ..resilience.supervisor import PoolCheckpointSupervisor
        pool = self._pool(pool_name)
        sup = pool._checkpoint_supervisor or \
            PoolCheckpointSupervisor(pool)
        restored, replayed = sup.recover()
        return {"status": "recovered", "pool": pool_name,
                "restored": restored, "replayed": replayed}

    def tenant_migrate(self, pool_name: str, tenant: str,
                       body: dict) -> dict:
        """``POST /siddhi/tenant/migrate/<pool>/<tid>`` with
        ``{"device": N}``: live-migrate one tenant's slot to another
        mesh device at the next round boundary (zero recompiles,
        bit-identical state, parked-ingest flip — serving/migrate.py
        protocol; docs/serving.md "Live migration & rebalance")."""
        pool = self._pool(pool_name)
        if "device" not in body:
            raise ValueError("migrate body needs 'device' (target "
                             "mesh device index)")
        rec = pool.migrate_tenant(tenant, int(body["device"]),
                                  cause=str(body.get("cause",
                                                     "manual")))
        return {"status": "migrated", "pool": pool_name, **rec}

    def tenant_evacuate(self, pool_name: str) -> dict:
        """``POST /siddhi/tenant/evacuate/<pool>``: restore every
        lost-device victim from the newest restorable pool checkpoint
        onto the surviving devices, then replay their error backlog in
        original-timestamp order (serving/migrate.py evacuate;
        docs/resilience.md "Device evacuation")."""
        from ..serving.migrate import evacuate
        pool = self._pool(pool_name)
        out = evacuate(pool)
        return {"status": "evacuated", "pool": pool_name,
                "evacuated": out["evacuated"],
                "revision": out["revision"],
                "replayed": out["replayed"]}

    def tenant_stats(self, pool_name: str,
                     tenant: str = None) -> dict:
        pool = self._pool(pool_name)
        stats = pool.statistics()
        if tenant is None:
            return stats
        entry = stats["tenants"].get(tenant)
        if entry is None:
            raise KeyError(f"no tenant '{tenant}' in pool "
                           f"'{pool_name}'")
        return {"pool": pool_name, "tenant": tenant, **entry}

    # -- operations -------------------------------------------------------
    def deploy(self, siddhi_ql: str) -> str:
        # identity holder: _deploy fills it as the failing deploy gets
        # further (parsed name, then plan hash once a runtime exists) so
        # the failure artifact names WHAT failed, not just that
        # something did — {app, pool, plan_hash} context uniformly
        # (obs/slo.py FlightRecorder identity contract)
        ident: dict = {"app": None, "pool": None, "plan_hash": None}
        try:
            return self._deploy(siddhi_ql, ident)
        except Exception as exc:
            if ident.get("app") is None:
                # parse-time failures never reached the name: re-parse
                # WITHOUT validation just to recover the identity (the
                # artifact must name what failed even for a bad plan)
                try:
                    from ..lang.parser import parse
                    ident["app"] = parse(siddhi_ql, validate=False).name
                except Exception:  # noqa: BLE001 — identity is
                    pass           # best-effort
            # deploy failure -> flight-recorder artifact (the ring holds
            # the recent deploy history; the path lands in the log so a
            # failed rollout is diagnosable post-mortem)
            self.flight.record("deploy-failure", error=str(exc),
                               app=ident.get("app"),
                               kind_of_error=type(exc).__name__)
            try:
                path = self.flight.dump(
                    "deploy-failure",
                    context={**ident,
                             "deployed": sorted(self._deployed),
                             "error": str(exc)})
                import logging
                logging.getLogger("siddhi_tpu.service").warning(
                    "deploy failed (%s); flight-recorder artifact: %s",
                    exc, path)
            except Exception:  # noqa: BLE001 — recording must not mask
                pass           # the real deploy error
            raise

    def _deploy(self, siddhi_ql: str, ident: Optional[dict] = None) -> str:
        ident = ident if ident is not None else {}
        # both checks run on the PARSED app before any runtime is built:
        # a textual scan is comment-bypassable, and building a duplicate
        # runtime would clobber the manager registry entry of the live one
        from ..lang.parser import parse
        app_ast = parse(siddhi_ql)
        ident["app"] = app_ast.name
        if not self.allow_scripts and app_ast.function_definitions:
            raise ValueError(
                "script function definitions are disabled for "
                "service-deployed apps (pass allow_scripts=True to "
                "accept remote code execution)")
        if app_ast.name and app_ast.name in self._deployed:
            raise DuplicateAppError(
                f"app '{app_ast.name}' is already deployed — undeploy it "
                "first")
        rt = self.manager.create_siddhi_app_runtime(siddhi_ql)
        ident["app"] = rt.name
        try:
            ident["plan_hash"] = rt.plan_hash()
        except Exception:  # noqa: BLE001 — identity is best-effort
            pass
        from .compile import warm_buckets_from_env
        warm = warm_buckets_from_env() if self.warm_async else ()
        if warm:
            # AOT-compile in the background: deploy returns immediately,
            # GET /ready stays 503 until every step program is
            # executable. Readiness is reserved BEFORE the app becomes
            # visible in _deployed, so no probe can observe a
            # ready->unready flap between deploy and the warm thread.
            rt._skip_start_warmup = True
            rt.compile_service._begin()
        rt.start()
        self._deployed[rt.name] = rt
        if warm:
            try:
                rt.warmup_async(buckets=warm)
            finally:
                rt.compile_service._end()
        self.flight.record("deploy", app=rt.name)
        return rt.name

    def undeploy(self, name: str) -> bool:
        rt = self._deployed.pop(name, None)
        if rt is None:
            return False
        # undeploy of a still-warming app: cancel the background AOT
        # compiles FIRST (they would otherwise keep compiling for a dead
        # app), then shut down, then join the warm threads so the
        # inflight counter provably returns to zero instead of leaking
        # behind a daemon thread (readiness bookkeeping stays exact)
        rt.compile_service.cancel()
        rt.shutdown()
        rt.compile_service.join(timeout=30)
        return True
