"""REST microservice: deploy / undeploy SiddhiQL apps over HTTP.

Reference mapping: modules/siddhi-service/ —
- POST /siddhi/artifact/deploy            (body: SiddhiQL text)
- GET  /siddhi/artifact/undeploy/{app}
(SiddhiApi.java:31,37-52; impl SiddhiApiServiceImpl.java:51,100)
plus GET /siddhi/artifacts (list deployed app names).

A stdlib http.server on a daemon thread fronting a SiddhiManager — the
reference uses MSF4J, the role is identical: remote lifecycle control."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class SiddhiService:
    def __init__(self, manager=None, host: str = "127.0.0.1",
                 port: int = 0):
        from .manager import SiddhiManager
        self.manager = manager or SiddhiManager()
        self._deployed: dict = {}
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != "/siddhi/artifact/deploy":
                    return self._send(404, {"error": "not found"})
                n = int(self.headers.get("Content-Length", 0))
                text = self.rfile.read(n).decode()
                try:
                    name = service.deploy(text)
                except Exception as e:  # noqa: BLE001 — surface to client
                    return self._send(400, {"error": str(e)})
                self._send(200, {"status": "deployed", "app": name})

            def do_GET(self):
                if self.path.startswith("/siddhi/artifact/undeploy/"):
                    name = self.path.rsplit("/", 1)[-1]
                    if service.undeploy(name):
                        return self._send(200, {"status": "undeployed",
                                                "app": name})
                    return self._send(404, {"error": f"no app '{name}'"})
                if self.path == "/siddhi/artifacts":
                    return self._send(200,
                                      {"apps": sorted(service._deployed)})
                self._send(404, {"error": "not found"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="siddhi-service")
        self._thread.start()

    def stop(self) -> None:
        for name in list(self._deployed):
            self.undeploy(name)
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- operations -------------------------------------------------------
    def deploy(self, siddhi_ql: str) -> str:
        rt = self.manager.create_siddhi_app_runtime(siddhi_ql)
        rt.start()
        self._deployed[rt.name] = rt
        return rt.name

    def undeploy(self, name: str) -> bool:
        rt = self._deployed.pop(name, None)
        if rt is None:
            return False
        rt.shutdown()
        return True
