"""REST microservice: deploy / undeploy SiddhiQL apps over HTTP.

Reference mapping: modules/siddhi-service/ —
- POST /siddhi/artifact/deploy            (body: SiddhiQL text)
- GET  /siddhi/artifact/undeploy/{app}
(SiddhiApi.java:31,37-52; impl SiddhiApiServiceImpl.java:51,100)
plus GET /siddhi/artifacts (list deployed app names).

A stdlib http.server on a daemon thread fronting a SiddhiManager — the
reference uses MSF4J, the role is identical: remote lifecycle control.

Security: deployed SiddhiQL can contain `define function f[python]`
bodies that are evaluated at plan time (core/extension.py), so deploy is
code execution by design. The service binds 127.0.0.1 by default; for
any other host an `auth_token` is REQUIRED and checked against the
`Authorization: Bearer <token>` header on every request, and script
function definitions are rejected for service-deployed apps unless
`allow_scripts=True` is passed explicitly."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class DuplicateAppError(ValueError):
    """Deploy of an app name that is already running (HTTP 409)."""


class SiddhiService:
    def __init__(self, manager=None, host: str = "127.0.0.1",
                 port: int = 0, auth_token: Optional[str] = None,
                 allow_scripts: bool = False):
        from .manager import SiddhiManager
        if host not in ("127.0.0.1", "localhost") and not auth_token:
            raise ValueError(
                "binding a non-loopback host requires auth_token= "
                "(deploy evaluates script functions: code execution)")
        self.manager = manager or SiddhiManager()
        self.auth_token = auth_token
        self.allow_scripts = allow_scripts
        self._deployed: dict = {}
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if service.auth_token is None:
                    return True
                got = self.headers.get("Authorization", "")
                return got == f"Bearer {service.auth_token}"

            def do_POST(self):
                if not self._authorized():
                    return self._send(401, {"error": "unauthorized"})
                if self.path != "/siddhi/artifact/deploy":
                    return self._send(404, {"error": "not found"})
                n = int(self.headers.get("Content-Length", 0))
                text = self.rfile.read(n).decode()
                try:
                    name = service.deploy(text)
                except DuplicateAppError as e:
                    return self._send(409, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — surface to client
                    return self._send(400, {"error": str(e)})
                self._send(200, {"status": "deployed", "app": name})

            def do_GET(self):
                if not self._authorized():
                    return self._send(401, {"error": "unauthorized"})
                if self.path.startswith("/siddhi/artifact/undeploy/"):
                    name = self.path.rsplit("/", 1)[-1]
                    if service.undeploy(name):
                        return self._send(200, {"status": "undeployed",
                                                "app": name})
                    return self._send(404, {"error": f"no app '{name}'"})
                if self.path == "/siddhi/artifacts":
                    return self._send(200,
                                      {"apps": sorted(service._deployed)})
                self._send(404, {"error": "not found"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="siddhi-service")
        self._thread.start()

    def stop(self) -> None:
        for name in list(self._deployed):
            self.undeploy(name)
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- operations -------------------------------------------------------
    def deploy(self, siddhi_ql: str) -> str:
        # both checks run on the PARSED app before any runtime is built:
        # a textual scan is comment-bypassable, and building a duplicate
        # runtime would clobber the manager registry entry of the live one
        from ..lang.parser import parse
        app_ast = parse(siddhi_ql)
        if not self.allow_scripts and app_ast.function_definitions:
            raise ValueError(
                "script function definitions are disabled for "
                "service-deployed apps (pass allow_scripts=True to "
                "accept remote code execution)")
        if app_ast.name and app_ast.name in self._deployed:
            raise DuplicateAppError(
                f"app '{app_ast.name}' is already deployed — undeploy it "
                "first")
        rt = self.manager.create_siddhi_app_runtime(siddhi_ql)
        rt.start()
        self._deployed[rt.name] = rt
        return rt.name

    def undeploy(self, name: str) -> bool:
        rt = self._deployed.pop(name, None)
        if rt is None:
            return False
        rt.shutdown()
        return True
