"""REST microservice: deploy / undeploy SiddhiQL apps over HTTP.

Reference mapping: modules/siddhi-service/ —
- POST /siddhi/artifact/deploy            (body: SiddhiQL text)
- GET  /siddhi/artifact/undeploy/{app}
(SiddhiApi.java:31,37-52; impl SiddhiApiServiceImpl.java:51,100)
plus GET /siddhi/artifacts (list deployed app names).

Observability endpoints (docs/observability.md):
- GET /metrics — Prometheus text exposition over every deployed app's
  MetricsRegistry (auth-protected when a token is set: metric names
  describe app internals).
- GET /health — liveness: 200 whenever the service loop is up. Never
  auth-protected (load-balancer probes don't carry tokens).
- GET /ready  — readiness: 200 only when every deployed app is running
  AND its CompileService has no AOT warmup in flight; 503 otherwise.
  With SIDDHI_TPU_WARM_BUCKETS configured, deploy() returns
  immediately and compiles in the background — the LB holds traffic on
  503 until the step programs are executable (PR 5 warmup wired into
  rollout semantics). Never auth-protected.

A stdlib http.server on a daemon thread fronting a SiddhiManager — the
reference uses MSF4J, the role is identical: remote lifecycle control.

Security: deployed SiddhiQL can contain `define function f[python]`
bodies that are evaluated at plan time (core/extension.py), so deploy is
code execution by design. The service binds 127.0.0.1 by default; for
any other host an `auth_token` is REQUIRED and checked against the
`Authorization: Bearer <token>` header on every request, and script
function definitions are rejected for service-deployed apps unless
`allow_scripts=True` is passed explicitly."""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class DuplicateAppError(ValueError):
    """Deploy of an app name that is already running (HTTP 409)."""


class SiddhiService:
    def __init__(self, manager=None, host: str = "127.0.0.1",
                 port: int = 0, auth_token: Optional[str] = None,
                 allow_scripts: bool = False, warm_async: bool = True):
        from .manager import SiddhiManager
        if host not in ("127.0.0.1", "localhost") and not auth_token:
            raise ValueError(
                "binding a non-loopback host requires auth_token= "
                "(deploy evaluates script functions: code execution)")
        self.manager = manager or SiddhiManager()
        self.auth_token = auth_token
        self.allow_scripts = allow_scripts
        # warm_async: with SIDDHI_TPU_WARM_BUCKETS set, deploy() compiles
        # in the background and GET /ready gates traffic instead of the
        # deploy call blocking for the whole AOT phase
        self.warm_async = warm_async
        self._deployed: dict = {}
        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str):
                body = text.encode()
                self.send_response(code)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if service.auth_token is None:
                    return True
                got = self.headers.get("Authorization", "")
                return got == f"Bearer {service.auth_token}"

            def do_POST(self):
                if not self._authorized():
                    return self._send(401, {"error": "unauthorized"})
                if self.path != "/siddhi/artifact/deploy":
                    return self._send(404, {"error": "not found"})
                n = int(self.headers.get("Content-Length", 0))
                text = self.rfile.read(n).decode()
                try:
                    name = service.deploy(text)
                except DuplicateAppError as e:
                    return self._send(409, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 — surface to client
                    return self._send(400, {"error": str(e)})
                self._send(200, {"status": "deployed", "app": name})

            def do_GET(self):
                # LB probes first: liveness/readiness carry no secrets
                # and no tokens
                if self.path == "/health":
                    return self._send(200, {"status": "up",
                                            "apps": len(service._deployed)})
                if self.path == "/ready":
                    ready, apps = service.readiness()
                    return self._send(200 if ready else 503,
                                      {"ready": ready, "apps": apps})
                if not self._authorized():
                    return self._send(401, {"error": "unauthorized"})
                if self.path == "/metrics":
                    return self._send_text(200, service.metrics_text())
                if self.path.startswith("/siddhi/artifact/undeploy/"):
                    name = self.path.rsplit("/", 1)[-1]
                    if service.undeploy(name):
                        return self._send(200, {"status": "undeployed",
                                                "app": name})
                    return self._send(404, {"error": f"no app '{name}'"})
                if self.path == "/siddhi/artifacts":
                    return self._send(200,
                                      {"apps": sorted(service._deployed)})
                self._send(404, {"error": "not found"})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="siddhi-service")
        self._thread.start()

    def stop(self) -> None:
        for name in list(self._deployed):
            self.undeploy(name)
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    # -- observability -----------------------------------------------------
    def readiness(self) -> tuple:
        """(all_ready, {app: ready}) — an app is ready when running and
        its CompileService has no warmup in flight (core/compile.py).
        Snapshots the deploy map first: probes race deploy/undeploy."""
        apps = {name: rt.ready
                for name, rt in list(self._deployed.items())}
        return all(apps.values()), apps

    def metrics_text(self) -> str:
        """One Prometheus scrape over every deployed app's registry."""
        parts = [rt.metrics.prometheus_text()
                 for rt in list(self._deployed.values())]
        text = "".join(p for p in parts if p)
        return text or "# no metrics (no apps deployed)\n"

    # -- operations -------------------------------------------------------
    def deploy(self, siddhi_ql: str) -> str:
        # both checks run on the PARSED app before any runtime is built:
        # a textual scan is comment-bypassable, and building a duplicate
        # runtime would clobber the manager registry entry of the live one
        from ..lang.parser import parse
        app_ast = parse(siddhi_ql)
        if not self.allow_scripts and app_ast.function_definitions:
            raise ValueError(
                "script function definitions are disabled for "
                "service-deployed apps (pass allow_scripts=True to "
                "accept remote code execution)")
        if app_ast.name and app_ast.name in self._deployed:
            raise DuplicateAppError(
                f"app '{app_ast.name}' is already deployed — undeploy it "
                "first")
        rt = self.manager.create_siddhi_app_runtime(siddhi_ql)
        from .compile import warm_buckets_from_env
        warm = warm_buckets_from_env() if self.warm_async else ()
        if warm:
            # AOT-compile in the background: deploy returns immediately,
            # GET /ready stays 503 until every step program is
            # executable. Readiness is reserved BEFORE the app becomes
            # visible in _deployed, so no probe can observe a
            # ready->unready flap between deploy and the warm thread.
            rt._skip_start_warmup = True
            rt.compile_service._begin()
        rt.start()
        self._deployed[rt.name] = rt
        if warm:
            try:
                rt.warmup_async(buckets=warm)
            finally:
                rt.compile_service._end()
        return rt.name

    def undeploy(self, name: str) -> bool:
        rt = self._deployed.pop(name, None)
        if rt is None:
            return False
        rt.shutdown()
        return True
