"""Step debugger: per-query IN/OUT breakpoints with pause / next / play.

Reference mapping:
- debugger/SiddhiDebugger.java — acquireBreakPoint(query, IN|OUT),
  next()/play(), semaphore pause, getQueryState; hooked at
  ProcessStreamReceiver.java:100-103 and the output callbacks
  (SiddhiAppRuntimeImpl.debug():657).

Here the hooks sit at the host boundary of the jitted step: IN fires
with the decoded input events before the device step of the named query,
OUT with the decoded output rows after it. `next()` releases one
breakpoint hit, `play()` releases the current hit and disables pausing
until another breakpoint is acquired. The callback runs on the ingest
thread (sync junctions), so inspection sees a quiesced pipeline —
the same contract as the reference's semaphore pause."""
from __future__ import annotations

import threading
from enum import Enum
from typing import Callable, Optional


class QueryTerminal(Enum):
    IN = "IN"
    OUT = "OUT"


class SiddhiDebugger:
    def __init__(self, app):
        self.app = app
        self._breakpoints: set = set()      # (query_name, terminal)
        self._gate = threading.Semaphore(0)
        self._paused = threading.Event()
        self._playing = False
        self.callback: Optional[Callable] = None

    # -- public API (SiddhiDebugger surface) ------------------------------
    def acquire_break_point(self, query_name: str,
                            terminal: QueryTerminal) -> None:
        self._breakpoints.add((query_name, terminal))
        self._playing = False

    def release_break_point(self, query_name: str,
                            terminal: QueryTerminal) -> None:
        self._breakpoints.discard((query_name, terminal))

    def release_all_break_points(self) -> None:
        self._breakpoints.clear()

    def next(self) -> None:
        """Release the current pause; the following hit pauses again."""
        self._gate.release()

    def play(self) -> None:
        """Release the current pause and stop pausing entirely."""
        self._playing = True
        self._gate.release()

    def get_query_state(self, query_name: str) -> dict:
        q = self.app.queries.get(query_name)
        if q is None or not hasattr(q, "snapshot_state"):
            return {}
        return q.snapshot_state()

    # -- runtime hook -----------------------------------------------------
    def check_break_point(self, query_name: str, terminal: QueryTerminal,
                          events) -> None:
        if (query_name, terminal) not in self._breakpoints:
            return
        if self.callback is not None:
            self.callback(query_name, terminal, events)
        if self._playing:
            return
        self._paused.set()
        self._gate.acquire()
        self._paused.clear()
