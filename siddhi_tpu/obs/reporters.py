"""Periodic metric reporters, configured via
``@app:statistics(reporter='console', interval='5 sec')``.

Reference mapping: util/statistics/metrics/SiddhiStatisticsManager
starts a Dropwizard ConsoleReporter/JmxReporter at the configured
interval when statistics are enabled. Here a daemon thread snapshots
the app's MetricsRegistry every interval and emits it:

- ``console`` / ``log``: one JSON object per tick through the
  ``siddhi_tpu.metrics`` logger (INFO).
- ``file`` / ``jsonl``: one JSON line per tick appended to a file
  (default ``./siddhi_metrics_<app>.jsonl``, override with the
  ``file`` annotation element).

Unknown reporter names fail at parse time (analysis/plan_rules.py
``statistics-reporter``), mirroring the `on-error-action` validation.
Reporters tick on WALL time even under ``@app:playback`` — reporting is
operational telemetry, not event-time semantics.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from typing import Optional

log = logging.getLogger("siddhi_tpu.metrics")

# parse-time validation surface (analysis/plan_rules.py imports this)
REPORTER_NAMES = ("console", "log", "file", "jsonl")

DEFAULT_INTERVAL_MS = 60_000


class PeriodicReporter:
    """Snapshot ``runtime.metrics.collect()`` every ``interval_ms`` on a
    daemon thread; subclasses implement ``emit(snapshot)``."""

    def __init__(self, runtime, interval_ms: int):
        self.runtime = runtime
        self.interval_ms = max(1, int(interval_ms))
        self.ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PeriodicReporter":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"siddhi-metrics-{self.runtime.name}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _run(self) -> None:
        interval_s = self.interval_ms / 1000.0
        while not self._stop.wait(interval_s):
            if not self.runtime.running:
                continue
            try:
                snap = self.runtime.metrics.collect()
                self.emit({"app": self.runtime.name,
                           "ts_ms": int(time.time() * 1000), **snap})
                self.ticks += 1
            except Exception:  # noqa: BLE001 — reporting must not kill
                log.exception("metrics reporter tick failed")  # the app

    def emit(self, snapshot: dict) -> None:
        raise NotImplementedError


class ConsoleReporter(PeriodicReporter):
    """reporter='console' (or 'log'): Dropwizard ConsoleReporter role."""

    def emit(self, snapshot: dict) -> None:
        log.info("%s", json.dumps(snapshot, sort_keys=True))


class JsonLinesReporter(PeriodicReporter):
    """reporter='file' (or 'jsonl'): one JSON line appended per tick."""

    def __init__(self, runtime, interval_ms: int,
                 path: Optional[str] = None):
        super().__init__(runtime, interval_ms)
        self.path = path or f"./siddhi_metrics_{runtime.name}.jsonl"

    def emit(self, snapshot: dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(snapshot, sort_keys=True) + "\n")


def build_reporter(runtime, name: str, interval_ms: int,
                   path: Optional[str] = None) -> PeriodicReporter:
    name = (name or "console").lower()
    if name in ("console", "log"):
        return ConsoleReporter(runtime, interval_ms)
    if name in ("file", "jsonl"):
        return JsonLinesReporter(runtime, interval_ms, path=path)
    # parse-time validation rejects unknown names; planner backstop for
    # validate=False / hand-built ASTs
    raise ValueError(
        f"unknown @app:statistics reporter '{name}' "
        f"(expected one of {', '.join(REPORTER_NAMES)})")
