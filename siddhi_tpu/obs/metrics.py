"""Metrics registry: counters / gauges / histograms under reference-style
dotted names (``siddhi.<app>.stream.<id>.throughput``,
``siddhi.<app>.query.<q>.latency`` ...), with Prometheus text-format
exposition.

Reference mapping: util/statistics/metrics/* — SiddhiStatisticsManager
holds one Dropwizard MetricRegistry per app; trackers register
themselves under dotted names and reporters/exposition read the
registry. Here the runtime's existing trackers (core/stats.py
QueryStats / StreamErrorStats, compile telemetry, junction queue
depths, checkpoint age, scheduler lag) publish into this registry via
pull-at-collection-time collectors: ``collect()`` runs every registered
collector (one batched walk over the runtime, under the app barrier)
and returns a flat ``{dotted_name: number}`` snapshot. The hot path
never touches the registry — see the package docstring.
"""
from __future__ import annotations

import math
import re
import threading
import time
from typing import Callable, Optional

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(dotted: str) -> str:
    """Sanitize a dotted metric name into a Prometheus metric name."""
    name = _PROM_NAME.sub("_", dotted)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _label_str(labels) -> str:
    """``(("tenant","t1"),)`` -> '{tenant="t1"}' with value escaping
    per the exposition format (backslash, quote, newline)."""
    if not labels:
        return ""
    parts = []
    for k, v in labels:
        v = str(v).replace("\\", r"\\").replace('"', r"\"") \
            .replace("\n", r"\n")
        parts.append(f'{prom_name(str(k))}="{v}"')
    return "{" + ",".join(parts) + "}"


class Counter:
    """Monotonic counter (Dropwizard Counter / Meter count)."""

    __slots__ = ("name", "_value", "_lock", "family", "labels")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()
        self.family = None
        self.labels = ()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Point-in-time value; either set directly or backed by a callable
    evaluated at collection time (so the instrumented path pays
    nothing). A gauge created through ``labeled_gauge`` additionally
    carries its metric ``family`` and ``labels`` so the Prometheus
    exposition emits ONE family with label-based samples instead of a
    dotted name per label combination (docs/observability.md)."""

    __slots__ = ("name", "_value", "_fn", "family", "labels")

    def __init__(self, name: str):
        self.name = name
        self._value: float = math.nan
        self._fn: Optional[Callable[[], float]] = None
        self.family = None
        self.labels = ()

    def set(self, value) -> None:
        self._value = value

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a broken gauge must not
                return math.nan  # kill a scrape
        return self._value


class Histogram:
    """Bounded-reservoir summary (avg / p50 / p95 / p99, plus CUMULATIVE
    count and sum), the same windowed model as core/stats.LatencyTracker.
    Exposed in Prometheus summary format: pre-computed quantiles over the
    reservoir window, with ``_count``/``_sum`` monotonic so scrapers can
    ``rate()`` them."""

    CAP = 4096

    __slots__ = ("name", "_samples", "_count", "_sum", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._samples: list[float] = []
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            if len(self._samples) >= self.CAP:
                del self._samples[: self.CAP // 2]
            self._samples.append(float(value))
            self._count += 1
            self._sum += float(value)

    def summary(self) -> Optional[dict]:
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
            count = self._count
            total = self._sum
        n = len(s)
        return {"avg": round(sum(s) / n, 3),
                "p50": round(s[n // 2], 3),
                "p95": round(s[min(n - 1, (n * 95) // 100)], 3),
                "p99": round(s[min(n - 1, (n * 99) // 100)], 3),
                "count": count,
                "sum": round(total, 3)}


class MetricsRegistry:
    """One registry per app runtime. Instruments are created lazily by
    dotted name; ``register_collector(fn)`` adds a pull-time source
    whose ``fn() -> {name: number}`` output lands as gauges on every
    ``collect()``."""

    def __init__(self):
        # RLock: collection walks hold it end to end while instruments
        # created inside the walk re-enter _get
        self._lock = threading.RLock()
        self._metrics: dict[str, object] = {}
        self._collectors: list[Callable[[], dict]] = []
        self._help: dict[str, str] = {}

    # -- instruments -----------------------------------------------------
    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def labeled_gauge(self, family: str, labels: dict,
                      dotted: Optional[str] = None,
                      help: Optional[str] = None) -> Gauge:
        """A gauge that is one SAMPLE of a labeled metric family: the
        exposition emits ``<family>{k="v",...}`` under one ``# TYPE``
        header, while registry dumps / ``collect()`` keep the readable
        ``dotted`` name (default: family + label values). This is the
        cardinality-safe shape for per-tenant metrics — one family with
        a ``tenant`` label, not a metric name per tenant."""
        items = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        if dotted is None:
            dotted = ".".join([family] + [v for _, v in items])
        g = self.gauge(dotted)
        g.family = family
        g.labels = items
        if help is not None:
            self.describe(family, help)
        return g

    def describe(self, name: str, help_text: str) -> None:
        """Attach ``# HELP`` text to a metric (family) name."""
        with self._lock:
            self._help.setdefault(name, help_text)

    def prune_family(self, family: str, keep_dotted) -> int:
        """Drop labeled samples of ``family`` whose dotted name is not
        in ``keep_dotted`` (departed tenants/scopes must not linger in
        scrapes); returns how many were removed."""
        keep = set(keep_dotted)
        removed = 0
        with self._lock:
            for n, m in list(self._metrics.items()):
                if getattr(m, "family", None) == family and n not in keep:
                    del self._metrics[n]
                    removed += 1
        return removed

    def set(self, name: str, value) -> None:
        self.gauge(name).set(value)

    def register_collector(self, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._collectors.append(fn)

    # -- collection ------------------------------------------------------
    def collect(self) -> dict:  # thread-entry (reporter + /metrics scrape threads reach here through untyped runtime handles)
        """Run every collector, fold the results into gauges, and return
        a flat JSON-serializable ``{dotted_name: number}`` snapshot
        (histograms flatten to ``<name>.avg/.p50/.p95/.p99/.count/.sum``).

        Thread model: collector callables run OUTSIDE the registry lock
        (they take the app barrier — holding both here would deadlock
        against dispatch threads that record histograms under the
        barrier), then the fold + instrument walk happens in ONE lock
        acquisition so a concurrent deploy registering collectors or
        creating instruments can never interleave a half-folded
        scrape."""
        with self._lock:
            collectors = list(self._collectors)
        updates: dict = {}
        for fn in collectors:
            try:
                updates.update(fn() or {})
            except Exception:  # noqa: BLE001 — one broken collector must
                continue  # not take down the scrape
        out: dict = {}
        with self._lock:  # the full registry walk is atomic
            for name, value in updates.items():
                self.gauge(name).set(value)
            metrics = list(self._metrics.values())
        for m in metrics:
            if isinstance(m, Histogram):
                s = m.summary()
                if s is not None:
                    for k, v in s.items():
                        out[f"{m.name}.{k}"] = v
            else:
                v = m.value
                if isinstance(v, float) and math.isnan(v):
                    continue
                out[m.name] = v
        return out

    # -- exposition ------------------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4). Counters and
        gauges one sample each; histograms as summaries (quantile
        samples plus cumulative ``_sum``/``_count`` so scrapers can
        ``rate()`` them). Labeled samples (``labeled_gauge``) group
        under ONE ``# HELP``/``# TYPE`` header per family — the shape
        real scrapers ingest as a single series family with a
        ``tenant=``/``query=`` dimension."""
        ts_ms = int(time.time() * 1000)
        lines: list[str] = []
        # refresh collector-backed gauges first
        self.collect()
        with self._lock:
            metrics = sorted(
                self._metrics.values(),
                key=lambda m: (getattr(m, "family", None) or m.name,
                               m.name))
            helps = dict(self._help)
        last_family = None
        for m in metrics:
            family = getattr(m, "family", None) or m.name
            fname = prom_name(family)
            lab = _label_str(getattr(m, "labels", ()))
            if isinstance(m, Counter):
                mtype = "counter"
                samples = [f"{fname}{lab} {m.value} {ts_ms}"]
            elif isinstance(m, Histogram):
                mtype = "summary"
                s = m.summary()
                if s is None:
                    continue
                samples = [
                    f'{fname}{{quantile="0.5"}} {s["p50"]}',
                    f'{fname}{{quantile="0.95"}} {s["p95"]}',
                    f'{fname}{{quantile="0.99"}} {s["p99"]}',
                    f"{fname}_sum {s['sum']}",
                    f"{fname}_count {s['count']}",
                ]
            else:
                mtype = "gauge"
                v = m.value
                if v is None or (isinstance(v, float) and math.isnan(v)):
                    continue
                if isinstance(v, bool):
                    v = int(v)
                if not isinstance(v, (int, float)):
                    continue
                samples = [f"{fname}{lab} {v} {ts_ms}"]
            if family != last_family:
                help_text = helps.get(family)
                if help_text is not None:
                    lines.append(f"# HELP {fname} {help_text}")
                lines.append(f"# TYPE {fname} {mtype}")
                last_family = family
            lines.extend(samples)
        return "\n".join(lines) + ("\n" if lines else "")
