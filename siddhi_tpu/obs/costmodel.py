"""Pipeline cost profiler: per-step device-time attribution, bottleneck
ranking, and a persisted cost table the DAG optimizer can consume.

PR 6 gave the runtime *counters* (what flowed where); this module
answers *which step is eating the device time*. The measurement model
follows the DETAIL-latency lesson (core/stats.py): on an async device
pipeline the only honest per-step wall/device number comes from a
``block_until_ready`` around the dispatched step — which serializes the
pipeline — so the profiler samples: every Nth chunk per cost center
(``SIDDHI_TPU_COST_EVERY``, default 64, same stride pattern as
``SIDDHI_TPU_LAT_EVERY``; the first chunk always samples so short runs
still report). The sync lives on the *sampled branch only* — the
host-sync-in-loop lint rule (extended to ``jax.block_until_ready``)
guards the recording paths, and profiling changes ZERO jit options, so
persistent compile-cache keys stay stable (docs/compile_cache.md).

Cost centers mirror the dispatch units the runtime actually executes —
an XLA program per dispatch, never finer:

- ``query/<q>``          one plain query step
- ``chain/<q1+q2+...>``  one fused insert-into segment (the segment IS
                         one XLA program; per-member split needs a
                         device profile with SIDDHI_TPU_PROFILE_SCOPES=1
                         — members are listed in the report instead)
- ``join/<q>.left|right[grid|probe]``  one join side step; the suffix
                         names the kernel that ran (the [B,W] broadcast
                         grid or the banded searchsorted probe — the
                         planner's cost-table consultation and
                         tools/profile_report.py read it back)
- ``pattern/<q>.<sid>``  one NFA stream step; ``pattern/<q>.timer`` the
                         absent-deadline timer step
- ``partition/<name>``   one K-vmapped partition block step

Samples accumulate into registry histograms
(``siddhi.<app>.query.<center>.step_ms`` /
``siddhi.<app>.partition.<name>.step_ms``) so ``/metrics`` scrapes and
reporters see the same numbers, and ``runtime.cost_report()`` rolls
them up into a ranked table (ms/event, share of total, queue-depth
trend -> bottleneck verdict). ``runtime.cost_save()`` persists the
table to ``<SIDDHI_TPU_CACHE_DIR>/costs.json`` next to the persistent
compile cache, keyed ``<kind>/<name>`` in the compile-spec key style —
the measured per-segment costs ROADMAP item 5's cost-aware plan
optimizer needs.
"""
from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Optional

EVERY_ENV = "SIDDHI_TPU_COST_EVERY"
ENABLE_ENV = "SIDDHI_TPU_COST_PROFILE"
DEFAULT_EVERY = 64

# bounded per-center reservoir for percentile rollups (same windowed
# model as obs/metrics.Histogram)
SAMPLE_CAP = 2048
# queue-depth history per @Async stream (trend detection)
QUEUE_CAP = 64


def default_costs_path() -> str:
    cache = os.environ.get("SIDDHI_TPU_CACHE_DIR") or "./.jax_cache"
    return os.path.join(cache, "costs.json")


class _Probe:
    """One sampled step timing: created right before the dispatch,
    ``done(rows=...)`` after the caller's sampled-branch
    ``block_until_ready``. ``cap`` (the dispatched chunk capacity, when
    the caller passes it) lands the sample in an additional
    per-capacity center ``<kind>/<name>@<cap>`` — the plan optimizer's
    chunk-size evidence (plan/optimizer.py)."""

    __slots__ = ("profiler", "key", "t0", "cap")

    def __init__(self, profiler: "CostProfiler", key: tuple,
                 cap: Optional[int] = None):
        self.profiler = profiler
        self.key = key
        self.cap = cap
        self.t0 = time.perf_counter()

    def done(self, rows: int = 0) -> None:
        dt_ms = (time.perf_counter() - self.t0) * 1000.0
        self.profiler.record(self.key, dt_ms, rows, cap=self.cap)


class _Center:
    """Accumulated cost of one dispatch unit."""

    __slots__ = ("kind", "name", "wall_ms", "events", "samples", "ms")

    def __init__(self, kind: str, name: str):
        self.kind = kind
        self.name = name
        self.wall_ms = 0.0
        self.events = 0
        self.samples = 0
        self.ms: list[float] = []

    def add(self, dt_ms: float, rows: int) -> None:
        self.wall_ms += dt_ms
        self.events += rows
        self.samples += 1
        if len(self.ms) >= SAMPLE_CAP:
            del self.ms[: SAMPLE_CAP // 2]
        self.ms.append(dt_ms)

    def percentiles(self) -> dict:
        s = sorted(self.ms)
        n = len(s)
        if not n:
            return {}
        return {"p50_ms": round(s[n // 2], 3),
                "p95_ms": round(s[min(n - 1, (n * 95) // 100)], 3),
                "p99_ms": round(s[min(n - 1, (n * 99) // 100)], 3)}


class CostProfiler:
    """Per-app sampled synchronous step timing (see module docstring).

    Hot-path contract: when disabled (the default) every dispatch site
    pays ONE attribute check (``app.cost.enabled``) — no locks, no
    syncs, no allocation. When enabled, every chunk bumps a per-center
    counter and every Nth chunk times the step synchronously."""

    def __init__(self, app):
        self.app = app
        self.enabled = os.environ.get(ENABLE_ENV, "") == "1"
        self.every = max(
            1, int(os.environ.get(EVERY_ENV, "") or DEFAULT_EVERY))
        self._lock = threading.Lock()
        self._counters: dict[tuple, int] = {}
        self._centers: dict[tuple, _Center] = {}
        # per-capacity sub-centers keyed (kind, name, cap): persisted as
        # `<kind>/<name>@<cap>` (the optimizer's chunk-size evidence)
        # but EXCLUDED from report() so shares still sum to ~100
        self._cap_centers: dict[tuple, _Center] = {}
        self._queues: dict[str, collections.deque] = {}
        # stale centers the optimizer's load dropped (absent from the
        # current plan — load_costs_for); surfaced in statistics()
        self.stale_centers: Optional[int] = None

    @property
    def samples(self) -> int:
        with self._lock:
            return sum(c.samples for c in self._centers.values())

    # -- lifecycle -------------------------------------------------------
    def start(self, every: Optional[int] = None) -> None:
        """Enable sampled profiling (clears previously accumulated
        costs; ``every=1`` times every chunk — bench's post-measurement
        breakdown pass)."""
        with self._lock:
            self._counters.clear()
            self._centers.clear()
            self._cap_centers.clear()
            self._queues.clear()
        if every is not None:
            self.every = max(1, int(every))
        self.enabled = True

    def stop(self) -> None:
        self.enabled = False

    # -- recording (hot path, only when enabled) -------------------------
    def probe(self, kind: str, name: str,
              cap: Optional[int] = None) -> Optional[_Probe]:
        """Return a timing probe on sampled chunks, else None. Callers
        gate on ``self.enabled`` first so the disabled path never gets
        here. ``cap`` additionally attributes the sample to a
        per-capacity center (see _Probe)."""
        if not self.enabled:
            return None
        key = (kind, name)
        with self._lock:
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
        if n % self.every:
            return None
        return _Probe(self, key, cap=cap)

    def record(self, key: tuple, dt_ms: float, rows: int,
               cap: Optional[int] = None) -> None:
        kind, name = key
        with self._lock:
            c = self._centers.get(key)
            if c is None:
                c = self._centers[key] = _Center(kind, name)
            c.add(dt_ms, rows)
            if cap is not None:
                ck = (kind, name, int(cap))
                cc = self._cap_centers.get(ck)
                if cc is None:
                    cc = self._cap_centers[ck] = _Center(
                        kind, f"{name}@{int(cap)}")
                cc.add(dt_ms, rows)
            # queue-depth samples ride along: backpressure building up
            # behind a step is the first-class bottleneck signal
            for sid, j in self.app.junctions.items():
                q = getattr(j, "_queue", None)
                if j.async_conf is not None and q is not None:
                    dq = self._queues.get(sid)
                    if dq is None:
                        dq = self._queues[sid] = collections.deque(
                            maxlen=QUEUE_CAP)
                    dq.append(q.qsize())
        # registry histogram: scrapes/reporters see the same samples
        self.app.metrics.histogram(self._metric_name(kind, name)) \
            .observe(round(dt_ms, 4))

    def _metric_name(self, kind: str, name: str) -> str:
        if kind == "partition":
            return f"siddhi.{self.app.name}.partition.{name}.step_ms"
        if kind == "fanout":
            return f"siddhi.{self.app.name}.fanout.{name}.step_ms"
        return f"siddhi.{self.app.name}.query.{name}.step_ms"

    # -- rollup ----------------------------------------------------------
    def _queue_trends(self) -> dict:
        out = {}
        for sid, dq in self._queues.items():
            hist = list(dq)
            if len(hist) < 6:
                continue
            third = max(1, len(hist) // 3)
            head = sum(hist[:third]) / third
            tail = sum(hist[-third:]) / third
            if tail > head * 1.5 + 1:
                trend = "rising"
            elif head > tail * 1.5 + 1:
                trend = "falling"
            else:
                trend = "stable"
            out[sid] = {"depth": hist[-1], "trend": trend,
                        "samples": len(hist)}
        return out

    def report(self) -> dict:
        """Ranked cost table: one row per center, ordered by total
        measured wall ms; ``share_pct`` values sum to ~100."""
        with self._lock:
            centers = sorted(self._centers.values(),
                             key=lambda c: -c.wall_ms)
        total_ms = sum(c.wall_ms for c in centers)
        steps = []
        for c in centers:
            row = {"step": f"{c.kind}/{c.name}",
                   "kind": c.kind,
                   "ms_total": round(c.wall_ms, 3),
                   "events": c.events,
                   "samples": c.samples,
                   "share_pct": round(100.0 * c.wall_ms / total_ms, 2)
                   if total_ms else 0.0,
                   **c.percentiles()}
            if c.events:
                row["ms_per_event"] = round(c.wall_ms / c.events, 6)
                row["events_per_s"] = round(
                    c.events / (c.wall_ms / 1000.0), 1) \
                    if c.wall_ms else math.inf
            if c.kind == "chain":
                row["members"] = c.name.split("+")
            elif c.kind == "fanout":
                row["junction"] = c.name
            steps.append(row)
        queues = self._queue_trends()
        report = {"profiling": {"enabled": self.enabled,
                                "every": self.every,
                                "samples": sum(c.samples
                                               for c in centers)},
                  "total_ms": round(total_ms, 3),
                  "steps": steps}
        if self.stale_centers is not None:
            # centers the optimizer's staleness guard dropped at load
            # (renamed/deleted plan units lingering in costs.json)
            report["stale_centers"] = self.stale_centers
        if queues:
            report["queues"] = queues
        if steps:
            top = steps[0]
            rising = [sid for sid, q in queues.items()
                      if q["trend"] == "rising"]
            verdict = (f"{top['step']} dominates measured step time "
                       f"({top['share_pct']}%)")
            if rising:
                verdict += ("; queue depth rising on "
                            + ", ".join(sorted(rising))
                            + " — upstream outpaces the bottleneck "
                            "(backpressure)")
            report["bottleneck"] = {"step": top["step"],
                                    "share_pct": top["share_pct"],
                                    "verdict": verdict}
        return report

    # -- Chrome trace annotations ---------------------------------------
    def trace_annotations(self) -> dict:
        """``{span_name: {cost_*: ...}}`` merged into ``trace_export``
        events so Perfetto rows carry measured device-time context.
        Join sides and pattern streams aggregate onto their query's
        ``step/<q>`` span (those paths dispatch per side/stream but the
        trace names the query)."""
        with self._lock:
            centers = list(self._centers.values())
        agg: dict[str, list] = {}
        for c in centers:
            if c.kind == "query":
                span = f"step/{c.name}"
            elif c.kind == "chain":
                span = f"chain/{c.name}"
            elif c.kind == "fanout":
                span = f"fanout/{c.name}"
            elif c.kind == "partition":
                span = f"partition/{c.name}"
            else:  # join/pattern: <q>.<side|sid|timer> -> step/<q>
                span = f"step/{c.name.rsplit('.', 1)[0]}"
            agg.setdefault(span, []).append(c)
        out = {}
        for span, cs in agg.items():
            ms = sum(c.wall_ms for c in cs)
            ev = sum(c.events for c in cs)
            ann = {"cost_ms_total": round(ms, 3),
                   "cost_samples": sum(c.samples for c in cs)}
            if ev:
                ann["cost_ms_per_event"] = round(ms / ev, 6)
            out[span] = ann
        return out

    # -- persistence ------------------------------------------------------
    def table(self) -> dict:
        """Flat ``{<kind>/<name>: costs}`` table (compile-spec key
        style) for persistence / the DAG optimizer. Per-capacity
        sub-centers ride along as ``<kind>/<name>@<cap>`` keys — the
        optimizer's chunk-size evidence."""
        with self._lock:
            centers = list(self._centers.values()) + \
                list(self._cap_centers.values())
        out = {}
        for c in centers:
            entry = {"ms_total": round(c.wall_ms, 3),
                     "events": c.events,
                     "samples": c.samples,
                     **c.percentiles()}
            if c.events:
                entry["ms_per_event"] = round(c.wall_ms / c.events, 6)
            out[f"{c.kind}/{c.name}"] = entry
        return out

    def save(self, path: Optional[str] = None) -> str:
        """Merge this app's cost table into the persisted
        ``costs.json`` next to the compile cache (tmp+rename, same
        atomicity contract as the filesystem error store).

        The merged table is PRUNED against the app's current plan
        (``SiddhiAppRuntime._cost_center_valid``): centers from
        renamed/deleted queries would otherwise linger forever and feed
        the plan optimizer stale evidence. Other apps' entries are left
        untouched. Returns the path written."""
        path = path or default_costs_path()
        table = self.table()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        existing: dict = {}
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = {}
        app_tbl = existing.setdefault(self.app.name, {})
        app_tbl.update(table)
        valid = getattr(self.app, "_cost_center_valid", None)
        if valid is not None:
            existing[self.app.name] = {
                k: v for k, v in app_tbl.items() if valid(k)}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(existing, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path


def load_costs(path: Optional[str] = None) -> dict:
    """Read the persisted cost table (``{app: {<kind>/<name>: costs}}``);
    missing/corrupt files read as empty — costs are advisory."""
    path = path or default_costs_path()
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def load_costs_for(app: str, valid_center,
                   path: Optional[str] = None) -> tuple[dict, int]:
    """One app's cost table through the staleness guard: centers whose
    keys ``valid_center`` rejects (plan units that no longer exist —
    renamed queries, dropped junctions) are ignored rather than fed to
    the optimizer, and counted. Returns ``(table, stale_count)``; the
    count is surfaced in ``statistics()['cost']['stale_centers']``."""
    tbl = load_costs(path).get(app) or {}
    kept = {k: v for k, v in tbl.items() if valid_center(k)}
    return kept, len(tbl) - len(kept)
