"""Plan explain: every planner decision as one structured, deterministic,
diffable document (docs/observability.md "Explain").

PR 6/7/11 made the runtime's *effects* observable (counters, step cost,
per-tenant latency); this module makes its *decisions* observable — the
things that determine performance before a single event flows: which
queries fused into one XLA program and why a hop broke a chain, which
join kernel the planner picked and on what evidence, which window
compaction variant is active, how state shards over a mesh, what
event-time and SLO contracts are configured, and which AOT programs
exist with what compile cost. The cost-aware DAG optimizer (ROADMAP
item 5) is undebuggable without this read side; TiLT-style optimization
over the temporal dataflow (PAPERS.md) presumes exactly this kind of
inspectable plan IR.

Report shape (``ExplainReport.as_dict()``)::

    {
      "explain_version": 1,
      "app": "<name>",            # identity only — NOT hashed
      "plan_hash": "<16 hex>",    # sha256 over {graph, decisions}
      "graph":     {...},         # streams / nodes / edges (hashed)
      "decisions": {...},         # planner choices + reasons (hashed)
      "programs":  {...},         # AOT inventory + compile ms (live)
      "live":      {...},         # per-edge traffic / cost share (live)
    }

Hash contract: ``plan_hash`` covers the ``graph`` and ``decisions``
sections ONLY, serialized as canonical JSON (sorted keys, no
whitespace). Two deploys of the same app text in the same environment
hash identically; live stats, compile wall times and the app's display
name never move the hash. ``explain_diff(a, b)`` walks exactly the
hashed sections and returns decision-level changes.

Assembly invariant (tested like the PR 6/7 overhead bounds): building a
report allocates ZERO new jitted programs, changes no jit options
(compile-cache keys stay stable), and performs no device reads — every
field is host-side planner/runtime metadata. It is a view over state
the runtime already holds.
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

_MISSING = "<absent>"

EXPLAIN_VERSION = 1


def _canonical(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)


def compute_plan_hash(graph: dict, decisions: dict) -> str:
    """sha256 (16 hex chars) over the canonical JSON of the two hashed
    sections — the ONLY inputs, so live stats can never move it."""
    blob = _canonical({"graph": graph, "decisions": decisions})
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# graph assembly (streams / nodes / edges from the live junction wiring)
# ---------------------------------------------------------------------------


def _window_names(ops) -> list:
    from ..ops.windows import WindowOp
    return [type(op).__name__ for op in ops if isinstance(op, WindowOp)]


def _operator_names(ops) -> list:
    return [type(op).__name__ for op in ops]


def _handler_targets(q) -> list:
    """Machine-readable output targets of one query's handlers plus any
    terminal table write. Unknown handler types degrade to their class
    name (never a crash — explain must work on extended runtimes)."""
    from ..core.runtime import (InsertIntoStreamHandler,
                                InsertIntoWindowHandler,
                                WindowPublishHandler)
    from ..ops.table import TableOutputOp
    out = []
    for h in getattr(q, "output_handlers", ()):
        if isinstance(h, InsertIntoStreamHandler):
            out.append(h.junction.stream_id)
        elif isinstance(h, InsertIntoWindowHandler):
            out.append("window:" + h.wq.name.replace("__window__", "", 1))
        elif isinstance(h, WindowPublishHandler):
            out.append(h.junction.stream_id)
        elif type(h).__name__ == "StoreOutputHandler":
            out.append("store:" + h.rt.table_id)
        else:
            out.append("handler:" + type(h).__name__)
    ops = getattr(q, "operators", None)
    if ops:
        last = ops[-1]
        if isinstance(last, TableOutputOp):
            out.append("table:" + last.table.table_id)
    return out


def _node_entry(rt, qname: str, q) -> dict:
    from ..core.runtime import (JoinQueryRuntime, PatternQueryRuntime,
                                QueryRuntime)
    if isinstance(q, JoinQueryRuntime):
        sides = {}
        for side, nm in (("L", "left"), ("R", "right")):
            if side in q.side_tables:
                sides[nm] = "table:" + q.side_tables[side].table_id
            else:
                sides[nm] = q.in_schemas[side].stream_id
        return {"kind": "join", "inputs": sorted(set(sides.values())),
                "sides": sides, "outputs": _handler_targets(q)}
    if isinstance(q, PatternQueryRuntime):
        slots = [{"ref": s.ref, "stream": s.stream_id}
                 for s in q.engine.slots]
        return {"kind": "pattern",
                "inputs": sorted({s.stream_id for s in q.engine.slots}),
                "slots": slots,
                "within_ms": q.engine.within_ms,
                "engine": type(q.engine).__name__,
                "outputs": _handler_targets(q)}
    if type(q).__name__ == "PartitionQueryPort":
        block = q.block
        plan = next(p for p in block.plans if p.name == qname)
        return {"kind": "partition-query",
                "partition": block.name,
                "inputs": sorted(getattr(plan, "input_ids",
                                         {plan.input_id})),
                "outputs": sorted(set(_handler_targets(q))
                                  | {plan.target})}
    if isinstance(q, QueryRuntime):
        kind = "window" if qname.startswith("__window__") else "query"
        return {"kind": kind, "inputs": [q.in_schema.stream_id],
                "outputs": _handler_targets(q)}
    return {"kind": type(q).__name__, "inputs": [],
            "outputs": _handler_targets(q)}


def runtime_graph(rt) -> dict:
    """The junction dataflow graph: streams (with @Async/@OnError
    config), query/join/pattern/partition nodes with their insert-into
    edges, tables, named windows and aggregations — the same topology
    the PR 3 typecheck fixpoint runs over, read off the live wiring."""
    streams = {}
    for sid, j in sorted(rt.junctions.items()):
        entry = {"attributes": [[a.name, a.type.name]
                                for a in j.schema.attributes]}
        if j.async_conf is not None:
            entry["async"] = {"capacity": int(j.async_conf[0]),
                              "batch_max": int(j.async_conf[1])}
        if j.on_error_action != "LOG":
            entry["on_error"] = j.on_error_action
        streams[sid] = entry
    nodes = {}
    for qname, q in sorted(rt.queries.items()):
        nodes[qname] = _node_entry(rt, qname, q)
    for wid, wq in sorted(rt.named_windows.items()):
        nodes["window:" + wid] = {
            "kind": "named-window",
            "inputs": [wq.in_schema.stream_id],
            "window": _window_names(wq.operators),
            "outputs": _handler_targets(wq)}
    for aid in sorted(rt.aggregations):
        ad = rt.ast.aggregation_definitions.get(aid)
        nodes["aggregation:" + aid] = {
            "kind": "aggregation",
            "inputs": [ad.input.stream_id] if ad is not None else [],
            "outputs": []}
    tables = {}
    for tid, t in sorted(rt.tables.items()):
        tables[tid] = {"capacity": int(getattr(t, "cap", 0)),
                       "primary_key": list(getattr(t, "pk", ()))}
    for tid in sorted(rt.record_tables):
        tables.setdefault(tid, {})["store"] = True
    edges = []
    for qname, node in sorted(nodes.items()):
        for sid in node.get("inputs", ()):
            edges.append({"from": sid, "to": qname})
        for tgt in node.get("outputs", ()):
            edges.append({"from": qname, "to": tgt})
    return {"streams": streams, "nodes": nodes, "tables": tables,
            "edges": edges}


# ---------------------------------------------------------------------------
# decisions (planner choices + machine-readable reasons)
# ---------------------------------------------------------------------------


def _fusion_decisions(rt) -> dict:
    """Fusion segment membership and, for every plain query that did NOT
    fuse forward, the machine-readable reason its hop broke the chain
    (core/runtime.py _fusible_next_info)."""
    from ..core.runtime import QueryRuntime
    segments = []
    member_of = {}
    for q in rt.queries.values():
        ch = getattr(q, "_fused_chain", None)
        if ch is not None:
            segments.append({"head": ch.head.name,
                             "members": [m.name for m in ch.queries]})
            for m in ch.queries:
                member_of[m.name] = ch.name
    group_of = {}
    for j in rt.junctions.values():
        fo = getattr(j, "fanout", None)
        if fo is not None:
            for u in fo.units:
                head = getattr(u, "head", u)
                group_of[head.name] = fo.name
    queries = {}
    for qname, q in rt.queries.items():
        if type(q) is not QueryRuntime or qname.startswith("__window__"):
            continue
        entry = {"segment": member_of.get(qname)}
        if qname in group_of:
            # fused into a fan-out group on its input junction
            # (plan/optimizer.py — details under decisions.optimizer)
            entry["fanout_group"] = group_of[qname]
        elif qname not in member_of:
            nxt, reason = rt._fusible_next_info(q)
            entry["break"] = "fusible-but-unfused" if nxt is not None \
                else reason
        queries[qname] = entry
    segments.sort(key=lambda s: s["head"])
    return {"enabled": rt._fusion_enabled(), "segments": segments,
            "queries": queries}


def _query_decisions(rt) -> dict:
    """Per-node compiled-shape choices: operator chain, window classes,
    capacity caps (sort-heavy splitting), timer scheduling mode."""
    from ..core.runtime import (JoinQueryRuntime, PatternQueryRuntime,
                                QueryRuntime)
    out = {}
    for qname, q in rt.queries.items():
        if isinstance(q, JoinQueryRuntime):
            entry = {
                "kind": "join",
                "sides": {nm: _operator_names(q.side_ops[s])
                          for s, nm in (("L", "left"), ("R", "right"))},
                "selector": _operator_names(q.operators),
                "capacity_cap": q.max_step_capacity,
            }
        elif isinstance(q, PatternQueryRuntime):
            entry = {
                "kind": "pattern",
                "engine": type(q.engine).__name__,
                "states": len(q.engine.slots),
                "selector": _operator_names(q.operators),
                "capacity_cap": q.max_step_capacity,
            }
        elif type(q).__name__ == "PartitionQueryPort":
            continue  # covered by the partitions section
        elif isinstance(q, QueryRuntime):
            entry = {
                "kind": "query",
                "operators": _operator_names(q.operators),
                "windows": _window_names(q.operators),
                "capacity_cap": q.max_step_capacity,
                "host_due_timers": bool(q._host_due_all),
            }
        else:
            entry = {"kind": type(q).__name__}
        out[qname] = entry
    return out


def _partition_decisions(rt) -> dict:
    from ..parallel import sharding as _sh
    out = {}
    for name, block in sorted(rt.partitions.items()):
        entry = {
            "slots": int(block.K),
            "key_streams": sorted(block.key_specs),
            "key_kinds": {sid: spec[0]
                          for sid, spec in sorted(block.key_specs.items())},
            "queries": [p.name for p in block.plans],
            "capacity_cap": block.max_step_capacity,
        }
        if block.mesh is not None:
            axis = block.mesh.axis_names[0]
            entry["mesh"] = {
                "axis": axis,
                "n_devices": int(block.mesh.shape[axis]),
                "slots_per_device":
                    int(block.K) // int(block.mesh.shape[axis]),
                # PartitionSpec placement per state leaf, from the regex
                # rule table (parallel/sharding.py) — pure path/shape
                # metadata, zero device reads
                "placement": _sh.describe_placement(
                    {"slot_tbl": block.slot_tbl,
                     "qstates": block.qstates},
                    _sh.PARTITION_STATE_RULES, axis),
            }
        out[name] = entry
    return out


def _watermark_decisions(rt) -> dict:
    out = {}
    for sid, buf in sorted(rt._reorder.items()):
        conf = buf.conf
        entry = {"lateness_ms": int(conf.lateness_ms),
                 "policy": conf.policy,
                 "cap": int(conf.cap),
                 "dedup": bool(conf.dedup)}
        if conf.late_stream is not None:
            entry["late_stream"] = conf.late_stream
        out[sid] = entry
    return out


def _optimizer_decisions(rt) -> dict:
    """The plan optimizer's decision record (plan/optimizer.py
    build_plan): transformation switches, per-junction fan-out fusion
    with cause slugs, CSE share classes, pushdown moves and
    cost-evidence chunk caps. HASHED — a flipped optimizer decision is
    a plan change. Before start() (no derivation yet) only the switch
    state is known."""
    d = getattr(rt, "_opt_decisions", None)
    if d is not None:
        return d
    from ..plan.optimizer import opt_enabled
    return {"enabled": opt_enabled(), "derived": False}


def _compaction_decision() -> dict:
    from ..ops import windows as _w
    return {"variant": "region" if _w._REGION_COMPACTION else "sort",
            "env": "SIDDHI_TPU_WINDOW_COMPACTION"}


def runtime_decisions(rt) -> dict:
    """Every planner decision with its machine-readable reason — the
    hashed heart of the report."""
    # NOTE: rt._columnar is runtime-OBSERVED (flips on the first
    # columnar ingest), not planned — it rides `live`, never the hash
    decisions = {
        "playback": bool(rt._playback),
        "fusion": _fusion_decisions(rt),
        "optimizer": _optimizer_decisions(rt),
        "queries": _query_decisions(rt),
        "window_compaction": _compaction_decision(),
    }
    if rt._join_kernels:
        decisions["join_kernels"] = {
            k: dict(v) for k, v in sorted(rt._join_kernels.items())}
    wm = _watermark_decisions(rt)
    if wm:
        decisions["watermarks"] = wm
    if rt.partitions:
        decisions["partitions"] = _partition_decisions(rt)
    if rt.slo is not None:
        decisions["slo"] = rt.slo.objective.as_dict() \
            if rt.slo.objective is not None else None
    if rt.mesh is not None:
        axis = rt.mesh.axis_names[0]
        decisions["mesh"] = {"axis": axis,
                             "n_devices": int(rt.mesh.shape[axis])}
    return decisions


# ---------------------------------------------------------------------------
# live annotations (NEVER hashed)
# ---------------------------------------------------------------------------


def _runtime_live(rt) -> dict:
    """Per-edge traffic and pressure, folded in from the host-side
    registries the runtime already maintains: events/s (ingest
    trackers), @Async queue depth, watermark lag / reorder depth, and
    the persisted cost share per center (costs.json). No device
    reads — live numbers are host counters by the obs/ design rule."""
    streams = {}
    for sid, j in sorted(rt.junctions.items()):
        entry = {}
        tput = getattr(j, "throughput", None)
        if tput is not None:
            entry["events"] = tput.count
            eps = tput.events_per_sec()
            if eps is not None:
                entry["events_per_s"] = round(eps, 1)
        if j.async_conf is not None and j._queue is not None:
            entry["queue_depth"] = j._queue.qsize()
        buf = rt._reorder.get(sid)
        if buf is not None:
            entry["watermark"] = buf.watermark
            entry["watermark_lag_ms"] = buf.lag_ms
            entry["reorder_depth"] = buf.depth
        if entry:
            streams[sid] = entry
    live = {"running": bool(rt.running),
            "columnar": bool(rt._columnar), "streams": streams}
    cost_share = {}
    try:
        from .costmodel import load_costs
        tbl = load_costs().get(rt.name) or {}
        total = sum(v.get("ms_total", 0.0) for v in tbl.values())
        if total > 0:
            cost_share = {
                k: round(100.0 * v.get("ms_total", 0.0) / total, 1)
                for k, v in sorted(tbl.items())}
    except Exception:  # noqa: BLE001 — the cost table is advisory
        cost_share = {}
    if cost_share:
        live["cost_share_pct"] = cost_share
    return live


def _programs_section(compile_service) -> dict:
    """AOT program inventory: every warmed step with its compile ms,
    plus the persistent-cache hit/miss story (core/compile.py). When
    the static program auditor ran (analysis/programs.py), its summary
    block rides here too under ``audit``. Live telemetry — compile wall
    time and audit results must never move the plan hash."""
    summary = compile_service.summary(detail=True)
    steps = summary.pop("steps", [])
    summary["steps"] = [{"step": r["step"], "compile_ms": r["ms"],
                         **({"sharded": True} if r.get("sharded")
                            else {})}
                        for r in sorted(steps, key=lambda r: r["step"])]
    return summary


# ---------------------------------------------------------------------------
# the report object
# ---------------------------------------------------------------------------


class ExplainReport:
    """One assembled explain document. ``as_dict()`` is JSON-ready;
    ``plan_hash`` is stable across deploys of the same plan;
    ``diff(other)`` returns decision-level changes."""

    def __init__(self, report: dict):
        self.report = report

    @property
    def plan_hash(self) -> str:
        return self.report["plan_hash"]

    def as_dict(self) -> dict:
        return self.report

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.report, indent=indent, sort_keys=True,
                          default=str)

    def diff(self, other) -> dict:
        return explain_diff(self.report, other)

    def to_dot(self) -> str:
        return to_dot(self.report)

    def describe(self) -> str:
        return render_text(self.report)

    # -- assembly ---------------------------------------------------------

    @classmethod
    def from_runtime(cls, rt, live: bool = True) -> "ExplainReport":
        """Assemble from a deployed SiddhiAppRuntime. Zero new jitted
        programs, zero jit-option changes, zero device reads — a pure
        host-side view (the tested invariant)."""
        graph = runtime_graph(rt)
        decisions = runtime_decisions(rt)
        report = {
            "explain_version": EXPLAIN_VERSION,
            "app": rt.name,
            "plan_hash": compute_plan_hash(graph, decisions),
            "graph": graph,
            "decisions": decisions,
            "programs": _programs_section(rt.compile_service),
        }
        if live:
            report["live"] = _runtime_live(rt)
        return cls(report)

    @classmethod
    def from_pool(cls, pool, live: bool = True) -> "ExplainReport":
        """Assemble from a TenantPool: the TEMPLATE explains once (its
        plan_hash is shared by every pool of that template in the same
        environment); slot-axis facts — current slot count, active
        tenants, per-device placement — are live, never hashed (the
        slot axis grows by doubling with churn)."""
        from ..parallel import sharding as _sh
        proto = pool.proto
        graph = runtime_graph(proto)
        # the optimizer plans ONCE per template: decisions derive from
        # the never-started prototype (pure — no artifacts installed;
        # the pool's vmapped slot-axis dispatch is its own execution
        # strategy, recorded under decisions.pool)
        from ..plan.optimizer import describe_decisions
        decisions = {
            "template": pool.template.key,
            "optimizer": describe_decisions(proto),
            "queries": _query_decisions(proto),
            "window_compaction": _compaction_decision(),
            "pool": {
                "order": list(pool._order),
                "ingest_stream": pool.ingest_stream,
                "ingest_streams": list(pool.ingest_streams),
                # operator class per pooled node (chain / pattern /
                # join / agg) — plan, not live: it derives from the
                # template and picks the vmapped step variants
                "kinds": {qn: pool._kind[qn] for qn in pool._order},
                "terminal_streams": list(pool._terminal),
                "batch_max": int(pool.batch_max),
                "max_tenants": int(pool.max_tenants),
                "state_quota_bytes": pool.state_quota_bytes,
                "execution": "vmap-slot-axis",
                "packed_ingest": bool(pool._packed_on),
            },
            "slo": pool.slo_engine.objective.as_dict()
            if pool.slo_engine.objective is not None else None,
            # QoS dials are plan (they shape scheduling for every
            # tenant); per-tenant weights/breaker states are live facts
            # and never hashed (serving/qos.py)
            "qos": pool._qos.describe() if pool._qos is not None
            else None,
        }
        if pool.mesh is not None:
            decisions["mesh"] = {
                "axis": pool.mesh_axis,
                "n_devices": int(pool.n_devices),
                # rule-table placement per state leaf (slot axis shards;
                # paths are stable across slot-axis growth)
                "placement": _sh.describe_placement(
                    {"states": pool._states, "emitted": pool._emitted},
                    _sh.POOL_STATE_RULES, pool.mesh_axis),
            }
        report = {
            "explain_version": EXPLAIN_VERSION,
            "app": pool.name,
            "pool": pool.name,
            "template": pool.template.key,
            "plan_hash": compute_plan_hash(graph, decisions),
            "graph": graph,
            "decisions": decisions,
            "programs": _programs_section(proto.compile_service),
        }
        if live:
            report["live"] = {
                "slots": int(pool.slots),
                "slots_per_device": int(pool.slots_per_device),
                "active_tenants": len(pool._tenants),
                "rounds": int(pool._rounds),
                "grows": int(pool._grows),
            }
        return cls(report)


# ---------------------------------------------------------------------------
# diff
# ---------------------------------------------------------------------------


def _walk_diff(path: tuple, a, b, changes: list) -> None:
    if isinstance(a, dict) and isinstance(b, dict):
        for k in sorted(set(a) | set(b)):
            _walk_diff(path + (str(k),), a.get(k, _MISSING),
                       b.get(k, _MISSING), changes)
        return
    if a != b:
        changes.append({"path": ".".join(path), "a": a, "b": b,
                        "summary": f"{'.'.join(path)}: {a!r} -> {b!r}"})


def explain_diff(a, b) -> dict:
    """Decision-level diff of two reports (dicts or ExplainReports):
    walks exactly the hashed sections (``decisions`` then ``graph``)
    and returns ``{equal, plan_hash_a, plan_hash_b, changes: [{path,
    a, b, summary}]}``. Lists compare wholesale — a reordered fusion
    segment IS a plan change."""
    ra = a.report if isinstance(a, ExplainReport) else a
    rb = b.report if isinstance(b, ExplainReport) else b
    changes: list = []
    _walk_diff(("decisions",), ra.get("decisions", {}),
               rb.get("decisions", {}), changes)
    _walk_diff(("graph",), ra.get("graph", {}), rb.get("graph", {}),
               changes)
    return {"equal": not changes,
            "plan_hash_a": ra.get("plan_hash"),
            "plan_hash_b": rb.get("plan_hash"),
            "changes": changes}


# ---------------------------------------------------------------------------
# renderers
# ---------------------------------------------------------------------------

_DOT_SHAPES = {"query": "box", "join": "diamond", "pattern": "hexagon",
               "partition-query": "box3d", "named-window": "component",
               "aggregation": "cylinder", "window": "component"}


def _dot_id(name: str) -> str:
    return '"' + name.replace('"', r"\"") + '"'


def to_dot(report: dict) -> str:
    """Graphviz digraph of the junction dataflow graph: streams as
    ellipses, queries per kind, fused segments boxed in clusters."""
    graph = report.get("graph", {})
    decisions = report.get("decisions", {})
    lines = ["digraph plan {", "  rankdir=LR;",
             f'  label="{report.get("app", "")} '
             f'plan={report.get("plan_hash", "")}";']
    for sid in sorted(graph.get("streams", ())):
        lines.append(f"  {_dot_id(sid)} [shape=ellipse];")
    segments = decisions.get("fusion", {}).get("segments", [])
    fused = {m for s in segments for m in s["members"]}
    for i, seg in enumerate(segments):
        lines.append(f"  subgraph cluster_fuse{i} {{")
        lines.append('    label="fused segment"; style=dashed;')
        for m in seg["members"]:
            lines.append(f"    {_dot_id(m)} [shape=box];")
        lines.append("  }")
    for qname, node in sorted(graph.get("nodes", {}).items()):
        if qname in fused:
            continue
        shape = _DOT_SHAPES.get(node.get("kind"), "box")
        lines.append(f"  {_dot_id(qname)} [shape={shape}];")
    for edge in graph.get("edges", ()):
        lines.append(f"  {_dot_id(edge['from'])} -> "
                     f"{_dot_id(edge['to'])};")
    lines.append("}")
    return "\n".join(lines) + "\n"


def render_text(report: dict) -> str:
    """Human-readable explain: the decisions section as an indented
    outline (the CLI's default output)."""
    out = [f"app: {report.get('app')}",
           f"plan_hash: {report.get('plan_hash')}"]
    decisions = report.get("decisions", {})
    fusion = decisions.get("fusion")
    if fusion:
        out.append("fusion:")
        for seg in fusion.get("segments", []):
            out.append("  segment: " + " -> ".join(seg["members"]))
        for qn, e in sorted(fusion.get("queries", {}).items()):
            if e.get("segment") is None:
                out.append(f"  {qn}: unfused ({e.get('break')})")
    opt = decisions.get("optimizer")
    if opt is not None:
        out.append(f"optimizer: {'on' if opt.get('enabled') else 'off'}")
        for sid, e in sorted((opt.get("fanout") or {}).items()):
            state = "fused" if e.get("fused") else "UNFUSED"
            out.append(f"  fanout {sid}: {state} [{e.get('cause')}] "
                       f"members={e.get('members')}")
            for cls in e.get("cse", ()):
                out.append(f"    shared prefix x{cls['ops']}: "
                           f"{cls['queries']}")
        for seg, moves in sorted((opt.get("pushdown") or {}).items()):
            for mv in moves:
                out.append(f"  pushdown {seg}: {mv['filter_of']} filter "
                           f"hoisted past {mv['hoisted_past']}")
        for key, e in sorted((opt.get("chunk_caps") or {}).items()):
            out.append(f"  chunk cap {key}: {e['cap']} [{e['cause']}]")
    jk = decisions.get("join_kernels")
    if jk:
        out.append("join kernels:")
        for side, e in sorted(jk.items()):
            out.append(f"  {side}: {e['kernel']} [{e.get('cause')}] "
                       f"— {e.get('reason')}")
    wm = decisions.get("watermarks")
    if wm:
        out.append("watermarks:")
        for sid, e in sorted(wm.items()):
            out.append(f"  {sid}: lateness={e['lateness_ms']}ms "
                       f"policy={e['policy']} cap={e['cap']}")
    if decisions.get("slo") is not None:
        out.append(f"slo: {decisions['slo']}")
    parts = decisions.get("partitions")
    if parts:
        out.append("partitions:")
        for name, e in sorted(parts.items()):
            mesh = e.get("mesh")
            extra = (f" mesh={mesh['n_devices']}x@{mesh['axis']}"
                     if mesh else "")
            out.append(f"  {name}: slots={e['slots']} "
                       f"queries={e['queries']}{extra}")
    wc = decisions.get("window_compaction", {})
    out.append(f"window compaction: {wc.get('variant')}")
    progs = report.get("programs", {})
    if progs.get("programs"):
        out.append(f"programs: {progs['programs']} compiled in "
                   f"{progs.get('compile_ms')} ms "
                   f"(cache {progs.get('cache_hits')} hits / "
                   f"{progs.get('cache_misses')} misses)")
    live = report.get("live")
    if live and live.get("streams"):
        out.append("live edges:")
        for sid, e in sorted(live["streams"].items()):
            bits = [f"{k}={v}" for k, v in sorted(e.items())]
            out.append(f"  {sid}: " + " ".join(bits))
    return "\n".join(out) + "\n"
