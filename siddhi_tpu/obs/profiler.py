"""Profiler hooks: ``runtime.profile(path)`` around
``jax.profiler.start_trace/stop_trace``, and opt-in ``jax.named_scope``
labels inside step traces.

Named scopes are STRICTLY opt-in behind ``SIDDHI_TPU_PROFILE_SCOPES=1``:
scope metadata changes the lowered HLO, which changes the persistent
compile-cache key (docs/compile_cache.md cache-key rules) — flipping
the default would invalidate every existing ``.jax_cache`` entry. The
env var is read at trace time (traces are rare; dispatches are not), so
enabling it recompiles the steps exactly once per process.
"""
from __future__ import annotations

import contextlib
import os

SCOPES_ENV = "SIDDHI_TPU_PROFILE_SCOPES"


def scopes_enabled() -> bool:
    return os.environ.get(SCOPES_ENV, "") == "1"


def op_scope(name: str):
    """``jax.named_scope(name)`` when profiling scopes are enabled, else
    a nullcontext — used around each operator inside step traces so
    device profiles attribute time to operators instead of one opaque
    fused computation."""
    if not scopes_enabled():
        return contextlib.nullcontext()
    import jax
    return jax.named_scope(name)


@contextlib.contextmanager
def profile(path: str):
    """Capture a device profile of the enclosed block into ``path``
    (TensorBoard/XProf trace directory)::

        with runtime.profile('/tmp/prof'):
            handler.send_arrays(ts, cols)
    """
    import jax
    jax.profiler.start_trace(path)
    try:
        yield path
    finally:
        jax.profiler.stop_trace()
