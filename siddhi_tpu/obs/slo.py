"""SLO engine: per-tenant / per-query ingest-to-emit latency attribution,
multi-window burn-rate alerting, saturation signals, and a flight
recorder.

PR 6/7 answered *what flowed* (counters) and *which step eats device
time* (cost profiler); this module answers the serving question ROADMAP
item 2 is graded against: **is every tenant meeting its latency
objective, and if not, why?**

Measurement model (the PR 7 lesson, unchanged): on an async device
pipeline the only honest ingest->emit number is host wall time around
work that is *provably finished*, so the engine samples with a stride
(``SIDDHI_TPU_SLO_EVERY``, default 64; the first span always samples so
short runs still report) and puts the ``block_until_ready`` /
host-decode sync on the sampled branch only. Zero jit options change —
persistent compile-cache keys stay stable (docs/compile_cache.md) — and
collection-time device reads stay batched: the pool's registry walk
still makes ONE ``device_get`` per pool with SLO tracking on
(tests/test_slo.py asserts the count).

Attribution points:

- ``TenantPool.send`` stamps every queued chunk with its host arrival
  time; on a sampled fair round the pool syncs after each vmapped query
  step and attributes ``arrival -> query emitted`` per (tenant, query)
  plus tenant- and pool-level end-to-end spans.
- ``InputHandler.send/send_arrays`` open a sampled span; each query that
  decodes host rows for its sinks/callbacks during the dispatch marks
  ``ingest -> emit`` under its own name (the host decode already forced
  the device sync, so the number is honest). Fused segments attribute to
  the tail member — the segment is one XLA program.

Burn-rate semantics (the standard multi-window model): an objective is a
latency bound (``p99='250 ms'``) plus a target attainment
(``target='0.99'``). A sample is *bad* when it exceeds the bound; the
error budget is ``1 - target``. ``burn = bad_fraction / budget`` over
the FAST (default 5 min) and SLOW (default 1 h) windows;
``min(burn_fast, burn_slow)`` >= ``warn.burn`` trips WARN, >=
``page.burn`` trips PAGE. Requiring BOTH windows to burn keeps one
slow chunk from paging while still paging fast on a real regression.

The **flight recorder** is a bounded ring of recent spans, admission
rejections and state transitions; entering PAGE (or an explicit caller
trigger: deploy failure, chaos-scenario failure) dumps the ring plus a
context snapshot as a JSON artifact under
``<SIDDHI_TPU_CACHE_DIR>/flightrec/`` so the breach is diagnosable
after the fact. See docs/observability.md "SLO engine".
"""
from __future__ import annotations

import dataclasses
import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Optional

EVERY_ENV = "SIDDHI_TPU_SLO_EVERY"
DEFAULT_EVERY = 64
FLIGHT_DIR_ENV = "SIDDHI_TPU_FLIGHT_DIR"

FAST_WINDOW_MS = 5 * 60 * 1000       # fast burn window (5 min)
SLOW_WINDOW_MS = 60 * 60 * 1000      # slow burn window / SLO window (1 h)
DEFAULT_TARGET = 0.99
DEFAULT_WARN_BURN = 2.0
DEFAULT_PAGE_BURN = 14.4             # the classic 30d-budget page rate

# bounded per-scope reservoir (same windowed model as obs/metrics
# Histogram; scopes are per tenant/query so the cap bounds memory at
# O(scopes * cap))
WINDOW_CAP = 4096

OK, WARN, PAGE = "OK", "WARN", "PAGE"
_STATE_NUM = {OK: 0, WARN: 1, PAGE: 2}

_TIME = re.compile(
    r"(\d+(?:\.\d+)?)\s*(millisecond|milliseconds|ms|sec|second|seconds|"
    r"s|min|minute|minutes|hour|hours|h)?")
_UNIT_MS = {"millisecond": 1, "milliseconds": 1, "ms": 1,
            "sec": 1000, "second": 1000, "seconds": 1000, "s": 1000,
            "min": 60_000, "minute": 60_000, "minutes": 60_000,
            "hour": 3_600_000, "hours": 3_600_000, "h": 3_600_000}


def _time_ms(value, role: str) -> float:
    """'250 ms' / '5 sec' / bare ms number -> milliseconds (ValueError
    on anything else — the ``slo-config`` plan rule's to surface)."""
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        ms = float(value)
    else:
        m = _TIME.fullmatch(str(value).strip().strip("'\""))
        if not m:
            raise ValueError(
                f"{role}: cannot parse time '{value}' "
                "(expected e.g. '250 ms', '5 sec', '1 min')")
        ms = float(m.group(1)) * _UNIT_MS[m.group(2) or "ms"]
    if ms <= 0:
        raise ValueError(f"{role}: must be positive, got {value!r}")
    return ms


def default_flight_dir() -> str:
    """Artifact directory: SIDDHI_TPU_FLIGHT_DIR, else ``flightrec/``
    next to the persistent compile cache (costs.json's neighborhood)."""
    d = os.environ.get(FLIGHT_DIR_ENV)
    if d:
        return d
    cache = os.environ.get("SIDDHI_TPU_CACHE_DIR") or "./.jax_cache"
    return os.path.join(cache, "flightrec")


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """One latency objective: bound(s) + target attainment + burn
    windows. ``p99_ms`` is the burn-rate bound; ``p50_ms`` is an
    additional reported bound (attainment only, no paging)."""

    p99_ms: Optional[float] = None
    p50_ms: Optional[float] = None
    target: float = DEFAULT_TARGET
    window_ms: float = SLOW_WINDOW_MS     # slow burn / SLO window
    fast_ms: float = FAST_WINDOW_MS       # fast burn window
    warn_burn: float = DEFAULT_WARN_BURN
    page_burn: float = DEFAULT_PAGE_BURN
    every: Optional[int] = None           # sampling stride override

    @property
    def bound_ms(self) -> Optional[float]:
        return self.p99_ms if self.p99_ms is not None else self.p50_ms

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    def as_dict(self) -> dict:
        d = {"target": self.target,
             "window_ms": self.window_ms, "fast_ms": self.fast_ms,
             "warn_burn": self.warn_burn, "page_burn": self.page_burn}
        if self.p99_ms is not None:
            d["p99_ms"] = self.p99_ms
        if self.p50_ms is not None:
            d["p50_ms"] = self.p50_ms
        return d


def config_from_annotation(ann) -> SLOObjective:
    """``@app:slo(p99='250 ms', target='0.99', window='1 hour',
    fast='5 min', warn.burn='2', page.burn='14.4', every='64')`` ->
    SLOObjective. Raises ValueError on any bad value — shared by the
    ``slo-config`` plan rule (parse time) and the planner backstop
    (validate=False / hand-built ASTs) so validation cannot drift from
    planner behavior (the watermark-config pattern)."""
    def num(key, role, lo=None):
        v = ann.element(key)
        if v is None:
            return None
        try:
            f = float(str(v).strip().strip("'\""))
        except ValueError:
            raise ValueError(f"@app:slo {role}: cannot parse '{v}'")
        if lo is not None and f <= lo:
            raise ValueError(f"@app:slo {role}: must be > {lo}, got {v}")
        return f

    p99 = ann.element("p99")
    p50 = ann.element("p50")
    if p99 is None and p50 is None:
        raise ValueError(
            "@app:slo needs a latency bound: p99='...' and/or p50='...'")
    kw: dict = {}
    if p99 is not None:
        kw["p99_ms"] = _time_ms(p99, "@app:slo p99")
    if p50 is not None:
        kw["p50_ms"] = _time_ms(p50, "@app:slo p50")
    target = num("target", "target", lo=0.0)
    if target is not None:
        if not (0.0 < target < 1.0):
            raise ValueError(
                f"@app:slo target: must be in (0, 1), got {target}")
        kw["target"] = target
    w = ann.element("window")
    if w is not None:
        kw["window_ms"] = _time_ms(w, "@app:slo window")
    f = ann.element("fast")
    if f is not None:
        kw["fast_ms"] = _time_ms(f, "@app:slo fast")
    if kw.get("fast_ms", FAST_WINDOW_MS) > kw.get("window_ms",
                                                  SLOW_WINDOW_MS):
        raise ValueError(
            "@app:slo fast window must not exceed the slow window")
    wb = num("warn.burn", "warn.burn", lo=0.0)
    pb = num("page.burn", "page.burn", lo=0.0)
    if wb is not None:
        kw["warn_burn"] = wb
    if pb is not None:
        kw["page_burn"] = pb
    if kw.get("warn_burn", DEFAULT_WARN_BURN) > \
            kw.get("page_burn", DEFAULT_PAGE_BURN):
        raise ValueError("@app:slo warn.burn must not exceed page.burn")
    ev = ann.element("every")
    if ev is not None:
        try:
            n = int(str(ev).strip().strip("'\""))
        except ValueError:
            n = 0
        if n <= 0:
            raise ValueError(
                f"@app:slo every: must be a positive integer, got '{ev}'")
        kw["every"] = n
    return SLOObjective(**kw)


def objective_from_dials(dials: dict) -> SLOObjective:
    """Pool-level ``slo={...}`` dial -> SLOObjective (constructor-style
    keys; time-ish values accept '250 ms' strings too)."""
    kw: dict = {}
    for key in ("p99_ms", "p50_ms", "window_ms", "fast_ms"):
        if key in dials and dials[key] is not None:
            kw[key] = _time_ms(dials[key], f"slo dial {key}")
    for key in ("target", "warn_burn", "page_burn"):
        if key in dials and dials[key] is not None:
            kw[key] = float(dials[key])
    if "target" in kw and not (0.0 < kw["target"] < 1.0):
        raise ValueError(
            f"slo dial target must be in (0, 1), got {kw['target']}")
    if "every" in dials and dials["every"] is not None:
        kw["every"] = max(1, int(dials["every"]))
    if kw.get("p99_ms") is None and kw.get("p50_ms") is None:
        raise ValueError(
            "slo dial needs a latency bound: p99_ms and/or p50_ms")
    return SLOObjective(**kw)


class FlightRecorder:
    """Bounded ring of recent observability events (sampled spans,
    admission rejections, state transitions) that ``dump()`` serializes
    — with a caller-supplied context snapshot — into a JSON artifact.

    The ring records host-side dicts only: no device reads, no locks
    beyond its own. ``dump()`` writes tmp+rename (the filesystem error
    store's atomicity contract) and returns the artifact path; callers
    put that path in log lines and assertion messages so a failed run
    is diagnosable after the process is gone."""

    CAP = 256

    # artifact identity keys: EVERY dump's context carries them (None
    # when unknown) so an artifact is always attributable to its app /
    # pool / plan without guessing from the filename
    IDENTITY_KEYS = ("app", "pool", "plan_hash")

    def __init__(self, name: str, cap: int = CAP,
                 dirpath: Optional[str] = None,
                 identity_fn: Optional[Callable[[], dict]] = None):
        self.name = name
        self.dirpath = dirpath
        # identity_fn() -> {"app": ..., "pool": ..., "plan_hash": ...}
        # evaluated at dump time (plan hashes can change on live graph
        # edits); owners set it after construction when the identity is
        # not known yet (SiddhiAppRuntime / TenantPool wiring)
        self.identity_fn = identity_fn
        self._ring: deque = deque(maxlen=max(1, int(cap)))
        self._lock = threading.Lock()
        self._seq = 0
        self.dumps: list[str] = []

    def _identity(self) -> dict:
        ident = {k: None for k in self.IDENTITY_KEYS}
        if self.identity_fn is not None:
            try:
                got = self.identity_fn() or {}
                ident.update({k: got[k] for k in self.IDENTITY_KEYS
                              if k in got})
            except Exception:  # noqa: BLE001 — identity is best-effort
                pass           # at dump time; the dump must still land
        return ident

    def record(self, kind: str, **data) -> None:
        entry = {"t_wall_ms": int(time.time() * 1000), "kind": kind}
        entry.update(data)
        with self._lock:
            self._ring.append(entry)

    def snapshot(self) -> list:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str, context: Optional[dict] = None,
             path: Optional[str] = None) -> str:
        """Write the artifact; returns its path. Artifact schema
        (docs/observability.md): ``{name, reason, dumped_at_ms, spans:
        [ring entries oldest-first], context: {app, pool, plan_hash,
        ...}}`` — the identity triple is ALWAYS present (None when
        unknown) so every artifact is attributable to its app/pool and
        the plan that produced it, no matter which path triggered the
        dump (PAGE transition, deploy failure, chaos failure)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            spans = list(self._ring)
        if path is None:
            d = self.dirpath or default_flight_dir()
            os.makedirs(d, exist_ok=True)
            slug = re.sub(r"[^A-Za-z0-9._-]", "_", f"{self.name}.{reason}")
            path = os.path.join(
                d, f"{slug}.{int(time.time() * 1000)}.{seq}.json")
        payload = {"name": self.name, "reason": reason,
                   "dumped_at_ms": int(time.time() * 1000),
                   "spans": spans,
                   "context": {**self._identity(), **(context or {})}}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True, default=str)
        os.replace(tmp, path)
        with self._lock:
            self.dumps.append(path)
        return path


class _Window:
    """Bounded (wall_ms, latency_ms) reservoir for one scope."""

    __slots__ = ("samples", "count", "sum")

    def __init__(self):
        self.samples: deque = deque(maxlen=WINDOW_CAP)
        self.count = 0      # cumulative, survives reservoir wrap
        self.sum = 0.0

    def add(self, t_ms: float, lat_ms: float) -> None:
        self.samples.append((t_ms, lat_ms))
        self.count += 1
        self.sum += lat_ms

    def in_window(self, now_ms: float, window_ms: float) -> list:
        lo = now_ms - window_ms
        return [v for t, v in self.samples if t >= lo]


def _percentiles(vals: list) -> dict:
    s = sorted(vals)
    n = len(s)
    if not n:
        return {}
    return {"p50_ms": round(s[n // 2], 3),
            "p95_ms": round(s[min(n - 1, (n * 95) // 100)], 3),
            "p99_ms": round(s[min(n - 1, (n * 99) // 100)], 3)}


def scope_name(labels: tuple) -> str:
    """``()`` -> 'total'; ``(("tenant","t1"),("query","q"))`` ->
    'tenant=t1,query=q' — the statistics()['slo']['scopes'] key."""
    if not labels:
        return "total"
    return ",".join(f"{k}={v}" for k, v in labels)


class SLOEngine:
    """Latency objective tracking for one app runtime or tenant pool.

    Hot-path contract (the obs/ design rule): ``observe()`` appends one
    tuple to a bounded deque under the engine lock — no device work.
    The sampled sync that makes a latency honest lives at the CALL
    sites (pool round drain / host row decode), on the sampled branch
    only. ``evaluate()`` / ``publish()`` run at collection time.

    Scope keys are label tuples: ``()`` is the app/pool aggregate,
    ``(("tenant", tid),)``, ``(("query", q),)`` and
    ``(("tenant", tid), ("query", q))`` the attribution axes — the same
    labels the Prometheus exposition carries (no dotted-name
    cardinality explosion; docs/observability.md)."""

    def __init__(self, name: str, objective: Optional[SLOObjective] = None,
                 every: Optional[int] = None,
                 recorder: Optional[FlightRecorder] = None,
                 context_fn: Optional[Callable[[], dict]] = None):
        self.name = name
        self.objective = objective
        if every is None:
            every = objective.every if objective is not None and \
                objective.every else None
        if every is None:
            every = max(1, int(os.environ.get(EVERY_ENV, "")
                               or DEFAULT_EVERY))
        self.every = max(1, int(every))
        self.recorder = recorder
        self.context_fn = context_fn
        # RLock: a collector walk may re-enter via publish() while a
        # dispatch thread observes (the PR 7 registry race pattern)
        self._lock = threading.RLock()
        self._windows: dict[tuple, _Window] = {}
        self._ticks: dict = {}
        self._states: dict[tuple, str] = {}
        self._tls = threading.local()
        self.breaches = 0          # transitions into PAGE

    # -- stride sampling --------------------------------------------------
    def tick(self, site) -> bool:
        """True on the sampled stride for ``site`` (first call always —
        short runs must still report)."""
        with self._lock:
            n = self._ticks.get(site, 0)
            self._ticks[site] = n + 1
        return n % self.every == 0

    # -- ingest->emit span (runtime path; see core/stream.py) ------------
    def ingest_begin(self, stream_id: str):
        """Open a sampled ingest span on this thread; returns a token
        (None off-stride). Queries that decode host rows during the
        dispatch call ``on_emit`` and attribute against this span."""
        if not self.tick(("ingest", stream_id)):
            return None
        self._tls.t0 = time.perf_counter()
        self._tls.emitted = False
        return stream_id

    def on_emit(self, query: str, rows: int = 0) -> None:
        """Ingest->emit mark for one query: host rows for its
        sinks/callbacks just materialized (the device_get that decoded
        them already forced the sync — honest by construction)."""
        t0 = getattr(self._tls, "t0", None)
        if t0 is None:
            return
        dt_ms = (time.perf_counter() - t0) * 1000.0
        self._tls.emitted = True
        self.observe((("query", query),), dt_ms, rows=rows)

    def ingest_end(self, token) -> None:
        """Close the span; records the aggregate end-to-end sample iff
        some query emitted during it (otherwise there was no host-side
        sync and the number would be a dispatch-enqueue time, not an
        ingest->emit latency)."""
        t0 = getattr(self._tls, "t0", None)
        self._tls.t0 = None
        if t0 is None or not getattr(self._tls, "emitted", False):
            return
        self.observe((), (time.perf_counter() - t0) * 1000.0)

    # -- recording --------------------------------------------------------
    def observe(self, labels: tuple, lat_ms: float,
                t_wall_ms: Optional[float] = None, rows: int = 0) -> None:
        """One latency sample for a scope. ``labels`` is a tuple of
        (name, value) pairs (possibly empty = aggregate); ``t_wall_ms``
        defaults to now (tests inject explicit times for deterministic
        window math)."""
        t = time.time() * 1000.0 if t_wall_ms is None else float(t_wall_ms)
        with self._lock:
            w = self._windows.get(labels)
            if w is None:
                w = self._windows[labels] = _Window()
            w.add(t, float(lat_ms))
        if self.recorder is not None:
            self.recorder.record("span", scope=scope_name(labels),
                                 lat_ms=round(lat_ms, 3), rows=rows)

    def percentiles_since(self, labels: tuple,
                          since_wall_ms: float) -> dict:
        """Latency percentiles for one scope restricted to samples at
        or after ``since_wall_ms`` (epoch ms) — the before/after phase
        split the migration scenarios and bench use to show a starved
        tenant's p99 recovering across a move. Returns counts only
        ({'count': 0}) when the scope has no samples in range."""
        with self._lock:
            w = self._windows.get(labels)
            vals = [v for t, v in w.samples
                    if t >= since_wall_ms] if w is not None else []
        out = _percentiles(vals)
        out["count"] = len(vals)
        return out

    # -- evaluation -------------------------------------------------------
    def _scope_entry(self, w: _Window, now_ms: float) -> dict:
        obj = self.objective
        slow_ms = obj.window_ms if obj else SLOW_WINDOW_MS
        vals = w.in_window(now_ms, slow_ms)
        entry = {"count": w.count, "window_count": len(vals),
                 **_percentiles(vals)}
        if obj is None or not vals:
            return entry
        bound = obj.bound_ms
        bad_slow = sum(1 for v in vals if v > bound)
        fast_vals = w.in_window(now_ms, obj.fast_ms)
        bad_fast = sum(1 for v in fast_vals if v > bound)
        frac_slow = bad_slow / len(vals)
        frac_fast = bad_fast / len(fast_vals) if fast_vals else 0.0
        burn_slow = frac_slow / obj.budget
        burn_fast = frac_fast / obj.budget
        # round before thresholding: 1 - target is not exactly
        # representable (0.02/0.01 must compare as exactly 2.0)
        burn = round(min(burn_fast, burn_slow), 9)
        state = PAGE if burn >= obj.page_burn else \
            WARN if burn >= obj.warn_burn else OK
        entry.update({
            "attainment": round(1.0 - frac_slow, 5),
            "burn_fast": round(burn_fast, 3),
            "burn_slow": round(burn_slow, 3),
            "state": state,
        })
        if obj.p50_ms is not None and "p50_ms" in entry:
            entry["p50_attained"] = entry["p50_ms"] <= obj.p50_ms
        return entry

    def evaluate(self, now_ms: Optional[float] = None,
                 saturation: Optional[dict] = None) -> dict:
        """The SLO report: per-scope percentiles, attainment, fast/slow
        burn rates and WARN/PAGE states. Detects state transitions; a
        transition into PAGE auto-dumps the flight recorder (once per
        transition, not per scrape)."""
        now = time.time() * 1000.0 if now_ms is None else float(now_ms)
        with self._lock:
            snapshot = list(self._windows.items())
        scopes: dict = {}
        transitions: list = []
        worst = OK
        for labels, w in snapshot:
            entry = self._scope_entry(w, now)
            sname = scope_name(labels)
            scopes[sname] = entry
            st = entry.get("state")
            if st is not None:
                if _STATE_NUM[st] > _STATE_NUM[worst]:
                    worst = st
                with self._lock:
                    prev = self._states.get(labels, OK)
                    if st != prev:
                        self._states[labels] = st
                        transitions.append((sname, prev, st))
        paged = [t for t in transitions if t[2] == PAGE]
        if self.recorder is not None:
            for sname, prev, st in transitions:
                self.recorder.record("slo-state", scope=sname,
                                     frm=prev, to=st)
        report = {"name": self.name, "every": self.every,
                  "objective": self.objective.as_dict()
                  if self.objective else None,
                  "state": worst if self.objective else None,
                  "breaches": self.breaches,
                  "scopes": scopes}
        if saturation is not None:
            report["saturation"] = saturation
        if paged:
            self.breaches += len(paged)
            report["breaches"] = self.breaches
            if self.recorder is not None:
                ctx = {"slo": {k: v for k, v in report.items()
                               if k != "saturation"},
                       "paged_scopes": [s for s, _p, _t in paged]}
                if saturation is not None:
                    ctx["saturation"] = saturation
                if self.context_fn is not None:
                    try:
                        ctx["runtime"] = self.context_fn()
                    except Exception:  # noqa: BLE001 — context is
                        pass           # best-effort at dump time
                report["flight_artifact"] = self.recorder.dump(
                    "slo-breach", context=ctx)
        if self.recorder is not None and self.recorder.dumps:
            report["flight_artifacts"] = list(self.recorder.dumps)
        return report

    @property
    def state(self) -> str:
        """Worst current scope state (cheap view over the last
        evaluate(); OK before any evaluation)."""
        with self._lock:
            states = list(self._states.values())
        worst = OK
        for s in states:
            if _STATE_NUM[s] > _STATE_NUM[worst]:
                worst = s
        return worst

    # -- registry publication (labeled families) -------------------------
    def publish(self, registry, prefix: str,
                now_ms: Optional[float] = None) -> None:
        """Set labeled gauges — ONE metric family per measure
        (``<prefix>.p99_ms`` etc.) with ``tenant=``/``query=`` labels,
        never a dotted name per tenant — and prune scopes that vanished
        (departed tenants must not leak stale samples into scrapes)."""
        now = time.time() * 1000.0 if now_ms is None else float(now_ms)
        with self._lock:
            snapshot = list(self._windows.items())
        fams = ("p50_ms", "p95_ms", "p99_ms", "attainment",
                "burn_fast", "burn_slow", "state", "window_count")
        keep: dict[str, set] = {f"{prefix}.{f}": set() for f in fams}
        for labels, w in snapshot:
            entry = self._scope_entry(w, now)
            ld = dict(labels)
            mid = "".join(f"{k}.{v}." for k, v in labels)
            for fam in fams:
                v = entry.get(fam)
                if fam == "state" and v is not None:
                    v = _STATE_NUM[v]
                if v is None or (isinstance(v, float) and math.isnan(v)):
                    continue
                family = f"{prefix}.{fam}"
                dotted = f"{prefix}.{mid}{fam}" if mid else family
                registry.labeled_gauge(
                    family, ld, dotted=dotted,
                    help=_FAMILY_HELP.get(fam)).set(v)
                keep[family].add(dotted)
        for family, dotted in keep.items():
            registry.prune_family(family, dotted)


_FAMILY_HELP = {
    "p50_ms": "ingest-to-emit latency p50 over the SLO window (ms)",
    "p95_ms": "ingest-to-emit latency p95 over the SLO window (ms)",
    "p99_ms": "ingest-to-emit latency p99 over the SLO window (ms)",
    "attainment": "fraction of samples inside the latency bound "
                  "over the SLO window",
    "burn_fast": "error-budget burn rate over the fast window",
    "burn_slow": "error-budget burn rate over the slow window",
    "state": "SLO state: 0=OK 1=WARN 2=PAGE",
    "window_count": "latency samples inside the SLO window",
}
