"""Observability subsystem: metrics registry + reporters, chunk-span
tracing, and profiler hooks.

Reference mapping:
- util/statistics/* (SiddhiStatisticsManager, Dropwizard trackers,
  periodic reporters configured via
  ``@app:statistics(reporter='console', interval='5 sec')``)
- the per-event trace hooks of SiddhiAppRuntimeImpl.setStatisticsLevel.

Design rule for an async device pipeline (docs/observability.md): the
hot path RECORDS into host-side trackers and ring buffers only — no
device syncs, no locks beyond what the runtime already holds. All
device reads (state bytes, emitted counters) happen at COLLECTION time
(a scrape, a reporter tick, a ``statistics()`` call), batched into one
pytree transfer under the app barrier. BASIC-level metrics therefore
cost nothing per chunk.
"""
from .costmodel import CostProfiler, load_costs  # noqa: F401
from .explain import ExplainReport, explain_diff  # noqa: F401
from .metrics import Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .slo import FlightRecorder, SLOEngine, SLOObjective  # noqa: F401
from .tracing import ChunkTracer, maybe_span  # noqa: F401
