"""Chunk-span tracing: host-side spans around the chunk pipeline
(ingest -> junction -> step dispatch -> sink), recorded into a bounded
ring buffer and exportable as Chrome ``trace_event`` JSON
(``chrome://tracing`` / Perfetto loadable).

Span semantics on an async device pipeline: a span measures HOST wall
time around a dispatch, not device execution time (the step may still
be running when the span closes — that is the pipeline working as
designed). Device-side timing comes from ``runtime.profile(path)``
(obs/profiler.py), which captures the XLA device trace. Fused chains
emit ONE span per segment (``chain/<q1+q2+...>``) with the member query
names in ``args`` — mirroring that the whole segment is a single XLA
program.

Recording is gated on ``tracer.enabled`` (default off; opt in via
``runtime.trace_start()`` or ``SIDDHI_TPU_TRACE=1``): a disabled span
is one attribute check, so the hot path stays free.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args):
        """Post-hoc arg attribution (no-op when tracing is off)."""


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "t0")

    def __init__(self, tracer: "ChunkTracer", name: str, cat: str, args):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_us = (time.perf_counter_ns() - self.t0) // 1000
        self.tracer.record(self.name, self.cat, self.t0 // 1000, dur_us,
                           self.args)
        return False

    def set(self, **args):
        """Attach args measured INSIDE the span before it records —
        e.g. the ingest pipeline's encode/dispatch overlap attribution
        (core/stream.py), which only exists after the chunks drain."""
        self.args = {**dict(self.args), **args}


class ChunkTracer:
    """Ring buffer of completed spans (newest CAP kept)."""

    CAP = 8192

    def __init__(self, capacity: int = CAP):
        self.enabled = os.environ.get("SIDDHI_TPU_TRACE", "") == "1"
        self._events = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        """Enable recording (clears previously buffered spans)."""
        with self._lock:
            self._events.clear()
        self.enabled = True

    def stop(self) -> None:
        self.enabled = False

    # -- recording -------------------------------------------------------
    def span(self, kind: str, name: str, **args):
        """Context manager timing one pipeline stage; event name is
        ``<kind>/<name>`` (e.g. ``step/q1``, ``chain/q1+q2``)."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, f"{kind}/{name}", kind, args)

    def record(self, name: str, cat: str, ts_us: int, dur_us: int,
               args) -> None:
        with self._lock:
            self._events.append(
                (name, cat, ts_us, dur_us, threading.get_ident(), args))

    def events(self) -> list:
        with self._lock:
            return list(self._events)

    # -- export ----------------------------------------------------------
    def export(self, path: str, annotations=None) -> str:
        """Write buffered spans as Chrome ``trace_event`` JSON ('X'
        complete events, microsecond timestamps); returns ``path``.

        Events are sorted by ``ts`` before writing — the ring buffer
        holds completion order, and Chrome/Perfetto only nest 'X' spans
        correctly from start-time-ordered input (an enclosing span
        completes AFTER its children, so buffer order is exactly
        wrong). ``annotations`` maps span names to extra ``args``
        entries — ``runtime.trace_export`` merges the cost profiler's
        measured device-time attribution here (obs/costmodel.py)."""
        ann = annotations or {}
        events = sorted(self.events(), key=lambda e: e[2])
        trace = {
            "displayTimeUnit": "ms",
            "traceEvents": [
                {"name": name, "cat": cat, "ph": "X", "ts": ts_us,
                 "dur": dur_us, "pid": os.getpid(), "tid": tid,
                 "args": {**dict(args), **ann.get(name, {})}}
                for name, cat, ts_us, dur_us, tid, args in events
            ],
        }
        with open(path, "w") as f:
            json.dump(trace, f)
        return path


def maybe_span(app, kind: str, name: str, **args):
    """Span against ``app.tracer`` when the owner is wired to an app
    runtime (junctions/sinks can exist standalone), else a no-op."""
    tracer = getattr(app, "tracer", None) if app is not None else None
    if tracer is None:
        return NOOP_SPAN
    return tracer.span(kind, name, **args)
