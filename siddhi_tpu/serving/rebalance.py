"""SLO-driven pool rebalancer: at most one live migration per interval.

The rebalancer closes the loop over signals the pool already emits —
per-device pending backlog, saturation, QoS priority deferrals, SLO
burn rates, per-device ``rows_ingested``/``collect_ms`` — and answers
one question per interval: *is one device persistently hotter than the
rest, and would moving one tenant off it help?* If yes, it calls
`TenantPool.migrate_tenant` (serving/migrate.py protocol) exactly once
and then cools down.

Hysteresis, so it cannot flap:

- the SAME device must be the hot one for ``confirm_steps``
  CONSECUTIVE observations before anything moves (oscillating load
  resets the streak every time the hot device changes);
- after a migration the loop sleeps ``cooldown_steps`` intervals
  (backlog the move itself created must not look like new skew);
- at most ONE migration per step, ever.

Kill switch: ``SIDDHI_TPU_REBALANCE=0`` disables the loop entirely —
`start()` refuses and `step()` no-ops (docs/serving.md "Live migration
& rebalance" lists the dials).
"""
from __future__ import annotations

import logging
import os
import threading
from collections import deque
from typing import Optional

REBALANCE_ENV = "SIDDHI_TPU_REBALANCE"   # "0" kills the loop

log = logging.getLogger("siddhi_tpu.serving")


class Rebalancer:
    """Background skew->migration loop for one mesh TenantPool.

    ``hot_ratio``: a device is hot when its pending backlog is at least
    this multiple of the coolest survivor's (and >= ``min_rows``).
    ``confirm_steps``: consecutive same-device hot observations before
    migrating. ``cooldown_steps``: idle observations after a move.
    """

    def __init__(self, pool, interval_s: float = 1.0,
                 hot_ratio: float = 3.0, confirm_steps: int = 2,
                 cooldown_steps: int = 4, min_rows: int = 1):
        if pool.mesh is None:
            raise ValueError(
                f"pool '{pool.name}' has no mesh — nothing to rebalance")
        self.pool = pool
        self.interval_s = float(interval_s)
        self.hot_ratio = float(hot_ratio)
        self.confirm_steps = int(confirm_steps)
        self.cooldown_steps = int(cooldown_steps)
        self.min_rows = int(min_rows)
        self.steps = 0
        self.migrations = 0
        # per-step decision log (signals + action) — the flap-guard
        # chaos scenario and the operator's post-mortem both read it
        self.decisions: deque = deque(maxlen=256)
        self._hot_device: Optional[int] = None
        self._streak = 0
        self._cooldown = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return os.environ.get(REBALANCE_ENV, "1") != "0"

    # -- signals ----------------------------------------------------------

    def signals(self) -> dict:
        """One consistent observation of everything the decision reads:
        per-device backlog/tenants (from the slot map), saturation,
        QoS deferrals, burn rates, per-device ingest/collect counters."""
        pool = self.pool
        with pool._lock:
            backlog = [0] * pool.n_devices
            tenants_by_device: list = [[] for _ in
                                       range(pool.n_devices)]
            pending = dict(pool._pending_rows)
            for tid, slot in pool._tenants.items():
                d = pool._device_of_slot(slot)
                backlog[d] += pending.get(tid, 0)
                tenants_by_device[d].append(tid)
            sig = {
                "backlog": backlog,
                "tenants_by_device": tenants_by_device,
                "pending": pending,
                "lost_devices": sorted(pool._lost_devices),
                "rows_per_device": list(pool._rows_per_device),
                "collect_ms_per_device":
                    list(pool._collect_ms_per_device),
                "saturation": pool._saturation_locked(),
                "deferrals": dict(pool._qos.deferrals)
                if pool._qos is not None else {},
            }
        # burn rates ride the SLO evaluation (host-side windows only);
        # scopes keyed "tenant=<id>" — the starved tenant's burn is the
        # leading indicator that backlog skew became an SLO breach
        slo = pool.slo_engine.evaluate()
        sig["burn"] = {
            name: {k: v for k, v in entry.items() if "burn" in k}
            for name, entry in (slo.get("scopes") or {}).items()}
        return sig

    # -- one decision -----------------------------------------------------

    def step(self) -> Optional[dict]:
        """One observation + at most one migration. Returns the
        migration record when one happened, else None. Synchronous and
        lock-free at the top so tests drive it directly."""
        if not self.enabled:
            return None
        self.steps += 1
        sig = self.signals()
        entry = {"step": self.steps, "action": "idle",
                 "backlog": sig["backlog"],
                 "lost_devices": sig["lost_devices"]}
        self.decisions.append(entry)
        if self._cooldown > 0:
            self._cooldown -= 1
            entry["action"] = "cooldown"
            return None
        alive = [d for d in range(self.pool.n_devices)
                 if d not in set(sig["lost_devices"])]
        if len(alive) < 2:
            return None
        backlog = sig["backlog"]
        hot = max(alive, key=lambda d: backlog[d])
        coldest = min((d for d in alive if d != hot),
                      key=lambda d: backlog[d])
        baseline = max(1, backlog[coldest])
        if backlog[hot] < self.min_rows or \
                backlog[hot] < self.hot_ratio * baseline:
            # not hot enough — and a cleared condition resets the
            # confirmation streak (half the hysteresis)
            self._hot_device, self._streak = None, 0
            return None
        if hot != self._hot_device:
            # the hot spot MOVED: oscillating load never confirms
            self._hot_device, self._streak = hot, 0
        self._streak += 1
        entry.update(hot_device=hot, streak=self._streak)
        if self._streak < self.confirm_steps:
            entry["action"] = "confirming"
            return None
        victims = sig["tenants_by_device"][hot]
        if not victims:
            self._hot_device, self._streak = None, 0
            return None
        victim = max(victims, key=lambda t: sig["pending"].get(t, 0))
        try:
            rec = self.pool.migrate_tenant(victim, coldest,
                                           cause="rebalance")
        except ValueError as exc:
            # no free slot / racing churn: log, reset, try again later
            entry["action"] = f"skipped: {exc}"
            self._hot_device, self._streak = None, 0
            return None
        self.migrations += 1
        self._hot_device, self._streak = None, 0
        self._cooldown = self.cooldown_steps
        entry["action"] = "migrated"
        entry["migration"] = rec
        log.info("pool '%s': rebalancer moved tenant '%s' d%d -> d%d "
                 "(backlog %s)", self.pool.name, victim, hot, coldest,
                 backlog)
        return rec

    # -- background loop --------------------------------------------------

    def start(self) -> bool:
        """Arm the interval loop on a daemon thread. Returns False (and
        starts nothing) under the SIDDHI_TPU_REBALANCE=0 kill switch."""
        if not self.enabled:
            log.info("pool '%s': rebalancer disabled (%s=0)",
                     self.pool.name, REBALANCE_ENV)
            return False
        if self._thread is not None:
            return True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"rebalance-{self.pool.name}")
        self._thread.start()
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — keep observing
                log.exception("pool '%s': rebalance step failed",
                              self.pool.name)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def report(self) -> dict:
        last = self.decisions[-1] if self.decisions else None
        return {"enabled": self.enabled, "steps": self.steps,
                "migrations": self.migrations,
                "interval_s": self.interval_s,
                "hot_ratio": self.hot_ratio,
                "confirm_steps": self.confirm_steps,
                "cooldown_steps": self.cooldown_steps,
                "last_decision": last}
