"""Live slot migration & device evacuation for tenant pools.

Two pool-reshaping operations built from machinery that already exists
(the elastic-scaling model of "Towards Concurrent Stateful Stream
Processing on Multicore", with Diba's pre-warmed re-configurable
processing units as the zero-recompile mechanism — PAPERS.md):

- **Live migration** (`TenantPool.migrate_tenant` /
  `request_migration`): one tenant's slot slice moves to another mesh
  device between fair rounds. The slice is exactly the PR 15
  `snapshot_tenant` read; the write is an `.at[slot].set` on the
  sharded stacked arrays, so XLA routes the data to the target device
  through the PR 12 rule-table placement — zero recompiles, and the
  moving tenant's in-flight chunks park in a bounded queue until the
  slot map flips. This module adds the ORCHESTRATION on top: picking
  targets, and the failure-driven evacuation below.

- **Evacuation** (`evacuate`): after `FaultInjector.kill_device` marks
  a device lost (`pool.mark_device_lost`), the victims' live state is
  gone — there is nothing to snapshot. Their slots restore from the
  newest restorable whole-pool checkpoint (walking revisions newest-
  first and skipping corrupt ones, the PoolCheckpointSupervisor
  contract) onto the least-loaded surviving devices, WITHOUT touching
  the survivors' live state — this is a per-slot graft, not a whole-
  pool restore. Victims with no checkpointed state re-init fresh from
  their bindings (flight-recorded as such). Their retained pending
  queues then drain through normal rounds and their error-partition
  backlog replays in original-timestamp order.

docs/serving.md "Live migration & rebalance" and docs/resilience.md
"Device evacuation" describe the protocols; the `migration.*` /
`evacuation.*` gauge families (docs/observability.md) expose the
counters this module bumps.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp

log = logging.getLogger("siddhi_tpu.serving")


def newest_restorable_checkpoint(pool) -> tuple[Optional[str],
                                                Optional[dict]]:
    """Walk the pool's checkpoint revisions newest-first and return
    (revision, deserialized payload) for the first one that
    deserializes to a matching tenant-pool snapshot — or (None, None)
    when no restorable checkpoint exists. Corrupt/foreign revisions are
    skipped with a warning (the supervisor's fallback contract), never
    raised: evacuation must proceed even when it can only fresh-init."""
    from ..core.persistence import deserialize
    store = pool.proto._persistence_store()
    for rev in reversed(store.list_revisions(pool.name)):
        try:
            data = store.load(pool.name, rev)
            if data is None:
                continue
            payload = deserialize(data)
            if payload.get("kind") != "tenant-pool" or \
                    payload.get("template") != pool.template.key:
                raise ValueError("not a snapshot of this pool")
            return rev, payload
        except Exception as exc:  # noqa: BLE001 — corrupt revision
            log.warning("pool '%s': revision %s is not restorable "
                        "(%s); falling back to the previous one",
                        pool.name, rev, exc)
    return None, None


def _pick_target_slot(pool) -> int:
    """Least-loaded surviving device's free slot (caller holds the pool
    lock; ``_free`` never contains lost-device slots)."""
    if not pool._free:
        raise ValueError(
            f"pool '{pool.name}': no free slot on any surviving device "
            "to evacuate into")
    loads = pool._placement_counts
    best = min(range(len(pool._free)),
               key=lambda i: (loads[pool._device_of_slot(
                   pool._free[i])], -pool._free[i]))
    return pool._free.pop(best)


def evacuate(pool, replay: bool = True) -> dict:
    """Restore every lost-device victim onto the surviving devices.

    Per victim: graft its slot slice from the newest restorable pool
    checkpoint into a free slot on the least-loaded surviving device
    (`.at[slot].set` on the sharded arrays — survivors' live state is
    untouched, bit-identical), or fresh-init from its bindings when the
    checkpoint predates the tenant. Then (``replay=True``) its error-
    partition backlog replays in original-timestamp order, and its
    RETAINED pending queue drains through the next normal rounds.
    Admission budgets re-derive; every graft is flight-recorded with
    before/after placement + source revision; recovery age and
    evacuation count surface in ``statistics()['mesh']``.
    """
    with pool._lock:
        victims = dict(pool._lost_tenants)
        if not victims:
            return {"evacuated": [], "revision": None, "replayed": {}}
        revision, payload = newest_restorable_checkpoint(pool)
        snap_tenants = (payload or {}).get("tenants", {})
        snap_queries = (payload or {}).get("queries", {})
        if payload is not None:
            from ..core.persistence import load_strings
            load_strings(payload["strings"])
        moved = []
        for tid in sorted(victims):
            old_slot = victims[tid]
            target = _pick_target_slot(pool)
            entry = snap_tenants.get(tid)
            if entry is not None:
                # slot-slice graft from the checkpoint payload: index
                # into the SNAPSHOT's arrays at the tenant's slot AT
                # CHECKPOINT TIME (may differ from its dying slot)
                s_slot = int(entry["slot"])
                for qn in pool._order:
                    snap = snap_queries[qn]
                    pool._states[qn] = jax.tree_util.tree_map(
                        lambda full, s: full.at[target].set(
                            jnp.asarray(s[s_slot])),
                        pool._states[qn], snap["states"])
                    pool._emitted[qn] = \
                        pool._emitted[qn].at[target].set(
                            jnp.asarray(snap["emitted"][s_slot]))
                source = "checkpoint"
            else:
                # the checkpoint predates this tenant (or none exists):
                # fresh state from its bindings — flight-recorded so
                # the operator knows this victim lost its window state
                from ..analysis.plan_rules import \
                    check_template_bindings
                vals = check_template_bindings(
                    pool.proto.ast, dict(pool._bindings.get(tid, {})))
                for qn in pool._order:
                    init = pool._tenant_init_states(qn, vals)
                    pool._states[qn] = jax.tree_util.tree_map(
                        lambda full, iv: full.at[target].set(iv),
                        pool._states[qn], init)
                    pool._emitted[qn] = \
                        pool._emitted[qn].at[target].set(0)
                source = "fresh-init"
            pool._tenants[tid] = target
            del pool._lost_tenants[tid]
            new_dev = pool._device_of_slot(target)
            pool._placement_counts[new_dev] += 1   # fresh per pick
            rec = {"tenant": tid, "source": source,
                   "revision": revision,
                   "from": {"slot": old_slot,
                            "device": pool._device_of_slot(old_slot)},
                   "to": {"slot": target, "device": new_dev}}
            pool.flight.record("evacuation", **rec)
            log.info("pool '%s': evacuated tenant '%s' slot %d -> "
                     "%d(d%d) from %s", pool.name, tid, old_slot,
                     target, new_dev,
                     revision if source == "checkpoint" else source)
            moved.append(rec)
        if pool.mesh is not None:
            pool._place_state()   # dedupe rule-table re-placement pass
        pool._recompute_placement_locked()
        pool._evacuations += len(moved)
        pool._last_evacuation_wall = time.time()
        pool._work.notify()
    replayed: dict = {}
    if replay:
        # OUTSIDE the lock: replay delivers through callbacks/breakers
        for rec in moved:
            replayed.update(pool.replay_errors(rec["tenant"]))
    return {"evacuated": moved, "revision": revision,
            "replayed": replayed}
