"""Tenant QoS: rate limits, priority classes, weighted-fair scheduling,
and per-tenant circuit breakers (docs/serving.md "QoS dials").

The whole layer is HOST-SIDE policy over the unchanged compiled
programs: a round's per-tenant take limits, a 429 before a chunk is
queued, a short-circuited callback — none of it touches a jit, so QoS
activity causes ZERO recompiles (counting-jit guarded in
tests/test_qos.py) and every dial degrades to the pre-QoS behavior at
its default:

- **Rate limits** — a token bucket per tenant (``rate.eps`` events/s,
  ``burst`` tokens of headroom). An over-rate ``send`` is rejected with
  an AdmissionError whose saturation payload carries cause
  ``rate-limited`` and the bucket's own ``retry_after_ms`` (time until
  the chunk's tokens accrue) — the service maps it to HTTP 429 with a
  Retry-After header. No rate configured -> no bucket -> no check.

- **Weighted fairness** — deficit round robin replaces the fixed
  batch_max-per-tenant round: each backlogged tenant accrues a quantum
  of ``batch_max * weight / max_weight_in_class`` credits per round and
  takes ``min(credits, pending, batch_max)`` rows, so over any run of
  rounds the rows dispatched per tenant converge to the weight ratio
  even when one tenant's backlog is unbounded (credits reset when a
  tenant's queue empties — classic DRR). All weights equal (the
  default) -> every quantum is batch_max -> bit-identical takes to the
  pre-QoS fair round.

- **Priority classes** — ``high | normal | low`` drain in order under
  backlog: a class is deferred (takes nothing this round) while any
  strictly-higher class still has residual backlog, but never more
  than ``max_defer`` consecutive rounds, so a starved class's p99 stays
  bounded at ``(max_defer + 1) x`` its fair-share round cadence.

- **Circuit breakers** — a tenant whose callback keeps failing trips
  OPEN after ``breaker.failures`` consecutive failed deliveries; while
  OPEN its output rows short-circuit to its error-store partition
  WITHOUT running the callback (the events survive for replay, the
  pool stops paying for a dead sink); after ``breaker.reset.ms`` one
  HALF_OPEN probe delivery is allowed — success closes the breaker,
  failure re-opens it. Transitions land in ``statistics()['qos']`` and
  the flight recorder.

Kill switch: ``SIDDHI_TPU_QOS=0`` disables the entire layer no matter
what is configured (the pool runs the exact pre-QoS code path).
"""
from __future__ import annotations

import math
import time
from typing import Callable, Optional

# class rank: lower drains first under backlog
PRIORITIES = {"high": 0, "normal": 1, "low": 2}

# consecutive rounds a lower class may be deferred while a higher class
# drains; bounds priority starvation (docs/serving.md "QoS dials")
DEFAULT_MAX_DEFER = 4

_BREAKER_STATES = ("CLOSED", "HALF_OPEN", "OPEN")


class TokenBucket:
    """Per-tenant ingest rate limiter: ``rate`` tokens/s refill toward a
    ``burst`` ceiling; a chunk of n rows takes n tokens or is rejected
    with the milliseconds until those n tokens will have accrued (the
    429's Retry-After)."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate <= 0:
            raise ValueError(f"rate.eps must be > 0 (got {rate})")
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.clock = clock
        self.tokens = self.burst
        self._t_last = clock()

    def _refill(self) -> None:
        now = self.clock()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._t_last) * self.rate)
        self._t_last = now

    def try_take(self, n: int) -> tuple[bool, int]:
        """(accepted, retry_after_ms). Oversized chunks (n > burst) are
        admitted whenever the bucket is full — the debt goes negative
        and refills before the next chunk passes, so a tenant whose
        chunking is coarser than its burst is throttled to the same
        average rate instead of being unservable."""
        self._refill()
        if self.tokens >= min(float(n), self.burst):
            self.tokens -= float(n)
            return True, 0
        need = min(float(n), self.burst) - self.tokens
        return False, max(1, int(math.ceil(need / self.rate * 1000.0)))


class CircuitBreaker:
    """Per-tenant callback breaker: CLOSED -> (``threshold`` consecutive
    delivery failures) -> OPEN -> (``reset_ms`` cooldown) -> HALF_OPEN
    probe -> CLOSED on success / OPEN on failure."""

    def __init__(self, threshold: int, reset_ms: int,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        if threshold < 1:
            raise ValueError("breaker.failures must be >= 1")
        self.threshold = int(threshold)
        self.reset_ms = int(reset_ms)
        self.clock = clock
        self.on_transition = on_transition
        self.state = "CLOSED"
        self.failures = 0           # consecutive failures while CLOSED
        self.trips = 0              # CLOSED/HALF_OPEN -> OPEN count
        self.short_circuited = 0    # events routed around the callback
        self._opened_at: Optional[float] = None

    def _move(self, state: str) -> None:
        if state == self.state:
            return
        prev, self.state = self.state, state
        if state == "OPEN":
            self.trips += 1
            self._opened_at = self.clock()
        if self.on_transition is not None:
            self.on_transition(prev, state)

    def gate(self) -> str:
        """Pre-delivery decision: ``closed`` (deliver normally),
        ``probe`` (HALF_OPEN trial delivery), ``open`` (short-circuit).
        Calling gate() when the cooldown has elapsed IS the transition
        to HALF_OPEN — at most one probe is in flight per cooldown."""
        if self.state == "OPEN":
            elapsed_ms = (self.clock() - self._opened_at) * 1000.0
            if elapsed_ms >= self.reset_ms:
                self._move("HALF_OPEN")
                return "probe"
            return "open"
        if self.state == "HALF_OPEN":
            # a probe already went out and has not resolved; keep
            # short-circuiting until record_* settles it
            return "open"
        return "closed"

    def record_success(self) -> None:
        self.failures = 0
        self._move("CLOSED")

    def record_failure(self) -> None:
        if self.state == "HALF_OPEN":
            self._move("OPEN")
            return
        self.failures += 1
        if self.failures >= self.threshold:
            self._move("OPEN")

    def as_dict(self) -> dict:
        return {"state": self.state, "failures": self.failures,
                "trips": self.trips,
                "short_circuited": self.short_circuited,
                "threshold": self.threshold, "reset_ms": self.reset_ms}


class TenantQoS:
    """One tenant's resolved QoS profile (per-tenant dials merged over
    the pool defaults)."""

    __slots__ = ("weight", "priority", "bucket", "breaker")

    def __init__(self, weight: float, priority: str,
                 bucket: Optional[TokenBucket],
                 breaker: Optional[CircuitBreaker]):
        self.weight = weight
        self.priority = priority
        self.bucket = bucket
        self.breaker = breaker


def _get(d: dict, *names, default=None):
    for n in names:
        if d.get(n) is not None:
            return d[n]
    return default


class PoolQoS:
    """The pool's QoS state: per-tenant profiles, DRR credits, class
    deferral counters. All methods are called under the pool lock."""

    def __init__(self, defaults: Optional[dict] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable] = None):
        d = dict(defaults or {})
        self.clock = clock
        self.on_transition = on_transition   # fn(tenant, prev, state)
        self.default_rate = _get(d, "rate_eps", "rate.eps")
        self.default_burst = _get(d, "rate_burst", "burst", "rate.burst")
        self.default_weight = float(_get(d, "weight", default=1.0))
        self.default_priority = self._check_priority(
            _get(d, "priority", default="normal"))
        self.breaker_failures = _get(d, "breaker_failures",
                                     "breaker.failures")
        self.breaker_reset_ms = int(_get(d, "breaker_reset_ms",
                                         "breaker.reset.ms",
                                         default=30_000))
        self.max_defer = int(_get(d, "max_defer", "max.defer",
                                  default=DEFAULT_MAX_DEFER))
        self._tenants: dict[str, TenantQoS] = {}
        self._deficit: dict[str, float] = {}
        self._defer: dict[int, int] = {}     # class rank -> deferred rounds
        self.deferrals: dict[str, int] = {}  # priority name -> total
        self.short_circuited = 0

    @staticmethod
    def _check_priority(p: str) -> str:
        p = str(p).lower()
        if p not in PRIORITIES:
            raise ValueError(
                f"unknown priority class '{p}' "
                f"(expected one of {', '.join(sorted(PRIORITIES))})")
        return p

    # -- tenant lifecycle -------------------------------------------------

    def add_tenant(self, tid: str, qos: Optional[dict] = None) -> None:
        q = dict(qos or {})
        unknown = set(q) - {"weight", "priority", "rate_eps", "rate.eps",
                            "burst", "rate_burst", "rate.burst"}
        if unknown:
            raise ValueError(
                f"unknown qos dial(s) {', '.join(sorted(unknown))} "
                "(expected weight / priority / rate_eps / burst)")
        weight = float(_get(q, "weight", default=self.default_weight))
        if weight <= 0:
            raise ValueError(f"qos weight must be > 0 (got {weight})")
        priority = self._check_priority(
            _get(q, "priority", default=self.default_priority))
        rate = _get(q, "rate_eps", "rate.eps", default=self.default_rate)
        burst = _get(q, "burst", "rate_burst", "rate.burst",
                     default=self.default_burst)
        bucket = None
        if rate is not None:
            bucket = TokenBucket(float(rate),
                                 float(burst if burst is not None
                                       else 2 * float(rate)),
                                 clock=self.clock)
        breaker = None
        if self.breaker_failures is not None:
            def transition(prev, state, _tid=tid):
                if self.on_transition is not None:
                    self.on_transition(_tid, prev, state)
            breaker = CircuitBreaker(int(self.breaker_failures),
                                     self.breaker_reset_ms,
                                     clock=self.clock,
                                     on_transition=transition)
        self._tenants[tid] = TenantQoS(weight, priority, bucket, breaker)
        self._deficit[tid] = 0.0

    def remove_tenant(self, tid: str) -> None:
        self._tenants.pop(tid, None)
        self._deficit.pop(tid, None)

    def profile(self, tid: str) -> Optional[TenantQoS]:
        return self._tenants.get(tid)

    # -- rate limiting ----------------------------------------------------

    def check_rate(self, tid: str, n: int) -> tuple[bool, int]:
        prof = self._tenants.get(tid)
        if prof is None or prof.bucket is None:
            return True, 0
        return prof.bucket.try_take(n)

    # -- weighted-fair scheduling (DRR + class deferral) ------------------

    def plan_round(self, pending: dict[str, int],
                   batch_max: int) -> dict[str, int]:
        """Per-tenant take limits for one fair round. ``pending`` maps
        tenant -> queued rows; only backlogged tenants get an entry.

        Classes drain in priority order: a class with a backlogged
        strictly-higher class above it defers (takes 0) for at most
        ``max_defer`` consecutive rounds. Within a class, DRR credits
        hold the weight ratio exactly over any run of rounds."""
        by_rank: dict[int, list[str]] = {}
        for tid, rows in pending.items():
            if rows <= 0:
                continue
            prof = self._tenants.get(tid)
            rank = PRIORITIES[prof.priority] if prof else \
                PRIORITIES["normal"]
            by_rank.setdefault(rank, []).append(tid)
        takes: dict[str, int] = {}
        residual_above = 0
        for rank in sorted(by_rank):
            members = by_rank[rank]
            if residual_above > 0 and \
                    self._defer.get(rank, 0) < self.max_defer:
                # a higher class is still draining: sit this round out
                self._defer[rank] = self._defer.get(rank, 0) + 1
                for tid in members:
                    takes[tid] = 0
                    prof = self._tenants.get(tid)
                    name = prof.priority if prof else "normal"
                    self.deferrals[name] = self.deferrals.get(name, 0) + 1
                residual_above += sum(pending[t] for t in members)
                continue
            self._defer[rank] = 0
            w_max = max((self._tenants[t].weight for t in members
                         if t in self._tenants), default=1.0)
            for tid in members:
                prof = self._tenants.get(tid)
                w = prof.weight if prof else 1.0
                self._deficit[tid] = self._deficit.get(tid, 0.0) \
                    + batch_max * (w / w_max)
                take = int(min(self._deficit[tid], pending[tid],
                               batch_max))
                takes[tid] = take
                self._deficit[tid] -= take
                if pending[tid] - take <= 0:
                    # queue drained: credits do not bank across idle
                    # periods (classic DRR)
                    self._deficit[tid] = 0.0
                residual_above += pending[tid] - take
        return takes

    # -- circuit breakers -------------------------------------------------

    def breaker_gate(self, tid: str) -> str:
        prof = self._tenants.get(tid)
        if prof is None or prof.breaker is None:
            return "closed"
        return prof.breaker.gate()

    def on_delivery(self, tid: str, ok: bool) -> None:
        prof = self._tenants.get(tid)
        if prof is None or prof.breaker is None:
            return
        if ok:
            prof.breaker.record_success()
        else:
            prof.breaker.record_failure()

    def count_short_circuit(self, tid: str, n: int) -> None:
        self.short_circuited += n
        prof = self._tenants.get(tid)
        if prof is not None and prof.breaker is not None:
            prof.breaker.short_circuited += n

    # -- observability ----------------------------------------------------

    def credits(self) -> dict[str, float]:
        return {tid: round(v, 3) for tid, v in self._deficit.items()}

    def describe(self) -> dict:
        """Static configuration view (rides pool explain decisions —
        per-tenant weights/priorities are live facts, dials are plan)."""
        return {
            "scheduler": "deficit-round-robin",
            "max_defer": self.max_defer,
            "default_weight": self.default_weight,
            "default_priority": self.default_priority,
            "default_rate_eps": self.default_rate,
            "breaker_failures": self.breaker_failures,
            "breaker_reset_ms": self.breaker_reset_ms
            if self.breaker_failures is not None else None,
        }

    def report(self) -> dict:
        tenants = {}
        for tid, prof in self._tenants.items():
            entry = {
                "weight": prof.weight,
                "priority": prof.priority,
                "rate_eps": prof.bucket.rate if prof.bucket else None,
                "burst": prof.bucket.burst if prof.bucket else None,
                "credits": round(self._deficit.get(tid, 0.0), 3),
            }
            if prof.breaker is not None:
                entry["breaker"] = prof.breaker.as_dict()
            tenants[tid] = entry
        return {
            "enabled": True,
            **self.describe(),
            "tenants": tenants,
            "deferrals": dict(self.deferrals),
            "short_circuited": self.short_circuited,
        }
