"""Multi-tenant serving subsystem (docs/serving.md).

Thousands of tenants of ONE query template share ONE compiled program
set: the template compiles once (`${name:type}` placeholders lower to
per-tenant runtime parameters, not baked literals), per-tenant state
stacks on a leading tenant axis, and `jax.vmap` advances every tenant
of a template in a single dispatch. See ROADMAP item 2 and the Diba
pre-staged re-configurable processing units (PAPERS.md).
"""
from .template import Template, TemplateRegistry
from .pool import AdmissionError, TenantPool
from .qos import CircuitBreaker, PoolQoS, TokenBucket
from .migrate import evacuate, newest_restorable_checkpoint
from .rebalance import REBALANCE_ENV, Rebalancer

__all__ = ["Template", "TemplateRegistry", "TenantPool",
           "AdmissionError", "PoolQoS", "TokenBucket",
           "CircuitBreaker", "evacuate",
           "newest_restorable_checkpoint", "Rebalancer",
           "REBALANCE_ENV"]
