"""Parameterized SiddhiQL templates: register once, instantiate per tenant.

A template is ordinary SiddhiQL text plus two placeholder kinds:

- ``${name:type}`` — a TENANT VALUE parameter (type one of int/long/
  float/double/bool/string). It parses into an ``A.TemplateParam`` node
  and lowers to a runtime read of a per-tenant parameter carried in the
  operator's state pytree (ops/expr.py), so every tenant of the template
  shares ONE compiled program and only the stacked parameter array
  differs. Allowed in filter conditions and non-aggregating
  select/having (the ``template-binding`` plan rule enforces this).
- ``${name}`` — a STRUCTURAL placeholder (table/stream refs, window
  sizes, anything that shapes the compiled program). Substituted
  textually from the pool's ``shared`` bindings BEFORE parsing; all
  tenants of one pool share the same structural bindings, and the
  (template hash, shared bindings) pair keys the pool — different
  structural bindings are a different program set by definition.

Templates are HASH-KEYED on whitespace-normalized text: two tenants
posting byte-different but content-identical templates land on the same
registry entry, the same pool, and the same compiled programs.
"""
from __future__ import annotations

import hashlib
import re
import threading
from typing import Optional

from ..core.types import AttrType, can_coerce
from ..lang import ast as A
from ..ops.expr import CompileError

# `${name}` or `${name:type}` — the same surface the lexer tokenizes
_PLACEHOLDER_RE = re.compile(r"\$\{(\w+)(?::(\w+))?\}")
_APPNAME_RE = re.compile(r"@app:name\(\s*['\"][^'\"]*['\"]\s*\)\s*")

_TYPES = {
    "int": AttrType.INT, "long": AttrType.LONG,
    "float": AttrType.FLOAT, "double": AttrType.DOUBLE,
    "bool": AttrType.BOOL, "string": AttrType.STRING,
}


# single source for value->AttrType literal classification (the
# template-binding rule and literal rendering must agree on it)
from ..analysis.plan_rules import _literal_type  # noqa: E402


def render_literal(value, t: AttrType) -> str:
    """Render a Python value as a SiddhiQL literal of type ``t`` (static
    instantiation: the separate-runtimes baseline and one-off deploys)."""
    lt = _literal_type(value)
    if lt is None or not can_coerce(lt, t):
        raise CompileError(
            f"template-binding: value {value!r} does not render as a "
            f"{t.value.upper()} literal")
    if t is AttrType.BOOL:
        return "true" if value else "false"
    if t is AttrType.STRING:
        return "'" + str(value).replace("'", "\\'") + "'"
    if t is AttrType.INT:
        return str(int(value))
    if t is AttrType.LONG:
        return f"{int(value)}L"
    if t is AttrType.FLOAT:
        return f"{float(value)!r}f"
    # DOUBLE: a bare decimal literal; repr always carries '.' or 'e'
    return repr(float(value))


class Template:
    """One registered template: raw text, content hash, placeholder split
    into tenant value params (typed) and structural names (untyped)."""

    def __init__(self, text: str, name: Optional[str] = None):
        self.text = text
        norm = "\n".join(ln.strip() for ln in text.strip().splitlines()
                         if ln.strip())
        self.key = hashlib.sha256(norm.encode()).hexdigest()[:16]
        self.name = name or f"tpl_{self.key[:8]}"
        self.value_params: dict[str, AttrType] = {}
        self.structural: set[str] = set()
        for pname, typename in _PLACEHOLDER_RE.findall(text):
            if not typename:
                if pname in self.value_params:
                    raise CompileError(
                        f"template-binding: placeholder '${{{pname}}}' "
                        "used both typed and untyped")
                self.structural.add(pname)
                continue
            t = _TYPES.get(typename.lower())
            if t is None:
                raise CompileError(
                    f"template-binding: unknown placeholder type "
                    f"'{typename}' in '${{{pname}:{typename}}}' "
                    f"(expected one of {', '.join(sorted(_TYPES))})")
            if pname in self.structural:
                raise CompileError(
                    f"template-binding: placeholder '${{{pname}}}' "
                    "used both typed and untyped")
            prev = self.value_params.get(pname)
            if prev is not None and prev is not t:
                raise CompileError(
                    f"template-binding: placeholder '${{{pname}}}' "
                    f"declared with conflicting types {prev.value} and "
                    f"{t.value}")
            self.value_params[pname] = t

    # -- text assembly ---------------------------------------------------

    def app_text(self, shared: Optional[dict] = None,
                 app_name: Optional[str] = None) -> str:
        """Template text with STRUCTURAL placeholders substituted from
        ``shared`` (raw text: identifiers go in bare, literal values via
        str()) and the @app:name rewritten. Typed placeholders remain for
        the template-mode parse."""
        shared = dict(shared or {})
        unknown = sorted(set(shared) - self.structural)
        if unknown:
            raise CompileError(
                "template-binding: shared binding(s) "
                f"{', '.join(unknown)} name no structural placeholder "
                f"(structural: {', '.join(sorted(self.structural)) or 'none'})")
        missing = sorted(self.structural - set(shared))
        if missing:
            raise CompileError(
                "template-binding: unbound structural placeholder(s) "
                + ", ".join(f"${{{m}}}" for m in missing)
                + " — pass them via shared=")

        def sub(m):
            pname, typename = m.group(1), m.group(2)
            if typename:
                return m.group(0)          # tenant param: leave for parse
            return str(shared[pname])
        text = _PLACEHOLDER_RE.sub(sub, self.text)
        if app_name is not None:
            text = "@app:name('%s')\n%s" % (app_name,
                                            _APPNAME_RE.sub("", text))
        return text

    def instantiate(self, shared: Optional[dict] = None,
                    app_name: Optional[str] = None) -> A.SiddhiApp:
        """Parse in template mode: typed placeholders stay as
        TemplateParam nodes (per-tenant runtime parameters); the
        template-binding plan rule and the typechecker both run."""
        from ..lang.parser import parse
        return parse(self.app_text(shared, app_name), template=True)

    def instantiate_static(self, bindings: dict,
                           shared: Optional[dict] = None,
                           app_name: Optional[str] = None) -> str:
        """Fully-bound SiddhiQL text: every typed placeholder replaced by
        the binding rendered as a literal of the declared type. This is
        the one-runtime-per-tenant baseline (bench.py `tenants` config
        measures it against the pooled path) and the escape hatch for
        deploying a template as a plain app."""
        unknown = sorted(set(bindings) - set(self.value_params))
        if unknown:
            raise CompileError(
                f"template-binding: unknown placeholder(s) "
                f"{', '.join(unknown)}")
        missing = sorted(set(self.value_params) - set(bindings))
        if missing:
            raise CompileError(
                "template-binding: unbound placeholder(s) "
                + ", ".join(f"${{{m}}}" for m in missing))
        text = self.app_text(shared, app_name)

        def sub(m):
            pname, typename = m.group(1), m.group(2)
            if not typename:
                return m.group(0)
            return render_literal(bindings[pname],
                                  self.value_params[pname])
        return _PLACEHOLDER_RE.sub(sub, text)


class TemplateRegistry:
    """Hash-keyed template store + pool cache: tenants instantiating the
    same (template, shared-bindings) pair share ONE TenantPool and
    therefore ONE compiled program set (AOT-warmed at pool creation,
    before the first tenant's traffic arrives)."""

    def __init__(self, manager=None):
        from ..core.manager import SiddhiManager
        self.manager = manager or SiddhiManager()
        self._templates: dict[str, Template] = {}    # key -> Template
        self._names: dict[str, str] = {}             # name -> key
        self._pools: dict[tuple, "TenantPool"] = {}
        self._lock = threading.RLock()

    def register(self, text: str, name: Optional[str] = None) -> Template:
        tpl = Template(text, name=name)
        with self._lock:
            existing = self._templates.get(tpl.key)
            if existing is None:
                self._templates[tpl.key] = tpl
                existing = tpl
            self._names.setdefault(existing.name, existing.key)
            if name:
                self._names[name] = existing.key
        return existing

    def get(self, ref: str) -> Optional[Template]:
        """Template by registered name or content key."""
        with self._lock:
            key = self._names.get(ref, ref)
            return self._templates.get(key)

    def resolve(self, template) -> Template:
        """Template object | registered name/key | inline SiddhiQL text."""
        if isinstance(template, Template):
            with self._lock:
                return self._templates.setdefault(template.key, template)
        got = self.get(template)
        if got is not None:
            return got
        return self.register(template)

    def pool(self, template, shared: Optional[dict] = None,
             warm: bool = True, **pool_kwargs) -> "TenantPool":
        """The ONE TenantPool for (template, shared bindings) — created
        and AOT-warmed on first use, returned as-is afterwards
        (``pool_kwargs`` only apply at creation)."""
        from .pool import TenantPool
        tpl = self.resolve(template)
        shared_key = tuple(sorted((shared or {}).items()))
        pkey = (tpl.key, shared_key)
        with self._lock:
            pool = self._pools.get(pkey)
            if pool is not None:
                return pool
            name = pool_kwargs.pop(
                "name", f"pool_{tpl.key[:8]}"
                + (f"_s{len([k for k in self._pools if k[0] == tpl.key])}"
                   if shared_key else ""))
            pool = TenantPool(tpl, shared=dict(shared or {}),
                              manager=self.manager, name=name,
                              **pool_kwargs)
            self._pools[pkey] = pool
        if warm:
            pool.warmup()
        return pool

    @property
    def pools(self) -> dict:
        with self._lock:
            return dict(self._pools)

    def shutdown(self) -> None:
        for pool in self.pools.values():
            pool.shutdown()
        with self._lock:
            self._pools.clear()
