"""TenantPool: vmapped multi-tenant execution of one compiled template.

One pool = one template (+ shared structural bindings) = ONE compiled
program set. Per-tenant state pytrees stack on a leading tenant axis;
`jax.vmap` over the standard operator-class traces advances EVERY
tenant of the template in a single dispatch. Four operator classes
pool (docs/serving.md "Poolable operator classes"):

- **chain** — filter/window/projection insert-into chains over the
  `_chain_body` trace (the original pooled class);
- **pattern** — NFA one-hot transition scans: the pending-match table
  plus selector states stack per slot, one vmapped step per input
  stream (ops/nfa.py), plus a vmapped absent-deadline timer step;
- **join** — banded equi-join probes: both side chains, the selector
  states and the join-cap overflow counter ride ONE donated state dict
  per query (the opposite side is read inside and returned unchanged,
  which keeps whole-dict donation safe), one vmapped step per side;
- **aggregation** — incremental-aggregation bucket tables stack per
  slot; `materialize_tenant` slices one tenant's buckets out.

Templates may name N ingest streams (patterns/joins consume several);
`send(..., stream=)` routes per stream and every fair round ships ONE
packed (slots, total) uint8 buffer PER INGEST STREAM — the PR 19
zero-copy columnar encode, widened round-wide so all slots share one
encoding tuple — slot-routed on device (`SIDDHI_TPU_POOL_PACKED=0`
falls back to the stacked EventBatch transfer). Tenant `${name:type}`
parameters ride the stacked operator state (ops/expr.py tparam
machinery), so tenant add/remove is pure slot assignment —
`.at[slot].set` writes, no retrace, no recompile (counting-jit guarded
in tests/test_serving.py).

Capacity model (`@app:cap(tenants=..., tenant.state.kb=...)` dial or
constructor knobs):

- the slot axis starts small and GROWS BY DOUBLING when tenants exceed
  it (a doubling is a recompile — amortized log2(max) compiles over the
  pool's lifetime; steady-state churn compiles nothing);
- admission control rejects deploys past `max_tenants` or past the
  per-tenant state quota with a reason string the service maps to
  HTTP 429;
- ingest is FAIR ROUND-ROBIN: each tenant contributes at most
  `batch_max` rows per dispatch round (the @Async batch.size.max dial,
  tenant-aware), so one hot tenant cannot starve the rest — its backlog
  just spans more rounds, bounded by `pending_cap` backpressure.

Isolation:

- `statistics()` / the metrics registry namespace per-tenant gauges as
  ``siddhi.<pool>.tenant.<id>.*``, collected with ONE device_get per
  pool (O(templates), not O(tenants) device reads);
- a tenant callback failure routes the events to THAT tenant's error
  store partition (``<pool>/tenant/<id>``, PR 2 error store) and never
  touches other tenants' delivery;
- `snapshot_tenant` / `restore_tenant` slice exactly one index of the
  tenant axis — other tenants' state stays bit-identical.
"""
from __future__ import annotations

import hashlib
import logging
import math
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.plan_rules import check_template_bindings
from ..core.event import EXPIRED, EventBatch, rows_from_batch
from ..core.runtime import (BATCH_BUCKETS, InsertIntoStreamHandler,
                            QueryRuntime, SiddhiAppRuntime, _as_current,
                            _chain_body, _donate, _fresh_device,
                            bucket_capacity)
from ..core.stream import Event
from ..core.types import AttrType, GLOBAL_STRINGS, np_dtype
from ..lang import ast as A
from ..obs.slo import (EVERY_ENV as _SLO_EVERY_ENV, FlightRecorder,
                       SLOEngine, config_from_annotation as _slo_from_ann,
                       objective_from_dials)
from ..ops.expr import CompileError
from .qos import PoolQoS

QOS_ENV = "SIDDHI_TPU_QOS"   # "0" kills the whole QoS layer
POOL_PACKED_ENV = "SIDDHI_TPU_POOL_PACKED"  # "0" = stacked EventBatch

# _kind slug -> the operator-class name used in quota accounting and
# the 429 per-class state-bytes breakdown (docs/serving.md matrix)
_CLASS_NAMES = {"chain": "chain", "pattern": "pattern", "join": "join",
                "agg": "aggregation"}

log = logging.getLogger("siddhi_tpu.serving")

_DEFAULT_MAX_TENANTS = 1024
_DEFAULT_BATCH_MAX = 1024
_DEFAULT_PENDING_CAP = 1 << 20   # rows buffered per tenant before 429
# SLO sampling stride for pool rounds: one fair round already advances
# EVERY tenant, so rounds are far rarer than per-tenant chunks — an 8x
# stride keeps histograms dense while the sampled block_until_ready
# serializes at most 1-in-8 rounds (SIDDHI_TPU_SLO_EVERY overrides)
_POOL_DEFAULT_EVERY = 8

_TENANT_HELP = {
    "emitted": "events emitted for one tenant across its queries",
    "pending": "rows queued for one tenant awaiting a fair round",
    "errors": "events routed to one tenant's error-store partition",
}


class AdmissionError(Exception):
    """Deploy/ingest rejected by admission control (HTTP 429 at the
    front door); `.reason` names the exhausted resource and
    `.saturation` carries the machine-readable cause (which resource,
    current pressure signals, a Retry-After estimate) so clients and
    autoscalers don't have to parse prose (docs/serving.md)."""

    def __init__(self, reason: str, saturation: Optional[dict] = None):
        super().__init__(reason)
        self.reason = reason
        self.saturation = saturation or {}


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _tree_zeros(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.result_type(x)), tree)


class TenantPool:
    """Stacked/vmapped runtime for every tenant of one template."""

    def __init__(self, template, shared: Optional[dict] = None,
                 manager=None, name: Optional[str] = None,
                 slots: int = 8, max_tenants: Optional[int] = None,
                 state_quota_bytes: Optional[int] = None,
                 batch_max: Optional[int] = None,
                 pending_cap: int = _DEFAULT_PENDING_CAP,
                 slo: Optional[dict] = None,
                 qos: Optional[dict] = None,
                 mesh=None,
                 device_round_cap: Optional[int] = None):
        """``mesh``: optional ``jax.sharding.Mesh`` — the tenant slot
        axis then shards over its first axis (1/n of the slots per
        device, parallel/sharding.py POOL_STATE_RULES), ingest rounds
        place the stacked batch the same way, and admission control
        accounts per-device slot budgets (docs/serving.md).

        ``device_round_cap``: optional per-DEVICE row budget per fair
        round on a mesh (None = unlimited, the legacy round shape).
        When a device's tenants together hit the cap, later tenants on
        that device wait for the next round — the signal the SLO-driven
        rebalancer (serving/rebalance.py) uses to move a colocated
        victim off a saturated device."""
        from ..core.manager import SiddhiManager
        from ..obs.metrics import MetricsRegistry
        self.template = template
        self.shared = dict(shared or {})
        self.name = name or f"pool_{template.key[:8]}"
        self.manager = manager or SiddhiManager()
        app_ast = template.instantiate(shared=self.shared,
                                       app_name=self.name)
        # prototype runtime: planned once, NEVER started — the pool
        # dispatches vmapped variants of its operator chains and its
        # CompileService carries the pool's one-program-set telemetry
        self.proto = SiddhiAppRuntime(app_ast, manager=None)
        # route the prototype's store lookups through the pool's
        # manager: tenant error partitions and pool checkpoints must
        # live in the SHARED stores so a fresh pool built after a crash
        # (resilience/supervisor.py PoolCheckpointSupervisor) finds them
        self.proto.manager = self.manager
        self._plan_topology()
        self._check_poolable()

        cap_ann = A.find_annotation(app_ast.annotations, "cap")
        if max_tenants is None:
            max_tenants = int(cap_ann.element("tenants")
                              or _DEFAULT_MAX_TENANTS) \
                if cap_ann else _DEFAULT_MAX_TENANTS
        if state_quota_bytes is None and cap_ann is not None:
            kb = cap_ann.element("tenant.state.kb")
            if kb is not None:
                state_quota_bytes = int(kb) * 1024 * max_tenants
        self.max_tenants = int(max_tenants)
        self.state_quota_bytes = state_quota_bytes
        if batch_max is None:
            batch_max = _DEFAULT_BATCH_MAX
        # fair-share row cap per tenant per round; bucketed so dispatch
        # capacities land on warm jit cache keys, and capped by the
        # sort-heavy step limits of the template's queries
        for q in self.proto.queries.values():
            if q.max_step_capacity is not None:
                batch_max = min(batch_max, q.max_step_capacity)
        self.batch_max = bucket_capacity(int(batch_max))
        self.pending_cap = int(pending_cap)

        # -- QoS (serving/qos.py; docs/serving.md "QoS dials") ------------
        # Dials merge constructor `qos={...}` over `@app:cap(...)`
        # elements (the deployment's word wins, like slo=). With the
        # SIDDHI_TPU_QOS=0 kill the layer is None and every call site
        # below runs the exact pre-QoS path; with no dials configured
        # the DRR plan is bit-identical to the fixed batch_max round.
        qos_dials: dict = {}
        if cap_ann is not None:
            for el, key in (("rate.eps", "rate_eps"),
                            ("rate.burst", "rate_burst"),
                            ("breaker.failures", "breaker_failures"),
                            ("breaker.reset.ms", "breaker_reset_ms"),
                            ("qos.max.defer", "max_defer")):
                v = cap_ann.element(el)
                if v is not None:
                    qos_dials[key] = float(v) if "rate" in el else int(v)
        qos_dials.update({k: v for k, v in dict(qos or {}).items()
                          if v is not None})
        if os.environ.get(QOS_ENV, "1") == "0":
            self._qos: Optional[PoolQoS] = None
        else:
            try:
                self._qos = PoolQoS(
                    qos_dials,
                    on_transition=self._on_breaker_transition)
            except ValueError as e:
                raise CompileError(f"pool '{self.name}' qos: {e}")

        # -- mesh (slot-axis sharding over devices) -----------------------
        self.mesh = mesh
        if mesh is not None:
            from ..parallel import sharding as _sh
            self.mesh_axis = mesh.axis_names[0]
            self.n_devices = int(mesh.shape[self.mesh_axis])
            self._sharding = _sh
        else:
            self.mesh_axis = None
            self.n_devices = 1
            self._sharding = None
        self.slots = _pow2(max(1, self.n_devices,
                               min(int(slots), self.max_tenants)))
        self._slot_cap = max(_pow2(self.max_tenants), self.n_devices)
        if mesh is not None:
            # pow2 slot axes divide pow2 meshes; anything else is a
            # config error, caught at pool build not first dispatch
            self._sharding.check_divisible(self.slots, mesh,
                                           f"pool '{self.name}' slots")
        # stacked per-query state: leading axis = tenant slot
        self._states = {qn: self._stack_init(qn, self.slots)
                        for qn in self._order}
        self._emitted = {qn: jnp.zeros((self.slots,), jnp.int64)
                         for qn in self._order}
        self._rows_per_device = [0] * self.n_devices
        self._collect_ms_per_device = [0.0] * self.n_devices
        if mesh is not None:
            self._place_state()   # initial slot-axis placement
        # per-tenant state bytes (quota accounting): one slot's slice of
        # every query state plus its emitted counter, accounted PER
        # OPERATOR CLASS — a join-heavy tenant's window buffers and an
        # aggregation's bucket tables all count against tenant.state.kb,
        # and the 429 payload carries the breakdown (docs/serving.md)
        self.state_bytes_by_class: dict[str, int] = {}
        total_bytes = 0
        for qn in self._order:
            b = 8 + sum(leaf.nbytes // self.slots for leaf in
                        jax.tree_util.tree_leaves(self._states[qn]))
            cls = _CLASS_NAMES[self._kind[qn]]
            self.state_bytes_by_class[cls] = \
                self.state_bytes_by_class.get(cls, 0) + b
            total_bytes += b
        self.state_bytes_per_tenant = total_bytes

        self._tenants: dict[str, int] = {}
        self._bindings: dict[str, dict] = {}      # tid -> bound values
        self._tenant_qos_raw: dict[str, dict] = {}  # tid -> qos dials
        self._free = list(range(self.slots - 1, -1, -1))
        # tid -> {ingest stream -> deque of (ts, cols, t_arrival)}
        self._pending: dict[str, dict] = {}
        self._pending_rows: dict[str, int] = {}
        # packed pool ingest (core/ingest.py): ONE sticky widen-only
        # encoder per ingest stream — all slots of a round share its
        # encoding tuple, so the whole round is ONE (slots, total)
        # uint8 device_put per stream (SIDDHI_TPU_POOL_PACKED=0 falls
        # back to the stacked EventBatch transfer)
        self._packed_on = os.environ.get(POOL_PACKED_ENV, "1") != "0"
        self._encoders: dict[str, object] = {}
        self._ingest_stats = {"transfers": 0, "rows": 0, "cells": 0,
                              "bytes": 0, "rounds": 0}
        self._callbacks: dict[str, list[Callable]] = {}
        self._error_counts: dict[str, int] = {}
        self.batch_callbacks: list[Callable] = []
        self._vsteps: dict = {}
        self._lock = threading.RLock()
        self._now = -(2 ** 62)
        self._rounds = 0
        self._dispatches = 0
        self._grows = 0
        self._warmed = False
        self._running = False
        self._worker: Optional[threading.Thread] = None
        self._work = threading.Condition(self._lock)
        self.metrics = MetricsRegistry()
        self.metrics.register_collector(
            lambda: self._collect_observability()[0])
        # -- SLO engine + saturation signals (obs/slo.py) -----------------
        # Always on for pools (per-tenant p99 is the ROADMAP item 2
        # deliverable); the latency OBJECTIVE is optional and comes from
        # the template's `@app:slo(...)` annotation or the constructor's
        # `slo={...}` dial (dial wins — it is the deployment's word).
        slo_dials = dict(slo or {})
        flight_dir = slo_dials.pop("flight_dir", None)
        objective = None
        if slo_dials:
            objective = objective_from_dials(slo_dials)
        else:
            slo_ann = A.find_annotation(app_ast.annotations, "slo")
            if slo_ann is not None:
                try:
                    objective = _slo_from_ann(slo_ann)
                except ValueError as e:
                    raise CompileError(str(e))
        every = objective.every if objective is not None and \
            objective.every else None
        if every is None:
            env = os.environ.get(_SLO_EVERY_ENV, "")
            every = int(env) if env else _POOL_DEFAULT_EVERY
        self.flight = FlightRecorder(
            self.name, dirpath=flight_dir,
            # every artifact carries {app, pool, plan_hash}: a PAGE
            # dump is attributable to the pool AND the template plan
            # that produced it (obs/slo.py identity contract)
            identity_fn=lambda: {"app": self.name, "pool": self.name,
                                 "plan_hash": self.plan_hash()})
        self.slo_engine = SLOEngine(
            self.name, objective=objective, every=every,
            recorder=self.flight, context_fn=self._flight_context)
        # admission-rejection saturation counters (host-side only)
        self._rejections: dict[str, int] = {}
        self._rejection_times: deque = deque(maxlen=512)
        self._last_pump_wall: Optional[float] = None
        self._round_ms_ema: Optional[float] = None
        # crash recovery bookkeeping (resilience/supervisor.py): the
        # supervisor registers itself here; restore() fills _recovery
        self._checkpoint_supervisor = None
        self._recovery: Optional[dict] = None
        # -- live migration / evacuation (serving/migrate.py;
        #    docs/serving.md "Live migration & rebalance") ---------------
        self.device_round_cap = int(device_round_cap) \
            if device_round_cap else None
        # tid -> {from/to slot+device, cause, parked deque, park_cap, ...}
        self._migrations: dict[str, dict] = {}
        self._migration_log: deque = deque(maxlen=64)
        self._migrations_done = 0
        self._rows_migrated = 0
        self._migration_pause_ms_last: Optional[float] = None
        self._lost_devices: set[int] = set()
        # victims of a lost device, awaiting evacuation: tid -> old slot
        # (their pending queues are RETAINED and drain after evacuation)
        self._lost_tenants: dict[str, int] = {}
        self._evacuations = 0
        self._last_evacuation_wall: Optional[float] = None
        # cached per-device placement + budget, re-derived on EVERY
        # slot-map change (_recompute_placement_locked) — the admission
        # staleness fix: a drained/evacuated device must stop 429-ing
        self._placement_counts: list = [0] * self.n_devices
        self._slot_budget = -(-self.max_tenants
                              // max(1, self.n_devices))

    # -- planning ---------------------------------------------------------

    def _plan_topology(self) -> None:
        """Derive the query wiring from the prototype's junction graph:
        N named ingest streams in, queries (and aggregations) in
        topological order, terminal streams (produced, never consumed)
        out. Every node gets a ``_kind`` (chain/pattern/join/agg) and a
        tuple of labeled inputs — chains one ``("*", sid)``, patterns
        one ``("s:<sid>", sid)`` per distinct engine stream, joins
        ``("L", sid)``/``("R", sid)`` — the label picks the vmapped
        step variant at dispatch."""
        from ..core.runtime import JoinQueryRuntime, PatternQueryRuntime
        p = self.proto
        self._q_in: dict[str, tuple] = {}
        self._q_out: dict[str, Optional[str]] = {}
        self._kind: dict[str, str] = {}
        self._aggs: dict[str, object] = {}
        produced: set[str] = set()
        consumers: dict[str, list[str]] = {}
        for qn, q in p.queries.items():
            if isinstance(q, PatternQueryRuntime):
                self._kind[qn] = "pattern"
                ins = tuple(
                    ("s:" + sid, sid) for sid in
                    sorted({s.stream_id for s in q.engine.slots}))
            elif isinstance(q, JoinQueryRuntime):
                self._kind[qn] = "join"
                ins = tuple(
                    (side, q.in_schemas[side].stream_id)
                    for side in ("L", "R")
                    if side not in q.side_tables)
            else:
                self._kind[qn] = "chain"
                ins = (("*", q.in_schema.stream_id),)
            self._q_in[qn] = ins
            for _lab, sid in ins:
                consumers.setdefault(sid, []).append(qn)
            out = None
            for h in q.output_handlers:
                if isinstance(h, InsertIntoStreamHandler):
                    out = h.junction.stream_id
                    produced.add(out)
            self._q_out[qn] = out
        for aid, ar in p.aggregations.items():
            if aid in self._q_in:
                raise CompileError(
                    f"aggregation '{aid}' collides with a query name")
            self._kind[aid] = "agg"
            self._aggs[aid] = ar
            self._q_in[aid] = (("*", ar.in_schema.stream_id),)
            self._q_out[aid] = None
            consumers.setdefault(ar.in_schema.stream_id, []).append(aid)
        ingest = sorted(sid for sid in consumers if sid not in produced)
        self._ingest_streams = ingest
        # topological order (BFS from the ingest streams; a node places
        # once ALL its labeled inputs are available)
        avail = set(ingest)
        order: list[str] = []
        remaining = {qn: {sid for _lab, sid in ins}
                     for qn, ins in self._q_in.items()}
        while remaining:
            placed = [qn for qn, sids in remaining.items()
                      if sids <= avail]
            if not placed:
                break   # unreachable/cyclic queries — poolability rejects
            for qn in sorted(placed):
                order.append(qn)
                remaining.pop(qn)
                if self._q_out[qn]:
                    avail.add(self._q_out[qn])
        self._order = order
        self._unreachable = sorted(remaining)
        self._terminal = sorted(
            sid for sid in produced if sid not in consumers)

    # classes that still cannot pool: (proto attr, what, why, nearest
    # poolable alternative) — each rejection names its reason AND the
    # closest construct that DOES pool (docs/serving.md matrix)
    _UNPOOLABLE = (
        ("partitions", "partitions",
         "partition state fans out per key value, not per tenant slot",
         "key by an attribute inside a pooled filter/window chain"),
        ("named_windows", "named windows",
         "a named window is one shared instance crossing query (and "
         "tenant) boundaries",
         "give each query its own window(...) inside the template"),
        ("tables", "tables",
         "table state is shared mutable storage updated by host-side "
         "index rebuilds",
         "model reference data as a windowed stream and join it"),
        ("record_tables", "@Store tables",
         "external-store I/O runs host callbacks per operation",
         "pre-join the store data into an ingest stream"),
        ("triggers", "triggers",
         "triggers fire on wall-clock schedules outside the fair round "
         "loop",
         "drive time with advance_time()/pump() rounds"),
    )

    def _check_poolable(self) -> None:
        p = self.proto
        problems = []
        for attr, what, why, alt in self._UNPOOLABLE:
            if getattr(p, attr):
                problems.append(f"{what} ({why}; nearest poolable "
                                f"alternative: {alt})")
        if p.sources or p.sinks:
            problems.append(
                "@source/@sink connectors (connectors own host I/O "
                "threads outside pool rounds; nearest poolable "
                "alternative: pool.send() and per-tenant callbacks at "
                "the service front door)")
        for qn, q in p.queries.items():
            if q.table_deps or getattr(q, "side_tables", None):
                problems.append(
                    f"query '{qn}' reads tables (shared mutable "
                    "storage; nearest poolable alternative: join "
                    "against a windowed stream)")
            elif self._q_out.get(qn) is None:
                problems.append(
                    f"query '{qn}' has a non-insert-into output "
                    "(nearest poolable alternative: insert into a "
                    "stream and attach per-tenant callbacks)")
        if not self._ingest_streams:
            problems.append("no ingest stream (every stream is "
                            "query-produced)")
        if self._unreachable:
            problems.append(
                f"unreachable queries {', '.join(self._unreachable)}")
        if problems:
            raise CompileError(
                f"template '{self.template.name}' is not poolable — "
                "vmapped tenant execution covers filter/window/"
                "projection chains, patterns, joins, and incremental "
                "aggregations over named ingest streams; "
                "found: " + "; ".join(problems))

    @property
    def ingest_stream(self) -> str:
        """First (often only) ingest stream — the single-stream
        compatibility surface (core/service.py rows endpoint)."""
        return self._ingest_streams[0]

    @property
    def ingest_streams(self) -> tuple:
        return tuple(self._ingest_streams)

    def _resolve_stream(self, stream: Optional[str]) -> str:
        if stream is None:
            if len(self._ingest_streams) == 1:
                return self._ingest_streams[0]
            raise ValueError(
                f"pool '{self.name}' has {len(self._ingest_streams)} "
                f"ingest streams {self._ingest_streams} — "
                "send(..., stream=) must name one")
        if stream not in self._ingest_streams:
            raise KeyError(
                f"'{stream}' is not an ingest stream of pool "
                f"'{self.name}' (ingest: {self._ingest_streams})")
        return stream

    # -- mesh placement (parallel/sharding.py) ----------------------------

    @property
    def slots_per_device(self) -> int:
        return self.slots // self.n_devices

    def _device_of_slot(self, slot: int) -> int:
        if self.mesh is None:
            return 0
        # host-side twin of the PartitionSpec placement — one shared
        # definition (parallel/sharding.py device_of_index) so the
        # migration/evacuation target math can never drift from the
        # rule-table layout
        return self._sharding.device_of_index(
            slot, self.slots, self.mesh, axis=self.mesh_axis)

    def _place_state(self) -> None:
        """Shard the stacked tenant states over the mesh's slot axis.
        Runs ONLY on initial build and slot-axis growth (the two events
        that change layout); `shard_pytree` skips leaves that are
        already placed, so even a redundant call transfers nothing
        (the dedupe contract, tests/test_mesh.py counts it)."""
        placed = self._sharding.shard_pytree(
            {"states": self._states, "emitted": self._emitted},
            self.mesh, self._sharding.POOL_STATE_RULES,
            axis=self.mesh_axis)
        self._states = placed["states"]
        self._emitted = placed["emitted"]

    def _place_batch(self, batch):
        """Stacked (slots, cap) round batch -> device(s): sharded over
        the slot axis on a mesh (each device receives ONLY its tenants'
        rows — one transfer either way)."""
        if self.mesh is None:
            return jax.device_put(batch)
        return self._sharding.place_leading(batch, self.mesh,
                                            axis=self.mesh_axis)

    def _device_loads_locked(self) -> list:
        """Tenants currently placed per device (host-side bookkeeping;
        re-entrant — callers already inside the RLock pay nothing,
        admission probes arriving lock-free get a consistent count)."""
        with self._lock:
            loads = [0] * self.n_devices
            for slot in self._tenants.values():
                loads[self._device_of_slot(slot)] += 1
            return loads

    def _recompute_placement_locked(self) -> None:
        """Re-derive the cached per-device placement counts AND the
        per-device slot budget. Called on EVERY slot-map change
        (add/remove/restore/migrate/evacuate/device-loss) — the
        admission-staleness fix: a device drained by removal or
        evacuation stops 429-ing traffic it can now accept, budgets
        split over the SURVIVING devices after a loss, and the 429
        payload's per-device placement reflects reality."""
        self._placement_counts = self._device_loads_locked()
        alive = self.n_devices - len(self._lost_devices)
        self._slot_budget = -(-self.max_tenants // max(1, alive))

    def _alive_devices_locked(self) -> list:
        return [d for d in range(self.n_devices)
                if d not in self._lost_devices]

    def _pick_slot(self) -> int:
        """Pop a free slot, mesh-aware: choose the slot on the device
        with the fewest placed tenants so the vmapped work stays
        balanced across the mesh (single-device pools keep LIFO order).
        Lost devices' slots never sit in ``_free`` (mark_device_lost
        strips them), so a degraded mesh places only on survivors."""
        if self.mesh is None:
            return self._free.pop()
        loads = self._device_loads_locked()
        best = min(range(len(self._free)),
                   key=lambda i: (loads[self._device_of_slot(
                       self._free[i])], -self._free[i]))
        return self._free.pop(best)

    # -- state stacking ---------------------------------------------------
    # Slot-state layout per operator class (docs/serving.md matrix):
    #   chain   -> tuple(op state, ...)                (the original)
    #   pattern -> {"nfa": pending-match table, "sel": tuple(op state)}
    #   join    -> {"sides": {"L": tuple, "R": tuple},
    #               "sel": tuple, "ovf": join-cap drop counter}
    #   agg     -> {duration: bucket-table state dict}
    # Everything downstream (snapshot/restore, migration, growth,
    # quota accounting) is generic tree_map over these pytrees.

    def _unstacked_init(self, qname: str):
        """One tenant's fresh state pytree for one query/aggregation."""
        kind = self._kind[qname]
        if kind == "agg":
            ar = self._aggs[qname]
            return {d: ar._init_state() for d in ar.durations}
        q = self.proto.queries[qname]
        sel = tuple(op.init_state() for op in q.operators)
        if kind == "pattern":
            return {"nfa": q.engine.init_state(), "sel": sel}
        if kind == "join":
            return {"sides": {s: tuple(op.init_state() for op in ops)
                              for s, ops in q.side_ops.items()},
                    "sel": sel, "ovf": jnp.int64(0)}
        return sel

    def _stack_init(self, qname: str, slots: int):
        # Host-side numpy repeat + one transfer per leaf: a jnp.repeat
        # here would compile an XLA fill program per distinct leaf shape
        # at pool CONSTRUCTION time, before warmup ever runs.
        return jax.tree_util.tree_map(
            lambda x: jnp.asarray(np.repeat(np.asarray(x)[None], slots,
                                            axis=0)),
            self._unstacked_init(qname))

    @staticmethod
    def _ops_init_with_params(ops, vals: dict):
        states = []
        for op in ops:
            st = op.init_state()
            tps = getattr(op, "tparams", ())
            if tps:
                st = {"tparams": {
                    n: jnp.asarray(TenantPool._encode_param(vals[n][0],
                                                            t),
                                   dtype=np_dtype(t))
                    for n, t in tps}}
            states.append(st)
        return tuple(states)

    def _tenant_init_states(self, qname: str, vals: dict):
        """One tenant's fresh (unstacked) state pytree with its bound
        `${...}` parameter values in place of the zeros (value params
        bind only in chain/selector positions — plan_rules)."""
        kind = self._kind[qname]
        if kind == "agg":
            return self._unstacked_init(qname)
        q = self.proto.queries[qname]
        sel = self._ops_init_with_params(q.operators, vals)
        if kind == "pattern":
            return {"nfa": q.engine.init_state(), "sel": sel}
        if kind == "join":
            return {"sides": {
                        s: self._ops_init_with_params(ops, vals)
                        for s, ops in q.side_ops.items()},
                    "sel": sel, "ovf": jnp.int64(0)}
        return sel

    @staticmethod
    def _encode_param(value, t: AttrType):
        if t is AttrType.STRING:
            return GLOBAL_STRINGS.encode(str(value))
        if t is AttrType.BOOL:
            return bool(value)
        return value

    # -- tenant lifecycle -------------------------------------------------

    def admit(self) -> tuple[bool, str]:
        """Admission control: (ok, reason). Checked by add_tenant and by
        the service front door BEFORE building anything (429 + reason).
        Takes the pool lock: the mesh branch reads the cached placement
        counts, which migrations rewrite under the lock."""
        with self._lock:
            ok, reason, _cause = self._admit_check()
        return ok, reason

    def _admit_check(self) -> tuple[bool, str, str]:
        """(ok, human reason, machine cause) — the cause slug rides the
        429's ``saturation`` payload (docs/serving.md). On a mesh the
        slot budget is accounted PER DEVICE: max_tenants splits evenly
        over the mesh, and admission rejects when every device's budget
        is spent (balanced placement makes this coincide with the
        global cap; an unbalanced restore surfaces here instead of
        overloading one device)."""
        if len(self._tenants) >= self.max_tenants:
            return False, (f"pool '{self.name}' tenant slots exhausted "
                           f"(cap {self.max_tenants})"), "slots-exhausted"
        if self.mesh is not None:
            # CACHED placement + budget (re-derived on every slot-map
            # change by _recompute_placement_locked — never recomputed
            # here, so a stale cache would be an observable bug, and
            # tests/test_migrate.py asserts it never goes stale)
            alive = self._alive_devices_locked()
            budget = self._slot_budget
            loads = self._placement_counts
            if not alive:
                return False, (f"pool '{self.name}' has no surviving "
                               "mesh devices"), "no-devices"
            if min(loads[d] for d in alive) >= budget:
                return False, (
                    f"pool '{self.name}' per-device slot budgets "
                    f"exhausted ({budget} tenants/device x "
                    f"{len(alive)} surviving devices, placed {loads})"), \
                    "slots-exhausted"
        if self.state_quota_bytes is not None:
            need = (len(self._tenants) + 1) * self.state_bytes_per_tenant
            if need > self.state_quota_bytes:
                per_class = ", ".join(
                    f"{k}={v}" for k, v in
                    sorted(self.state_bytes_by_class.items()))
                return False, (
                    f"pool '{self.name}' per-tenant state quota "
                    f"exhausted ({need} > {self.state_quota_bytes} bytes "
                    f"at {self.state_bytes_per_tenant} bytes/tenant: "
                    f"{per_class})"), \
                    "state-quota"
        return True, "", ""

    # -- saturation signals (obs/slo.py; docs/observability.md) -----------

    def _retry_after_ms(self, pending_rows: int) -> int:
        """Backlog drain estimate: rounds needed at the fair-share rate
        times the EMA round duration — the 429's Retry-After hint."""
        rounds = max(1, math.ceil(pending_rows / max(1, self.batch_max)))
        per_round = self._round_ms_ema if self._round_ms_ema else 1.0
        return int(math.ceil(rounds * max(per_round, 1.0)))

    def _retry_after_flip_ms(self) -> int:
        """Retry hint for the `migrating` cause: the parked queue
        releases at the NEXT round boundary (the flip), so the honest
        estimate is ONE EMA round — not the backlog-drain estimate,
        which assumes the whole queue must empty first and over-reports
        the pause by orders of magnitude (the satellite fix)."""
        per_round = self._round_ms_ema if self._round_ms_ema else 1.0
        return int(math.ceil(max(per_round, 1.0)))

    def _reject(self, cause: str, reason: str,
                tenant: Optional[str] = None, **info):
        """Count + flight-record an admission rejection, then raise
        AdmissionError carrying the machine-readable saturation payload
        (caller holds the pool lock)."""
        self._rejections[cause] = self._rejections.get(cause, 0) + 1
        self._rejection_times.append(time.perf_counter())
        sat = {"cause": cause, **info, **self._saturation_locked()}
        if tenant is not None:
            sat["tenant"] = tenant
        self.flight.record("admission-reject", cause=cause,
                           tenant=tenant, reason=reason)
        raise AdmissionError(reason, saturation=sat)

    def _saturation_locked(self) -> dict:
        """Current pressure signals (host-side only; caller holds the
        lock): queue age, backlog, round-drain lag, rejection counts."""
        now = time.perf_counter()
        ages = [now - q[0][2] for qs in self._pending.values()
                for q in qs.values() if q]
        pending_total = sum(self._pending_rows.values())
        lag = 0.0
        if pending_total and self._last_pump_wall is not None:
            lag = (now - self._last_pump_wall) * 1000.0
        recent = sum(1 for t in self._rejection_times if now - t <= 60.0)
        sat = {
            "pending_rows": pending_total,
            "queue_age_ms_max": round(max(ages) * 1000.0, 1)
            if ages else 0.0,
            "drain_lag_ms": round(lag, 1),
            "round_ms_ema": round(self._round_ms_ema, 2)
            if self._round_ms_ema is not None else None,
            "rejections": dict(self._rejections),
            "rejections_last_60s": recent,
        }
        if self.mesh is not None:
            # the 429 payload must show the REAL per-device placement
            # (the cached counts, re-derived on every slot-map change)
            sat["placement"] = {str(d): self._placement_counts[d]
                                for d in range(self.n_devices)}
            sat["slot_budget"] = self._slot_budget
            if self._lost_devices:
                sat["lost_devices"] = sorted(self._lost_devices)
        return sat

    def saturation(self) -> dict:
        with self._lock:
            return self._saturation_locked()

    def _flight_context(self) -> dict:
        """Host-side pool snapshot for flight-recorder dumps (no device
        reads, no registry re-entrancy)."""
        with self._lock:
            return {
                "pool": self.name, "slots": self.slots,
                "active": len(self._tenants), "rounds": self._rounds,
                "pending": dict(self._pending_rows),
                "saturation": self._saturation_locked(),
            }

    def add_tenant(self, tenant_id: str,
                   bindings: Optional[dict] = None,
                   qos: Optional[dict] = None) -> int:
        """Admit a tenant into a slot: validate bindings
        (template-binding rule), reset the slot's state slice, write the
        stacked parameter values. ``qos`` carries per-tenant dials
        (weight / priority / rate_eps / burst) merged over the pool
        defaults (docs/serving.md "QoS dials"). Steady-state adds
        compile NOTHING — only a growth doubling does."""
        with self._lock:
            if tenant_id in self._tenants:
                raise ValueError(
                    f"tenant '{tenant_id}' is already deployed in pool "
                    f"'{self.name}'")
            ok, reason, cause = self._admit_check()
            if not ok:
                self._reject(cause, reason, tenant=tenant_id,
                             active=len(self._tenants),
                             max_tenants=self.max_tenants,
                             state_bytes_per_tenant=
                             self.state_bytes_per_tenant,
                             state_bytes_by_class=dict(
                                 self.state_bytes_by_class))
            vals = check_template_bindings(self.proto.ast,
                                           dict(bindings or {}))
            if self._qos is not None:
                self._qos.add_tenant(tenant_id, qos)
            if not self._free:
                self._grow()
            slot = self._pick_slot()
            for qn in self._order:
                init = self._tenant_init_states(qn, vals)
                self._states[qn] = jax.tree_util.tree_map(
                    lambda full, iv: full.at[slot].set(iv),
                    self._states[qn], init)
                self._emitted[qn] = self._emitted[qn].at[slot].set(0)
            self._tenants[tenant_id] = slot
            self._bindings[tenant_id] = dict(bindings or {})
            self._tenant_qos_raw[tenant_id] = dict(qos or {})
            self._pending[tenant_id] = self._fresh_queues()
            self._pending_rows[tenant_id] = 0
            self._error_counts[tenant_id] = 0
            self._recompute_placement_locked()
            return slot

    def remove_tenant(self, tenant_id: str) -> bool:
        """Free the tenant's slot (state stays masked-out until the slot
        is reassigned — zero recompiles)."""
        with self._lock:
            slot = self._tenants.pop(tenant_id, None)
            if slot is None:
                return False
            self._free.append(slot)
            self._pending.pop(tenant_id, None)
            self._pending_rows.pop(tenant_id, None)
            self._callbacks.pop(tenant_id, None)
            self._error_counts.pop(tenant_id, None)
            self._bindings.pop(tenant_id, None)
            self._tenant_qos_raw.pop(tenant_id, None)
            if self._qos is not None:
                self._qos.remove_tenant(tenant_id)
            mig = self._migrations.pop(tenant_id, None)
            if mig is not None:
                # an undeployed tenant's reserved target slot and
                # parked rows go with it
                self._free.append(mig["to_slot"])
            self._recompute_placement_locked()
            return True

    def _grow(self) -> None:
        new_slots = self.slots * 2
        if new_slots > self._slot_cap:
            self._reject(
                "slots-exhausted",
                f"pool '{self.name}' tenant slots exhausted "
                f"(cap {self.max_tenants})",
                active=len(self._tenants),
                max_tenants=self.max_tenants)
        log.info("pool '%s': growing tenant axis %d -> %d slots "
                 "(programs recompile at the new width)",
                 self.name, self.slots, new_slots)

        def pad(x):
            return jnp.concatenate(
                [x, jnp.zeros((self.slots,) + x.shape[1:], x.dtype)],
                axis=0)
        self._states = {qn: jax.tree_util.tree_map(pad, st)
                        for qn, st in self._states.items()}
        self._emitted = {qn: pad(e) for qn, e in self._emitted.items()}
        self._free.extend(range(new_slots - 1, self.slots - 1, -1))
        self.slots = new_slots
        if self.mesh is not None:
            # slot-axis growth is one of the two re-placement events
            # (the other is restore): the concatenated arrays come back
            # sharded over the NEW width in one placement pass
            self._place_state()
        if self._lost_devices:
            # growth re-derives slot->device (slots_per_device changed);
            # slots now landing on a lost device must not be handed out
            self._free = [s for s in self._free
                          if self._device_of_slot(s)
                          not in self._lost_devices]
        self._recompute_placement_locked()
        self._vsteps.clear()
        self._grows += 1
        self._warmed = False

    def _slot(self, tenant_id: str) -> int:
        slot = self._tenants.get(tenant_id)
        if slot is None:
            raise KeyError(f"no tenant '{tenant_id}' in pool "
                           f"'{self.name}'")
        return slot

    def tenant_partition(self, tenant_id: str) -> str:
        """Error-store partition key for one tenant (PR 2 store SPI keys
        by app name; each tenant gets its own namespace)."""
        return f"{self.name}/tenant/{tenant_id}"

    def add_callback(self, tenant_id: str, fn: Callable) -> None:
        """Per-tenant output callback: fn(events) with the tenant's rows
        of every terminal stream. A raising callback routes ITS events
        to ITS error-store partition; other tenants are unaffected."""
        with self._lock:
            self._slot(tenant_id)
            self._callbacks.setdefault(tenant_id, []).append(fn)

    # -- ingest (fair round-robin batching) -------------------------------

    def _fresh_queues(self) -> dict:
        return {sid: deque() for sid in self._ingest_streams}

    def send(self, tenant_id: str, ts, cols,
             stream: Optional[str] = None) -> None:
        """Queue one columnar chunk for a tenant (numpy ts + columns,
        STRING columns as dictionary codes — the send_arrays contract).
        ``stream`` routes multi-ingest templates (patterns/joins name
        several ingest streams); single-stream templates may omit it.
        Every chunk is stamped with its host arrival time (one
        perf_counter read — the queue-age saturation signal and the
        ingest side of the sampled ingest->emit span). Dispatch happens
        in fair rounds via pump()/flush() or the background worker."""
        sid = self._resolve_stream(stream)
        ts = np.asarray(ts, dtype=np.int64)
        n = int(ts.shape[0])
        if n == 0:
            return
        cols = [np.ascontiguousarray(c) for c in cols]
        t_arr = time.perf_counter()
        with self._lock:
            if tenant_id not in self._lost_tenants:
                # a lost device's victim keeps its queue: rows buffer
                # here through the outage and drain after evacuation
                self._slot(tenant_id)
            if self._qos is not None:
                # token-bucket rate limit (serving/qos.py): over-rate
                # ingest is rejected BEFORE it queues, with the
                # bucket's own accrual time as the Retry-After hint
                ok, retry_ms = self._qos.check_rate(tenant_id, n)
                if not ok:
                    self._reject(
                        "rate-limited",
                        f"tenant '{tenant_id}' over its ingest rate "
                        f"limit ({n} rows rejected; retry in "
                        f"{retry_ms} ms)",
                        tenant=tenant_id, rows=n,
                        retry_after_ms=retry_ms)
            mig = self._migrations.get(tenant_id)
            if mig is not None:
                # migration pause: in-flight chunks park in the bounded
                # migration queue and release after the flip — the 429
                # here carries the `migrating` cause with the flip
                # latency (one round), NOT the backlog-drain estimate
                if mig["parked_rows"] + n > mig["park_cap"]:
                    self._reject(
                        "migrating",
                        f"tenant '{tenant_id}' is migrating to device "
                        f"{mig['to_device']} and its parked-ingest "
                        f"queue is full ({mig['parked_rows']} rows "
                        f"parked, cap {mig['park_cap']}); retry after "
                        "the round-boundary flip",
                        tenant=tenant_id, rows=n,
                        parked_rows=mig["parked_rows"],
                        park_cap=mig["park_cap"],
                        retry_after_ms=self._retry_after_flip_ms())
                mig["parked"].append((sid, ts, cols, t_arr))
                mig["parked_rows"] += n
                return
            if self._pending_rows[tenant_id] + n > self.pending_cap:
                self._reject(
                    "ingest-backlog",
                    f"tenant '{tenant_id}' ingest backlog full "
                    f"({self._pending_rows[tenant_id]} rows pending, "
                    f"cap {self.pending_cap})",
                    tenant=tenant_id,
                    pending_rows=self._pending_rows[tenant_id],
                    pending_cap=self.pending_cap,
                    retry_after_ms=self._retry_after_ms(
                        self._pending_rows[tenant_id]))
            qs = self._pending.setdefault(tenant_id,
                                          self._fresh_queues())
            qs[sid].append((ts, cols, t_arr))
            self._pending_rows[tenant_id] += n
            self._work.notify()

    def _take(self, tenant_id: str, sid: str, limit: int):
        """Up to `limit` rows off a tenant's pending queue for ONE
        ingest stream (splitting a chunk re-queues the remainder at the
        head — order AND arrival stamp preserved). Returns
        (ts, cols, oldest_arrival)."""
        q = self._pending.get(tenant_id, {}).get(sid)
        if not q:
            return None
        ts_parts, col_parts, taken = [], [], 0
        t_oldest = None
        while q and taken < limit:
            ts, cols, t_arr = q.popleft()
            room = limit - taken
            if len(ts) > room:
                q.appendleft((ts[room:], [c[room:] for c in cols], t_arr))
                ts, cols = ts[:room], [c[:room] for c in cols]
            ts_parts.append(ts)
            col_parts.append(cols)
            taken += len(ts)
            if t_oldest is None:
                t_oldest = t_arr
        if not taken:
            return None
        self._pending_rows[tenant_id] -= taken
        ts = np.concatenate(ts_parts)
        cols = [np.concatenate([p[i] for p in col_parts])
                for i in range(len(col_parts[0]))]
        return ts, cols, t_oldest

    def pump(self) -> int:
        """One fair dispatch round: every tenant contributes up to
        batch_max rows, ONE vmapped step per query advances all of them.
        Returns rows dispatched (0 = nothing pending).

        On every ``slo_engine.every``-th round the SLO engine samples:
        the round blocks after each vmapped query step (the sampled
        branch only — the PR 7 stride contract) and attributes
        arrival->emit latency per (tenant), (tenant, query) and
        pool-wide from the chunks' host arrival stamps."""
        t_round0 = time.perf_counter()
        with self._lock:
            # round boundary: requested migrations flip HERE, before any
            # take — the moving tenant is never dispatched between its
            # request and its flip, so the move is atomic w.r.t. rounds
            self._apply_migrations_locked()
            # sid -> {slot -> (ts, cols)} for this round
            per_stream: dict[str, dict] = {}
            stamps: dict[str, float] = {}
            taken = 0
            last_ts = self._now
            # per-tenant take limits: the DRR/priority plan when QoS is
            # live (serving/qos.py — all-default dials produce exactly
            # batch_max per backlogged tenant), the fixed fair share
            # otherwise. A tenant's limit spends across its ingest
            # streams in stream order — the credit is per tenant, not
            # per (tenant, stream).
            limits = None
            if self._qos is not None:
                limits = self._qos.plan_round(dict(self._pending_rows),
                                              self.batch_max)
            # optional per-DEVICE row budget (device_round_cap): tenants
            # colocated on a saturated device wait for later rounds —
            # the contention signal the rebalancer reads
            dev_budget = None
            if self.mesh is not None and self.device_round_cap:
                dev_budget = [self.device_round_cap] * self.n_devices
            for tid, slot in self._tenants.items():
                limit = self.batch_max if limits is None \
                    else limits.get(tid, 0)
                dev = self._device_of_slot(slot)
                if dev_budget is not None:
                    limit = min(limit, dev_budget[dev])
                if limit <= 0:
                    continue
                for sid in self._ingest_streams:
                    if limit <= 0:
                        break
                    got = self._take(tid, sid, limit)
                    if got is None:
                        continue
                    ts_a, cols_a, t_arr = got
                    n = len(ts_a)
                    limit -= n
                    if dev_budget is not None:
                        dev_budget[dev] -= n
                    per_stream.setdefault(sid, {})[slot] = (ts_a, cols_a)
                    stamps[tid] = min(stamps.get(tid, t_arr), t_arr)
                    taken += n
                    last_ts = max(last_ts, int(ts_a[-1]))
            if not taken:
                self._last_pump_wall = time.perf_counter()
                return 0
            self._now = max(self._now, last_ts)
            if self.mesh is not None:
                # per-device ingest attribution (host counters only;
                # the `device=` labeled gauge family)
                for per_slot in per_stream.values():
                    for slot, (ts_a, _c) in per_slot.items():
                        self._rows_per_device[
                            self._device_of_slot(slot)] += len(ts_a)
            # ONE transfer per ingest stream: the packed (slots, total)
            # buffer, or the stacked EventBatch fallback — either way a
            # single device_put per stream per round
            stream_inputs = {
                sid: [self._ingest_entry(sid, per_slot)]
                for sid, per_slot in per_stream.items()}
            self._ingest_stats["rounds"] += 1
            sampled = self.slo_engine.tick("round")
            terminal, qtimes = self._dispatch(stream_inputs, self._now,
                                              sample=sampled)
            self._rounds += 1
            if self._checkpoint_supervisor is not None:
                # periodic whole-pool checkpoint at the round boundary
                # (state updated, delivery not yet run — the error-store
                # replay covers the delivery tail, at-least-once)
                self._checkpoint_supervisor.on_round(self._rounds)
            if sampled and qtimes:
                self._slo_attribute(stamps, qtimes, taken)
            dur_ms = (time.perf_counter() - t_round0) * 1000.0
            self._round_ms_ema = dur_ms if self._round_ms_ema is None \
                else 0.8 * self._round_ms_ema + 0.2 * dur_ms
            self._last_pump_wall = time.perf_counter()
        self._deliver(terminal)
        return taken

    def _slo_attribute(self, stamps: dict, qtimes: dict,
                       taken: int) -> None:
        """Fold one sampled round's per-query completion times into the
        SLO windows (host wall math only — the sync already happened on
        the sampled branch of _dispatch)."""
        eng = self.slo_engine
        t_end = max(qtimes.values()) if qtimes else None
        oldest = min(stamps.values()) if stamps else None
        for tid, t_arr in stamps.items():
            for qn, t_q in qtimes.items():
                eng.observe((("tenant", tid), ("query", qn)),
                            (t_q - t_arr) * 1000.0)
            if t_end is not None:
                eng.observe((("tenant", tid),),
                            (t_end - t_arr) * 1000.0)
        if t_end is not None and oldest is not None:
            lat = (t_end - oldest) * 1000.0
            eng.observe((), lat)
            self.flight.record("round", rows=taken,
                               tenants=len(stamps),
                               lat_ms=round(lat, 3))

    def flush(self) -> int:
        """Drain every pending chunk through fair rounds."""
        total = 0
        while True:
            n = self.pump()
            if n == 0:
                return total
            total += n

    def advance_time(self, now_ms: int) -> None:
        """Drive time-based window boundaries (and pattern absent
        deadlines) with no traffic: one empty-batch dispatch per ingest
        stream at the given event time (all slots masked invalid — same
        compiled programs as a tiny round)."""
        with self._lock:
            self._now = max(self._now, int(now_ms))
            stream_inputs = {
                sid: [("b", self._stacked_batch({}, BATCH_BUCKETS[0],
                                                sid))]
                for sid in self._ingest_streams}
            terminal, _qt = self._dispatch(stream_inputs, self._now)
        self._deliver(terminal)

    # -- dispatch ---------------------------------------------------------

    def _ingest_entry(self, sid: str, per_slot: dict):
        """One ingest stream's round input as a dispatch entry:
        ``("p", (buf, enc, cap))`` packed (the default — ONE uint8
        device_put for all slots) or ``("b", EventBatch)`` stacked
        (SIDDHI_TPU_POOL_PACKED=0). Updates the packed-ingest stats
        either way (transfers per round, rows vs padded cells)."""
        cap = bucket_capacity(
            max(len(r[0]) for r in per_slot.values()))
        st = self._ingest_stats
        st["transfers"] += 1
        st["rows"] += sum(len(t) for t, _c in per_slot.values())
        st["cells"] += self.slots * cap
        if not self._packed_on:
            return ("b", self._stacked_batch(per_slot, cap, sid))
        return self._pack_round(sid, per_slot, cap)

    def _pack_round(self, sid: str, per_slot: dict, cap: int):
        """Pack one ingest stream's round into ONE (slots, total) uint8
        buffer (core/ingest.py wire format, one row per slot) and ship
        it with a single device_put (mesh: a single SHARDED put — each
        device receives only its slots' rows).

        The stream's sticky encoder widens ROUND-WIDE first
        (`widen_round`) so every slot's row assembles under the same
        final encoding tuple — the enc tuple is part of the jit cache
        key, so it must be one value per transfer. Empty slots stay
        all-zero except the `now` header slot: every row carries the
        round clock, so idle tenants' windows expire on the same clock
        as active ones (the batch flavor's global `now` twin)."""
        from ..core.ingest import PackedEncoder, layout
        enc_ = self._encoders.get(sid)
        if enc_ is None:
            schema = self.proto.junctions[sid].schema
            enc_ = self._encoders[sid] = PackedEncoder(schema)
        chunks = list(per_slot.values())
        enc = enc_.widen_round(chunks)
        _H, _offs, total = layout(len(enc_.schema.types), enc, cap)
        big = np.zeros((self.slots, total), np.uint8)
        # round clock into EVERY slot's header (bytes 16:24 = now)
        big[:, 16:24] = np.frombuffer(np.int64(self._now).tobytes(),
                                      np.uint8)
        for slot, (ts_a, cols_a) in per_slot.items():
            enc_.encode_into(ts_a, cols_a, cap, self._now,
                             out=big[slot])
        self._ingest_stats["bytes"] += big.nbytes
        if self.mesh is None:
            dev = jax.device_put(big)
        else:
            dev = self._sharding.place_leading(big, self.mesh,
                                               axis=self.mesh_axis)
        return ("p", (dev, enc, cap))

    def _stacked_batch(self, per_slot: dict, cap: int,
                       sid: str) -> EventBatch:
        """(slots, cap) stacked EventBatch from per-slot row chunks; one
        device_put for the whole pytree. Slots without rows are
        all-padding (their tenants' states pass through unchanged)."""
        schema = self.proto.junctions[sid].schema
        N = self.slots
        ts = np.zeros((N, cap), np.int64)
        valid = np.zeros((N, cap), np.bool_)
        kind = np.zeros((N, cap), np.int32)
        cols = [np.zeros((N, cap), np_dtype(t)) for t in schema.types]
        for slot, (t, cs) in per_slot.items():
            n = len(t)
            ts[slot, :n] = t
            valid[slot, :n] = True
            for i, c in enumerate(cs):
                cols[i][slot, :n] = c
        batch = EventBatch(
            ts=ts, cols=tuple(cols),
            nulls=tuple(np.zeros((N, cap), np.bool_) for _ in cols),
            kind=kind, valid=valid)
        return self._place_batch(batch)

    def _vstep_for(self, qname: str, label: str, flavor: tuple) \
            -> Callable:
        # warm_specs builders run on compile-pool threads; the lock keeps
        # concurrent builds from double-creating (and double-compiling)
        # the same jit wrapper
        with self._lock:
            return self._vstep_for_locked(qname, label, flavor)

    def _core_body(self, qname: str, label: str) -> Callable:
        """The per-slot step for one (query, input-label): the same
        trace the separate runtimes jit per instance (core/runtime.py
        `_chain_body` / `_step_for_stream` / `_step_for_side`), minus
        table support (poolability rejects tables). Signature
        ``(st, emitted, batch, now) -> (st, emitted, out|None)`` —
        vmapped over the leading slot axis by `_vstep_for_locked`."""
        kind = self._kind[qname]
        rewrite = self._q_out.get(qname) is not None
        if kind == "agg":
            astep = self._aggs[qname]._make_step()

            def agg_body(st, emitted, batch, now):
                st = astep(st, batch)
                emitted = emitted + batch.count().astype(jnp.int64)
                return st, emitted, None
            return agg_body
        q = self.proto.queries[qname]
        sel_ops = q.operators
        if kind == "chain":
            chain = _chain_body(q.operators, q._has_timers)

            def body(states, emitted, batch, now):
                states, _t, emitted, out, _due = chain(
                    states, {}, emitted, batch, now)
                if rewrite:
                    # insert-into kind rewrite inside the trace, exactly
                    # like FusedChain hops
                    out = _as_current(out)
                return states, emitted, out
            return body
        if kind == "pattern":
            nfa_step = q.engine.make_stream_step(label[2:])

            def pbody(st, emitted, batch, now):
                nfa_state, match = nfa_step(st["nfa"], batch, now)
                new_sel = []
                for op, s in zip(sel_ops, st["sel"]):
                    s, match = op.step(s, match, now)
                    new_sel.append(s)
                emitted = emitted + match.count().astype(jnp.int64)
                if rewrite:
                    match = _as_current(match)
                return ({"nfa": nfa_state, "sel": tuple(new_sel)},
                        emitted, match)
            return pbody
        # join: ONE whole-dict donated state per query — the opposite
        # side's leaves are read inside and returned unchanged (an
        # identity alias of the donated input, which is exactly what
        # donation wants), so L and R steps share one state home
        from ..ops.join import combined_schema
        side = label
        opp = "R" if side == "L" else "L"
        my_ops = q.side_ops[side]
        opp_window = q.side_ops[opp][-1] if q.side_ops[opp] else None
        cross = q.crosses[side]
        gate_alive = self.proto._columnar

        def jbody(st, emitted, batch, now):
            sides = st["sides"]
            new_my = []
            for op, s in zip(my_ops, sides[side]):
                s, batch = op.step(s, batch, now)
                new_my.append(s)
            if cross is not None:
                opp_buf = opp_window.findable_buffer(sides[opp][-1])
                joined, lost = cross.cross(batch, opp_buf,
                                           gate_alive=gate_alive)
            else:
                sch = combined_schema("#j", q.in_schemas["L"],
                                      q.in_schemas["R"])
                joined = EventBatch.empty(sch, 16)
                lost = jnp.int64(0)
            new_sel = []
            for op, s in zip(sel_ops, st["sel"]):
                s, joined = op.step(s, joined, now)
                new_sel.append(s)
            emitted = emitted + joined.count().astype(jnp.int64)
            if rewrite:
                joined = _as_current(joined)
            return ({"sides": {side: tuple(new_my),
                               opp: sides[opp]},
                     "sel": tuple(new_sel),
                     "ovf": st["ovf"] + lost},
                    emitted, joined)
        return jbody

    def _vstep_for_locked(self, qname: str, label: str,
                          flavor: tuple) -> Callable:
        """jit(vmap(...)) step for one (query, input label, flavor):
        flavor ``("b", cap)`` takes a stacked EventBatch + global now,
        ``("p", enc, cap)`` unpacks the packed round buffer per slot
        (core/ingest.py — each slot's header carries the round clock),
        ``("t",)`` is the pattern absent-deadline timer step. States
        and emitted donate; the batch/buffer argument never does (a
        fan-out template dispatches the same entry to several
        queries)."""
        key = (qname, label, flavor, self.slots)
        fn = self._vsteps.get(key)
        if fn is None:
            if flavor[0] == "t":
                q = self.proto.queries[qname]
                tstep = q.engine.make_timer_step()
                sel_ops = q.operators
                rewrite = self._q_out.get(qname) is not None

                def tbody(st, emitted, now):
                    nfa_state, match = tstep(st["nfa"], now)
                    new_sel = []
                    for op, s in zip(sel_ops, st["sel"]):
                        s, match = op.step(s, match, now)
                        new_sel.append(s)
                    emitted = emitted + match.count().astype(jnp.int64)
                    if rewrite:
                        match = _as_current(match)
                    return ({"nfa": nfa_state, "sel": tuple(new_sel)},
                            emitted, match)

                fn = jax.jit(jax.vmap(tbody, in_axes=(0, 0, None)),
                             **_donate(0, 1))
            elif flavor[0] == "p":
                from ..core.ingest import unpack_buffer
                _tag, enc, cap = flavor
                core = self._core_body(qname, label)
                sid = next(s for lab, s in self._q_in[qname]
                           if lab == label)
                schema = self.proto.junctions[sid].schema

                def pk_body(st, emitted, buf):
                    batch, now = unpack_buffer(schema, enc, cap, buf)
                    return core(st, emitted, batch, now)

                fn = jax.jit(jax.vmap(pk_body, in_axes=(0, 0, 0)),
                             **_donate(0, 1))
            else:
                core = self._core_body(qname, label)
                fn = jax.jit(jax.vmap(core, in_axes=(0, 0, 0, None)),
                             **_donate(0, 1))
            self._vsteps[key] = fn
        return fn

    def _run_step(self, qname: str, label: str, entry,
                  now_dev) -> Optional[EventBatch]:
        """Advance one query's stacked state over one dispatch entry;
        returns the stacked out batch (None for aggregations)."""
        if entry[0] == "p":
            buf, enc, cap = entry[1]
            step = self._vstep_for(qname, label, ("p", enc, cap))
            args = (self._states[qname], self._emitted[qname], buf)
        else:
            batch = entry[1]
            cap = int(batch.ts.shape[1])
            step = self._vstep_for(qname, label, ("b", cap))
            args = (self._states[qname], self._emitted[qname], batch,
                    now_dev)
        self._states[qname], self._emitted[qname], out = step(*args)
        self._dispatches += 1
        return out

    def _dispatch(self, stream_inputs: dict, now: int,
                  sample: bool = False) -> tuple[dict, dict]:
        """Run the template's query graph over one stacked round;
        ``stream_inputs`` maps ingest stream -> list of entries
        (``("b", batch)`` / ``("p", (buf, enc, cap))``). Returns
        ({terminal stream id: [stacked out batches]} (device),
        {query: host completion time}). Patterns with absent deadlines
        additionally run their vmapped timer step every dispatch (the
        pool's round clock replaces the host scheduler — absent
        matches fire at round boundaries). The completion times are
        only populated when ``sample`` is set: that branch blocks after
        each query's last step (``block_until_ready`` — NOT a
        device_get; the one-device-read-per-pool stats contract is
        untouched) so the per-query ingest->emit attribution is
        honest."""
        now_dev = jnp.asarray(now, dtype=jnp.int64)
        stream_batches = {sid: list(entries)
                          for sid, entries in stream_inputs.items()}
        terminal: dict = {}
        qtimes: dict = {}
        for qname in self._order:
            outs = []
            for label, sid in self._q_in[qname]:
                for entry in stream_batches.get(sid, ()):
                    out = self._run_step(qname, label, entry, now_dev)
                    if out is not None:
                        outs.append(out)
            if self._kind[qname] == "pattern" and \
                    self.proto.queries[qname].engine.has_absent:
                step = self._vstep_for(qname, "timer", ("t",))
                self._states[qname], self._emitted[qname], out = step(
                    self._states[qname], self._emitted[qname], now_dev)
                self._dispatches += 1
                outs.append(out)
            if sample and outs:
                # sampled branch ONLY (1-in-slo_engine.every rounds):
                # the sync is the point — per-query ingest->emit
                # attribution needs the step provably finished
                # (the PR 7 sampled-probe pattern)
                jax.block_until_ready(outs[-1].valid)  # lint: disable=host-sync-in-loop
                qtimes[qname] = time.perf_counter()
            tgt = self._q_out[qname]
            if not outs or tgt is None:
                continue
            if tgt in self._terminal:
                terminal.setdefault(tgt, []).extend(outs)
            else:
                stream_batches.setdefault(tgt, []).extend(
                    ("b", o) for o in outs)
        return terminal, qtimes

    def _deliver(self, terminal: dict) -> None:
        for fn in self.batch_callbacks:
            fn(terminal)   # device batches, zero sync (bench fast path)
        if not self._callbacks or not terminal:
            return
        host = jax.device_get(terminal)   # ONE transfer for every tenant
        with self._lock:
            targets = [(tid, self._tenants[tid], list(cbs))
                       for tid, cbs in self._callbacks.items()
                       if tid in self._tenants]
        for tid, slot, cbs in targets:
            per_sid = []
            for sid, outs in host.items():
                evs = []
                for out in outs:
                    evs.extend(self._decode_slot(sid, out, slot))
                if evs:
                    per_sid.append((sid, evs))
            if not per_sid:
                continue
            self._deliver_tenant(tid, cbs, per_sid)

    def _deliver_tenant(self, tid: str, cbs: list,
                        per_sid: list) -> None:
        """Deliver one tenant's decoded rows through its circuit
        breaker (serving/qos.py): OPEN short-circuits every stream's
        events to the tenant's error-store partition WITHOUT running
        the callback, HALF_OPEN lets exactly one probe delivery
        through, and the delivery outcome feeds the state machine.
        Shared by the round delivery path and replay_errors."""
        gate = "closed"
        if self._qos is not None:  # lint: disable=racy-attribute-read (qos ref rebinds only under restore quiesce; a stale ref delays new dials one round)
            with self._lock:
                # gate() on an elapsed cooldown IS the HALF_OPEN
                # transition, so it runs only when rows are in hand
                gate = self._qos.breaker_gate(tid)
        if gate == "open":
            for sid, events in per_sid:
                self._short_circuit(tid, sid, events)
            return
        failed = False
        for sid, events in per_sid:
            for cb in cbs:
                try:
                    cb(events)
                except Exception as exc:  # noqa: BLE001 — isolate
                    failed = True
                    self._tenant_error(tid, sid, events, exc)
        if self._qos is not None:  # lint: disable=racy-attribute-read (qos ref rebinds only under restore quiesce; a stale ref delays new dials one round)
            with self._lock:
                self._qos.on_delivery(tid, ok=not failed)

    def _short_circuit(self, tid: str, sid: str, events: list) -> None:
        """OPEN-breaker path: the events survive in the tenant's error
        partition (replayable) but its failing callback never runs."""
        from ..resilience.errorstore import ErroredEvent
        with self._lock:
            self._qos.count_short_circuit(tid, len(events))
            self._error_counts[tid] = \
                self._error_counts.get(tid, 0) + len(events)
        try:
            self.proto._error_store().store(
                self.tenant_partition(tid),
                ErroredEvent.from_events(
                    sid, events, "circuit-open: delivery short-circuited",
                    now=self._now))  # lint: disable=racy-attribute-read (monotonic round clock; an error-record timestamp one round stale is tolerable)
        except Exception:  # noqa: BLE001 — isolation must not cascade
            log.exception("pool '%s': error-store write failed for "
                          "short-circuited tenant '%s'", self.name, tid)

    def _on_breaker_transition(self, tid: str, prev: str,
                               state: str) -> None:
        self.flight.record("breaker-transition", tenant=tid,
                           prev=prev, state=state)
        log.warning("pool '%s': tenant '%s' circuit breaker %s -> %s",
                    self.name, tid, prev, state)

    def _decode_slot(self, sid: str, host_out, slot: int) -> list:
        types = self.proto.junctions[sid].schema.types
        row = EventBatch(
            ts=host_out.ts[slot], cols=tuple(c[slot]
                                             for c in host_out.cols),
            nulls=tuple(nl[slot] for nl in host_out.nulls),
            kind=host_out.kind[slot], valid=host_out.valid[slot])
        return [Event(ts, vals, is_expired=(kind == EXPIRED))
                for ts, kind, vals in rows_from_batch(types, row)]

    def _tenant_error(self, tid: str, sid: str, events: list,
                      exc: Exception) -> None:
        """Sink-failure isolation: the failing tenant's events land in
        ITS error-store partition; delivery to other tenants continues."""
        from ..resilience.errorstore import ErroredEvent
        with self._lock:
            self._error_counts[tid] = \
                self._error_counts.get(tid, 0) + len(events)
        try:
            self.proto._error_store().store(
                self.tenant_partition(tid),
                ErroredEvent.from_events(
                    sid, events, f"{type(exc).__name__}: {exc}",
                    now=self._now))  # lint: disable=racy-attribute-read (monotonic round clock; an error-record timestamp one round stale is tolerable)
        except Exception:  # noqa: BLE001 — isolation must not cascade
            log.exception("pool '%s': error-store write failed for "
                          "tenant '%s'", self.name, tid)
        log.warning("pool '%s': tenant '%s' callback failed on stream "
                    "'%s' (%d event(s) -> partition '%s'): %s",
                    self.name, tid, sid, len(events),
                    self.tenant_partition(tid), exc)

    # -- AOT warmup (one program set per template) ------------------------

    def _spec_key_base(self) -> str:
        """Content-addressed spec key prefix: template hash + shared
        structural bindings + mesh width. Two pools instantiating the
        same (template, shared) pair produce byte-identical programs, so
        their specs must carry IDENTICAL keys — the CompileService key
        dedupe and the persistent compile cache both line up on it
        (a pool's display name never reaches the key)."""
        base = f"tpl:{self.template.key}"
        if self.shared:
            blob = repr(sorted(self.shared.items()))
            base += "+" + hashlib.sha256(blob.encode()).hexdigest()[:8]
        if self.n_devices > 1:
            base += f"@mesh{self.n_devices}"
        return base

    def _warm_spec_list(self, caps=None) -> list:
        """The pool's vmapped step specs for the given row caps — the
        list warmup() compiles and the compiled-program auditor
        (analysis/programs.py audit_pool) traces abstractly. Builders
        route every allocation through core/compile.py's mode-aware
        helpers; mesh placement only happens on the concrete (warmup)
        path — placing needs real buffers, and the audit never builds
        any (it sees the single-device twin of each program)."""
        from ..core.compile import (CompileSpec, spec_args_abstract,
                                    zeros_array)
        from ..core.ingest import initial_encoding, layout
        caps = sorted({bucket_capacity(min(int(c), self.batch_max))
                       for c in (caps or (self.batch_max,))})
        base = self._spec_key_base()
        specs = []

        def place(qname, states, emitted, batch=None, buf=None):
            if self.mesh is None or spec_args_abstract():
                return states, emitted, batch, buf
            # warm SHARDED programs: the example args must carry the
            # runtime placement or the AOT compile lands on a
            # different (and never-dispatched) single-device program
            placed = self._sharding.shard_pytree(
                {"states": {qname: states},
                 "emitted": {qname: emitted}},
                self.mesh, self._sharding.POOL_STATE_RULES,
                axis=self.mesh_axis)
            states = placed["states"][qname]
            emitted = placed["emitted"][qname]
            if batch is not None:
                batch = self._place_batch(batch)
            if buf is not None:
                buf = self._sharding.place_leading(
                    buf, self.mesh, axis=self.mesh_axis)
            return states, emitted, batch, buf

        with self._lock:
            slots = self.slots
            ingest = set(self._ingest_streams)
            for cap in caps:
                for qname in self._order:
                    for label, sid in self._q_in[qname]:
                        lab = "" if label == "*" \
                            else "/" + label.replace(":", "-")
                        schema = self.proto.junctions[sid].schema

                        def build(qname=qname, label=label, cap=cap,
                                  schema=schema):
                            fn = self._vstep_for(qname, label,
                                                 ("b", cap))
                            states = _tree_zeros(self._states[qname])
                            emitted = zeros_array((slots,), jnp.int64)
                            N = slots
                            batch = EventBatch(
                                ts=zeros_array((N, cap), jnp.int64),
                                cols=tuple(
                                    zeros_array((N, cap), np_dtype(t))
                                    for t in schema.types),
                                nulls=tuple(
                                    zeros_array((N, cap), jnp.bool_)
                                    for _ in schema.types),
                                kind=zeros_array((N, cap), jnp.int32),
                                valid=zeros_array((N, cap), jnp.bool_))
                            states, emitted, batch, _ = place(
                                qname, states, emitted, batch=batch)
                            return fn, (states, emitted, batch,
                                        zeros_array((), jnp.int64))
                        specs.append(CompileSpec(
                            f"{base}/{qname}{lab}/v{slots}x{cap}",
                            build))
                        if not (self._packed_on and sid in ingest):
                            continue
                        # packed flavor: one spec per current sticky
                        # encoding (the enc tuple is part of the key —
                        # a widened stream warms its new shape)
                        enc_obj = self._encoders.get(sid)
                        enc = enc_obj.encoding if enc_obj is not None \
                            else initial_encoding(schema)

                        def pbuild(qname=qname, label=label, cap=cap,
                                   schema=schema, enc=enc):
                            fn = self._vstep_for(qname, label,
                                                 ("p", enc, cap))
                            states = _tree_zeros(self._states[qname])
                            emitted = zeros_array((slots,), jnp.int64)
                            _H, _o, total = layout(len(schema.types),
                                                   enc, cap)
                            buf = zeros_array((slots, total),
                                              jnp.uint8)
                            states, emitted, _, buf = place(
                                qname, states, emitted, buf=buf)
                            return fn, (states, emitted, buf)
                        specs.append(CompileSpec(
                            f"{base}/{qname}{lab}/v{slots}x{cap}"
                            f"/pk-{'.'.join(enc)}", pbuild))
            # pattern absent-deadline timer steps (cap-independent)
            for qname in self._order:
                if self._kind[qname] != "pattern" or \
                        not self.proto.queries[qname].engine.has_absent:
                    continue

                def tbuild(qname=qname):
                    fn = self._vstep_for(qname, "timer", ("t",))
                    states = _tree_zeros(self._states[qname])
                    emitted = zeros_array((slots,), jnp.int64)
                    states, emitted, _, _ = place(qname, states,
                                                  emitted)
                    return fn, (states, emitted,
                                zeros_array((), jnp.int64))
                specs.append(CompileSpec(
                    f"{base}/{qname}/timer/v{slots}", tbuild))
        return specs

    def warmup(self, caps=None, workers: Optional[int] = None) -> dict:
        """Compile the pool's vmapped step programs through the
        prototype's PR 5 CompileService (parallel lowering + persistent
        cache + telemetry) BEFORE the first tenant's traffic: telemetry
        lands in statistics()['compile'] exactly once per pool no matter
        how many tenants deploy. Specs are keyed by template content
        (not pool name) and the service skips keys it already compiled,
        so re-warms with overlapping cap lists lower only the NEW
        shapes."""
        result = self.proto.compile_service.warm_specs(
            self._warm_spec_list(caps), workers=workers)
        self._warmed = True
        return result

    def audit_programs(self, caps=None, **kw) -> dict:
        """Static audit of the pool's vmapped programs (zero
        executions/compiles — analysis/programs.py): donation aliasing,
        host callbacks, dtype drift, @app:cap(program.mb=) budget. The
        summary rides statistics()['compile']['audit']."""
        from ..analysis.programs import audit_pool
        return audit_pool(self, caps=caps, **kw).summary()

    @property
    def ready(self) -> bool:
        """Load-balancer readiness: no AOT warmup in flight."""
        return self.proto.compile_service.ready

    # -- background worker (service front door) ---------------------------

    def start(self) -> None:
        """Run the fair-batching drain loop on a daemon thread (the
        tenant-aware @Async queue worker)."""
        with self._lock:
            if self._running:
                return
            self._running = True
        self._worker = threading.Thread(
            target=self._drain_loop, daemon=True,
            name=f"tenantpool-{self.name}")
        self._worker.start()

    def _drain_loop(self) -> None:
        while True:
            with self._lock:
                while self._running and not any(
                        self._pending_rows.get(t, 0)
                        for t in self._tenants):
                    self._work.wait(timeout=0.5)
                if not self._running:
                    return
            try:
                self.pump()
            except Exception:  # noqa: BLE001 — keep serving other rounds
                log.exception("pool '%s': dispatch round failed",
                              self.name)

    def shutdown(self) -> None:
        with self._lock:
            self._running = False
            self._work.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=5)
            self._worker = None

    # -- per-tenant snapshot / restore ------------------------------------

    def snapshot_tenant(self, tenant_id: str) -> bytes:
        """One tenant's state: the tenant-axis slice of every query
        state + emitted counter, serialized with the restricted
        snapshot pickler (core/persistence.py)."""
        from ..core.persistence import dump_strings, serialize
        with self._lock:
            slot = self._slot(tenant_id)
            payload = {
                "pool": self.name,
                "template": self.template.key,
                "tenant": tenant_id,
                "queries": jax.device_get({
                    qn: {"states": jax.tree_util.tree_map(
                            lambda x: x[slot], self._states[qn]),
                         "emitted": self._emitted[qn][slot]}
                    for qn in self._order}),
                "strings": dump_strings(),
            }
            return serialize(payload)

    def restore_tenant(self, tenant_id: str, data: bytes) -> None:
        """Write a snapshot back into the tenant's slot; every other
        index of the tenant axis is untouched (bit-identical)."""
        from ..core.persistence import deserialize, load_strings
        payload = deserialize(data)
        if payload.get("template") != self.template.key:
            raise ValueError(
                f"snapshot is for template {payload.get('template')!r}, "
                f"pool '{self.name}' runs {self.template.key!r}")
        with self._lock:
            slot = self._slot(tenant_id)
            load_strings(payload["strings"])
            for qn in self._order:
                snap = payload["queries"][qn]
                self._states[qn] = jax.tree_util.tree_map(
                    lambda full, s: full.at[slot].set(jnp.asarray(s)),
                    self._states[qn], snap["states"])
                self._emitted[qn] = self._emitted[qn].at[slot].set(
                    jnp.asarray(snap["emitted"]))

    # -- aggregation query side -------------------------------------------

    def materialize_tenant(self, tenant_id: str, agg_id: str,
                           duration: str, start: Optional[int] = None,
                           end: Optional[int] = None):
        """One tenant's `within/per` view of a pooled incremental
        aggregation: slice the tenant's slot out of the stacked bucket
        tables (one device_get of one slot's slice) and materialize it
        host-side through the aggregation runtime's own projection
        (core/aggregation.py materialize_from) — bit-identical to a
        separate runtime fed the same rows."""
        with self._lock:
            ar = self._aggs.get(agg_id)
            if ar is None:
                raise KeyError(
                    f"no aggregation '{agg_id}' in pool '{self.name}' "
                    f"(aggregations: {sorted(self._aggs)})")
            slot = self._slot(tenant_id)
            d = ar.duration_key(duration)
            host = jax.device_get(jax.tree_util.tree_map(
                lambda x: x[slot], self._states[agg_id][d]))
        return ar.materialize_from(host, d, start, end)

    # -- live slot migration (serving/migrate.py orchestrates; docs/
    # serving.md "Live migration & rebalance") ----------------------------

    def request_migration(self, tenant_id: str, device: int,
                          cause: str = "manual",
                          park_cap: Optional[int] = None) -> dict:
        """Reserve a free slot on ``device`` and start parking the
        tenant's new ingest in a bounded migration queue. The actual
        state move + slot-map flip happens at the NEXT round boundary
        (`_apply_migrations_locked`, called at the top of pump while
        the lock is held). Returns the planned move."""
        if self.mesh is None:
            raise ValueError(
                f"pool '{self.name}' has no mesh — migration moves a "
                "slot between mesh devices")
        with self._lock:
            slot = self._slot(tenant_id)
            if not 0 <= device < self.n_devices:
                raise ValueError(
                    f"device {device} out of range "
                    f"(mesh has {self.n_devices})")
            if device in self._lost_devices:
                raise ValueError(f"device {device} is marked lost")
            src = self._device_of_slot(slot)
            if device == src:
                raise ValueError(
                    f"tenant '{tenant_id}' is already on device "
                    f"{device}")
            if tenant_id in self._migrations:
                raise ValueError(
                    f"tenant '{tenant_id}' already has a migration "
                    "in flight")
            target = None
            for i, s in enumerate(self._free):
                if self._device_of_slot(s) == device:
                    target = self._free.pop(i)
                    break
            if target is None:
                raise ValueError(
                    f"no free slot on device {device} for tenant "
                    f"'{tenant_id}'")
            self._migrations[tenant_id] = {
                "from_slot": slot, "from_device": src,
                "to_slot": target, "to_device": device,
                "cause": cause, "parked": deque(), "parked_rows": 0,
                "park_cap": int(park_cap) if park_cap
                else self.pending_cap,
                "t_req": time.perf_counter(),
            }
            self.flight.record(
                "migration-request", tenant=tenant_id, cause=cause,
                from_={"slot": slot, "device": src},
                to={"slot": target, "device": device})
            return {"tenant": tenant_id, "from_slot": slot,
                    "from_device": src, "to_slot": target,
                    "to_device": device}

    def _apply_migrations_locked(self) -> list:
        """Flip every requested migration at this round boundary
        (caller holds the pool RLock — holding it across a pump round
        IS the boundary). Per move: slice the source slot exactly like
        `snapshot_tenant` (the PR 15 per-slot machinery), write it into
        the reserved target slot with `.at[slot].set` on the SHARDED
        arrays — XLA routes the slice to the target device through the
        PR 12 placement, zero recompiles — then flip the slot map,
        release the parked chunks in arrival order, and assert row
        conservation. Every move is flight-recorded with its cause and
        before/after placement."""
        if not self._migrations:
            return []
        results = []
        t0 = time.perf_counter()
        # ONE host round-trip for every flip this boundary (the
        # snapshot_tenant slice per tenant, batched into a single
        # pytree transfer): fresh buffers on write keep the
        # donation-safe contract
        moved_all = jax.device_get({
            tid: {qn: {"states": jax.tree_util.tree_map(
                           lambda x, s=mig["from_slot"]: x[s],
                           self._states[qn]),
                       "emitted":
                           self._emitted[qn][mig["from_slot"]]}
                  for qn in self._order}
            for tid, mig in self._migrations.items()})
        for tid, mig in list(self._migrations.items()):
            old, new = mig["from_slot"], mig["to_slot"]
            moved = moved_all[tid]
            for qn in self._order:
                snap = moved[qn]
                self._states[qn] = jax.tree_util.tree_map(
                    lambda full, s: full.at[new].set(jnp.asarray(s)),
                    self._states[qn], snap["states"])
                self._emitted[qn] = self._emitted[qn].at[new].set(
                    jnp.asarray(snap["emitted"]))
            self._tenants[tid] = new
            self._free.append(old)
            # release the parked chunks BEHIND the surviving pending
            # tail (they arrived later; arrival stamps ride along), then
            # assert conservation: parked + pending in == pending out
            before = self._pending_rows.get(tid, 0)
            parked = mig["parked_rows"]
            qs = self._pending.setdefault(tid, self._fresh_queues())
            for sid, ts, cols, t_arr in mig["parked"]:
                qs[sid].append((ts, cols, t_arr))
            self._pending_rows[tid] = before + parked
            actual = sum(len(t) for q in qs.values()
                         for t, _c, _a in q)
            assert actual == self._pending_rows[tid], (
                f"migration row conservation broken for '{tid}': "
                f"{actual} queued != {before} pending + {parked} parked")
            pause_ms = (time.perf_counter() - mig["t_req"]) * 1000.0
            flip_ms = (time.perf_counter() - t0) * 1000.0
            rec = {"tenant": tid, "cause": mig["cause"],
                   "from": {"slot": old, "device": mig["from_device"]},
                   "to": {"slot": new, "device": mig["to_device"]},
                   "rows_moved": self._pending_rows[tid],
                   "parked_rows": parked,
                   "pause_ms": round(pause_ms, 3),
                   "flip_ms": round(flip_ms, 3),
                   "round": self._rounds}
            del self._migrations[tid]
            self._migration_log.append(rec)
            self._migrations_done += 1
            self._rows_migrated += rec["rows_moved"]
            self._migration_pause_ms_last = rec["pause_ms"]
            self.flight.record("migration", **rec)
            log.info("pool '%s': migrated tenant '%s' slot %d(d%d) -> "
                     "%d(d%d) (%s, %d rows, pause %.1f ms)",
                     self.name, tid, old, mig["from_device"], new,
                     mig["to_device"], mig["cause"], rec["rows_moved"],
                     rec["pause_ms"])
            results.append(rec)
        if self.mesh is not None:
            # dedupe re-placement pass through the rule tables: every
            # leaf already carries the slot-axis sharding, so this
            # transfers NOTHING (shard_pytree's skip contract) — it re-
            # asserts the layout instead of trusting the .at[] writes
            self._place_state()
        self._recompute_placement_locked()
        self._work.notify()
        return results

    def migrate_tenant(self, tenant_id: str, device: int,
                       cause: str = "manual",
                       park_cap: Optional[int] = None) -> dict:
        """Request + flip in ONE held-lock critical section: the RLock
        spans both, no pump round can interleave, so the call site sees
        a completed move (service endpoint + rebalancer entry point).
        Returns the migration record (cause, before/after placement,
        rows moved, pause ms)."""
        with self._lock:
            self.request_migration(tenant_id, device, cause=cause,
                                   park_cap=park_cap)
            recs = self._apply_migrations_locked()
        return next(r for r in recs if r["tenant"] == tenant_id)

    def migration_log(self) -> list:
        with self._lock:
            return list(self._migration_log)

    # -- device loss & degraded mode (serving/migrate.py evacuate();
    # docs/resilience.md "Device evacuation") -----------------------------

    def mark_device_lost(self, device: int) -> dict:
        """Degraded mode: mark one mesh device lost
        (`FaultInjector.kill_device` arms this). Its slots leave the
        free list, its tenants move to the lost set (pending queues and
        error partitions RETAINED — they drain after evacuation),
        admission budgets re-derive over the survivors, and pump keeps
        serving the surviving slots. `serving.migrate.evacuate`
        restores the victims from the newest pool checkpoint."""
        if self.mesh is None:
            raise ValueError(f"pool '{self.name}' has no mesh")
        with self._lock:
            if not 0 <= device < self.n_devices:
                raise ValueError(
                    f"device {device} out of range "
                    f"(mesh has {self.n_devices})")
            if device in self._lost_devices:
                return {"device": device, "victims": []}
            if len(self._lost_devices) + 1 >= self.n_devices:
                raise ValueError(
                    f"pool '{self.name}': cannot lose device {device} "
                    "— no surviving device would remain")
            self._lost_devices.add(device)
            self._free = [s for s in self._free
                          if self._device_of_slot(s) != device]
            victims = sorted(
                tid for tid, slot in self._tenants.items()
                if self._device_of_slot(slot) == device)
            for tid in victims:
                self._lost_tenants[tid] = self._tenants.pop(tid)
            # cancel any in-flight migration touching the dead device:
            # parked rows fall back onto the pending queue (retained)
            for tid, mig in list(self._migrations.items()):
                if device not in (mig["from_device"],
                                  mig["to_device"]):
                    continue
                if mig["to_device"] != device:
                    self._free.append(mig["to_slot"])
                qs = self._pending.setdefault(tid,
                                              self._fresh_queues())
                for sid, ts, cols, t_arr in mig["parked"]:
                    qs[sid].append((ts, cols, t_arr))
                self._pending_rows[tid] = \
                    self._pending_rows.get(tid, 0) + mig["parked_rows"]
                del self._migrations[tid]
                self.flight.record("migration-cancelled", tenant=tid,
                                   reason=f"device {device} lost")
            self._recompute_placement_locked()
            self.flight.record("device-lost", device=device,
                               victims=victims,
                               survivors=len(self._tenants))
            log.warning("pool '%s': device %d lost — %d victim(s) %s "
                        "await evacuation, %d tenant(s) keep serving",
                        self.name, device, len(victims), victims,
                        len(self._tenants))
            return {"device": device, "victims": victims}

    def lost_tenants(self) -> dict:
        with self._lock:
            return dict(self._lost_tenants)

    # -- whole-pool checkpoint / crash recovery ---------------------------
    # (resilience/supervisor.py PoolCheckpointSupervisor drives these;
    # docs/resilience.md "Pool recovery")

    def snapshot(self) -> bytes:
        """Whole-pool state in ONE device_get: every query's stacked
        (slots, ...) state pytree + emitted counters (the slot-sliced
        per-tenant machinery reads the same arrays one index at a
        time), plus the slot map, tenant bindings, and QoS dials needed
        to rebuild admission bookkeeping on a fresh pool."""
        from ..core.persistence import dump_strings, serialize
        with self._lock:
            payload = {
                "kind": "tenant-pool",
                "pool": self.name,
                "template": self.template.key,
                "shared": dict(self.shared),
                "slots": self.slots,
                "now": self._now,
                "rounds": self._rounds,
                "tenants": {
                    tid: {"slot": slot,
                          "bindings": dict(self._bindings.get(tid, {})),
                          "qos": dict(self._tenant_qos_raw.get(tid, {}))}
                    for tid, slot in self._tenants.items()},
                "queries": jax.device_get({
                    qn: {"states": self._states[qn],
                         "emitted": self._emitted[qn]}
                    for qn in self._order}),
                "strings": dump_strings(),
            }
            return serialize(payload)

    def persist(self) -> str:
        """Checkpoint to the manager's persistence store (the
        filesystem backend writes tmp + rename, so a crash mid-persist
        never leaves a torn revision); returns the revision id."""
        from ..core.persistence import new_revision
        store = self.proto._persistence_store()
        rev = new_revision(self.name)
        store.save(self.name, rev, self.snapshot())
        return rev

    def restore(self, data: bytes) -> None:
        """Write a whole-pool snapshot onto THIS pool (typically a
        fresh one built from the same template after a crash). Stacked
        states land as fresh device buffers (``jnp.asarray`` of the
        host snapshot — the donation-safe `_fresh_device` contract) and
        on a mesh the placement is re-derived through the
        parallel/sharding.py rule tables, never copied from the dead
        process. QoS profiles are rebuilt from the snapshot's dials;
        circuit breakers restart CLOSED (a still-dead sink re-trips
        within `breaker.failures` rounds)."""
        from ..core.persistence import deserialize, load_strings
        payload = deserialize(data)
        if payload.get("kind") != "tenant-pool":
            raise ValueError("snapshot is not a tenant-pool snapshot")
        if payload.get("template") != self.template.key:
            raise ValueError(
                f"snapshot is for template {payload.get('template')!r}, "
                f"pool '{self.name}' runs {self.template.key!r}")
        if dict(payload.get("shared") or {}) != self.shared:
            raise ValueError(
                "snapshot was taken with different shared structural "
                "bindings — that is a different compiled program set")
        with self._lock:
            load_strings(payload["strings"])
            slots = int(payload["slots"])
            if self.mesh is not None:
                self._sharding.check_divisible(
                    slots, self.mesh, f"pool '{self.name}' restored slots")
            if slots != self.slots:
                # restored width != fresh-pool width: programs compile
                # at the snapshot's slot count (same class of event as
                # a growth doubling)
                self._vsteps.clear()
                self._warmed = False
            self.slots = slots
            # _fresh_device, not jnp.asarray: device_put may alias a
            # numpy buffer ZERO-COPY, and these arrays feed DONATED
            # step arguments on the next round (the restore
            # double-free class, core/runtime.py)
            self._states = {
                qn: _fresh_device(payload["queries"][qn]["states"])
                for qn in self._order}
            self._emitted = {
                qn: _fresh_device(payload["queries"][qn]["emitted"])
                for qn in self._order}
            if self.mesh is not None:
                self._place_state()   # rule-table placement, re-derived
            self._now = max(self._now, int(payload.get("now", self._now)))
            self._rounds = int(payload.get("rounds", 0))
            self._tenants = {}
            self._bindings = {}
            self._tenant_qos_raw = {}
            self._pending = {}
            self._pending_rows = {}
            self._error_counts = {}
            if self._qos is not None:
                self._qos = PoolQoS(
                    {k: v for k, v in (
                        ("rate_eps", self._qos.default_rate),
                        ("rate_burst", self._qos.default_burst),
                        ("weight", self._qos.default_weight),
                        ("priority", self._qos.default_priority),
                        ("breaker_failures", self._qos.breaker_failures),
                        ("breaker_reset_ms", self._qos.breaker_reset_ms),
                        ("max_defer", self._qos.max_defer))
                        if v is not None},
                    on_transition=self._on_breaker_transition)
            used = set()
            for tid, entry in payload["tenants"].items():
                slot = int(entry["slot"])
                used.add(slot)
                self._tenants[tid] = slot
                self._bindings[tid] = dict(entry.get("bindings") or {})
                self._tenant_qos_raw[tid] = dict(entry.get("qos") or {})
                self._pending[tid] = self._fresh_queues()
                self._pending_rows[tid] = 0
                self._error_counts[tid] = 0
                if self._qos is not None:
                    self._qos.add_tenant(tid, self._tenant_qos_raw[tid])
            # restore is a whole-pool rebuild: in-flight migrations die
            # with the old slot map (their parked rows were never acked
            # past the snapshot), lost-device marks survive — the
            # hardware did not come back because we restored
            self._migrations = {}
            self._lost_tenants = {}
            self._free = [s for s in range(self.slots - 1, -1, -1)
                          if s not in used
                          and self._device_of_slot(s)
                          not in self._lost_devices]
            self._recompute_placement_locked()
            self._recovery = {
                "restored_wall": time.time(),
                "revision": None,       # restore_revision fills it
                "tenants": len(self._tenants),
                "replayed": 0,
            }

    def restore_revision(self, revision: str) -> None:
        store = self.proto._persistence_store()
        data = store.load(self.name, revision)
        if data is None:
            raise KeyError(f"no revision '{revision}' for pool "
                           f"'{self.name}'")
        self.restore(data)
        with self._lock:
            self._recovery["revision"] = revision

    def replay_errors(self, tenant_id: Optional[str] = None) -> dict:
        """Drain per-tenant ``<pool>/tenant/<id>`` error partitions and
        re-deliver through the owning slot's callbacks in
        ORIGINAL-TIMESTAMP order (the PR 9 replay contract: the store
        interleaves rounds out of event-time order, and a replay in
        store order would re-introduce the disorder). Consecutive
        same-origin runs re-deliver as one batch; deliveries go through
        the tenant's circuit breaker, so replaying against a still-OPEN
        breaker lands the events straight back in the partition
        (at-least-once, nothing lost). A tenant with no callbacks keeps
        its backlog. Returns {tenant: events_replayed}."""
        store = self.proto._error_store()
        with self._lock:
            tids = list(self._tenants) if tenant_id is None \
                else [tenant_id]
            if tenant_id is not None:
                self._slot(tenant_id)
            cbs_of = {tid: list(self._callbacks.get(tid, ()))
                      for tid in tids}
        replayed: dict[str, int] = {}
        for tid in tids:
            part = self.tenant_partition(tid)
            records = store.drain(part)
            if not records:
                continue
            if not cbs_of[tid]:
                for rec in records:     # nowhere to deliver — keep
                    store.store(part, rec)
                continue
            entries = []
            seq = 0
            for rec in records:
                for e in rec.to_events():
                    entries.append((e.timestamp, seq, rec.origin, e))
                    seq += 1
            entries.sort(key=lambda t: (t[0], t[1]))
            n = 0
            batch_origin, batch = None, []
            for _ts, _s, origin, e in entries:
                if origin != batch_origin and batch:
                    self._deliver_tenant(tid, cbs_of[tid],
                                         [(batch_origin, batch)])
                    batch = []
                batch_origin = origin
                batch.append(e)
                n += 1
            if batch:
                self._deliver_tenant(tid, cbs_of[tid],
                                     [(batch_origin, batch)])
            replayed[tid] = n
            log.info("pool '%s': replayed %d event(s) for tenant '%s' "
                     "in original-timestamp order", self.name, n, tid)
        return replayed

    # -- observability ----------------------------------------------------

    def statistics(self) -> dict:
        return self._collect_observability()[1]

    def slo_report(self) -> dict:
        """The SLO/burn-rate view on its own (``GET /siddhi/slo``):
        per-scope latency percentiles, attainment, burn rates, states,
        plus the pool's saturation signals."""
        return self.slo_engine.evaluate(saturation=self.saturation())

    def explain(self, live: bool = True) -> dict:
        """Plan explain for the pool (obs/explain.py): the TEMPLATE
        explains once — its ``plan_hash`` covers the prototype's graph
        and the pool's configured decisions (query order, batch_max,
        admission caps, SLO objective, mesh placement rules) and is
        shared by every pool of the same template in the same
        environment. Slot-axis facts (current slots, active tenants,
        rounds) ride the ``live`` section, never the hash — the slot
        axis grows by doubling with churn."""
        from ..obs.explain import ExplainReport
        with self._lock:
            return ExplainReport.from_pool(self, live=live).as_dict()

    def plan_hash(self) -> str:
        """Stable content hash of the template plan (decisions + graph
        only) — stamped into flight-recorder artifacts."""
        from ..obs.explain import ExplainReport
        return ExplainReport.from_pool(self, live=False).plan_hash

    def _collect_sharded_locked(self) -> dict:
        """Mesh pools collect with ONE read PER DEVICE: each device's
        addressable shard of the stacked emitted counters is fetched
        directly (no cross-device gather ever materializes on the
        mesh), timed per device for the ``collect_ms{device=}`` gauge
        family. Caller holds the pool lock."""
        dev_pos = {d.id: i for i, d in
                   enumerate(self.mesh.devices.flat)}
        times = [0.0] * self.n_devices
        emitted = {}
        for qn in self._order:
            arr = self._emitted[qn]
            out = np.zeros(arr.shape, np.int64)
            for sh in arr.addressable_shards:   # one read per device
                t0 = time.perf_counter()
                data = np.asarray(sh.data)
                times[dev_pos.get(sh.device.id, 0)] += \
                    time.perf_counter() - t0
                out[sh.index] = data
            emitted[qn] = out
        self._collect_ms_per_device = [round(t * 1000.0, 3)
                                       for t in times]
        return {"emitted": emitted}

    def _collect_observability(self) -> tuple[dict, dict]:
        """ONE walk shared by statistics() and the registry collector.
        Device reads are O(templates), not O(tenants): the stacked
        emitted counters come back in a single device_get per pool
        (per DEVICE on a mesh — `_collect_sharded_locked`); the
        per-tenant fan-out below is pure host-side numpy indexing (the
        SLO windows are host-side too — tracking ON adds zero device
        reads here; tests/test_slo.py monkeypatch-counts this)."""
        with self._lock:
            if self.mesh is not None:
                host = self._collect_sharded_locked()
            else:
                host = jax.device_get({"emitted": self._emitted})
            tenants = dict(self._tenants)
            pending = dict(self._pending_rows)
            errors = dict(self._error_counts)
            pool_stats = {
                "slots": self.slots, "active": len(tenants),
                "max_tenants": self.max_tenants,
                "batch_max": self.batch_max,
                "rounds": self._rounds, "dispatches": self._dispatches,
                "grows": self._grows,
                "state_bytes_per_tenant": self.state_bytes_per_tenant,
                "state_bytes_by_class":
                    dict(self.state_bytes_by_class),
            }
            ist = self._ingest_stats
            packed_ingest = {
                "enabled": self._packed_on,
                "transfers_per_round":
                    round(ist["transfers"] / ist["rounds"], 3)
                    if ist["rounds"] else 0.0,
                "rows_packed": ist["rows"],
                "pad_frac":
                    round(1.0 - ist["rows"] / ist["cells"], 4)
                    if ist["cells"] else 0.0,
                "bytes": ist["bytes"],
                "rounds": ist["rounds"],
                "streams": len(self._ingest_streams),
            }
            saturation = self._saturation_locked()
            qos_rep = None
            if self._qos is not None:
                qos_rep = self._qos.report()
                qos_rep["throttled_429s"] = \
                    self._rejections.get("rate-limited", 0)
            sup = self._checkpoint_supervisor
            recovery = None
            if sup is not None or self._recovery is not None:
                # recovery age is the operator's "how stale could a
                # crash make me" number (docs/resilience.md)
                wall = time.time()
                recovery = {}
                if sup is not None:
                    recovery.update({
                        "checkpoints": sup.checkpoints,
                        "checkpoint_failures": sup.failures,
                        "last_revision": sup.last_revision,
                        "checkpoint_age_ms":
                            round((wall - sup.last_checkpoint_wall)
                                  * 1000.0, 1)
                            if sup.last_checkpoint_wall else None,
                    })
                if self._recovery is not None:
                    recovery.update({
                        "restored_revision": self._recovery.get("revision"),
                        "restored_tenants": self._recovery.get("tenants"),
                        "replayed": self._recovery.get("replayed"),
                        "recovery_age_ms":
                            round((wall - self._recovery["restored_wall"])
                                  * 1000.0, 1),
                    })
            mesh_info = None
            if self.mesh is not None:
                loads = list(self._placement_counts)
                wall = time.time()
                mesh_info = {
                    "axis": self.mesh_axis,
                    "n_devices": self.n_devices,
                    "slots_per_device": self.slots_per_device,
                    "lost_devices": sorted(self._lost_devices),
                    "lost_tenants": sorted(self._lost_tenants),
                    "evacuations": self._evacuations,
                    "evacuation_age_ms":
                        round((wall - self._last_evacuation_wall)
                              * 1000.0, 1)
                        if self._last_evacuation_wall else None,
                    "migrations": self._migrations_done,
                    "migrations_in_flight": len(self._migrations),
                    "rows_migrated": self._rows_migrated,
                    "migration_pause_ms_last":
                        self._migration_pause_ms_last,
                    "per_device": {
                        str(d): {
                            "slots_placed": loads[d],
                            "slot_budget": self._slot_budget,
                            "lost": d in self._lost_devices,
                            "rows_ingested": self._rows_per_device[d],
                            "collect_ms":
                                self._collect_ms_per_device[d],
                        } for d in range(self.n_devices)},
                }
        p = f"siddhi.{self.name}"
        flat: dict = {}
        report: dict = {"pool": pool_stats, "tenants": {}}
        emitted = host["emitted"]
        # per-tenant gauges: ONE metric family per measure with a
        # `tenant` (and `query`) label — scrapers see a labeled series
        # family, registry dumps keep the readable dotted name
        # (docs/observability.md "label conventions")
        fams = {key: f"{p}.tenant.{key}" for key in
                ("emitted", "pending", "errors")}
        qfam = f"{p}.tenant.query.emitted"
        keep: dict[str, set] = {f: set() for f in fams.values()}
        keep[qfam] = set()
        for tid, slot in tenants.items():
            per_q = {qn: int(emitted[qn][slot]) for qn in self._order}
            entry = {"slot": slot, "emitted": per_q,
                     "pending": pending.get(tid, 0),
                     "errors": errors.get(tid, 0)}
            if qos_rep is not None and tid in qos_rep["tenants"]:
                q = qos_rep["tenants"][tid]
                entry["qos"] = {
                    "weight": q["weight"], "priority": q["priority"],
                    "breaker": q.get("breaker", {}).get("state"),
                }
            report["tenants"][tid] = entry
            base = f"{p}.tenant.{tid}"
            for key, value in (("emitted", sum(per_q.values())),
                               ("pending", entry["pending"]),
                               ("errors", entry["errors"])):
                dotted = f"{base}.{key}"
                self.metrics.labeled_gauge(
                    fams[key], {"tenant": tid}, dotted=dotted,
                    help=_TENANT_HELP[key]).set(value)
                keep[fams[key]].add(dotted)
            for qn, v in per_q.items():
                dotted = f"{base}.query.{qn}.emitted"
                self.metrics.labeled_gauge(
                    qfam, {"tenant": tid, "query": qn},
                    dotted=dotted,
                    help="events emitted by one tenant's query").set(v)
                keep[qfam].add(dotted)
        for fam, dotted in keep.items():
            # departed tenants must not linger in scrapes
            self.metrics.prune_family(fam, dotted)
        for k, v in pool_stats.items():
            if isinstance(v, dict):
                for kk, vv in v.items():
                    flat[f"{p}.pool.{k}.{kk}"] = vv
            else:
                flat[f"{p}.pool.{k}"] = v
        # packed pool ingest (docs/performance.md "Packed pool
        # ingest"): one transfer per ingest stream per round is the
        # acceptance invariant — transfers_per_round tracks it, and
        # pad_frac shows how much of each (slots, cap) round was
        # padding (bench.py tenants arms export the same block)
        report["packed_ingest"] = packed_ingest
        for k in ("transfers_per_round", "rows_packed", "pad_frac"):
            flat[f"{p}.ingest.{k}"] = packed_ingest[k]
        if mesh_info is not None:
            # per-device labeled gauge FAMILIES (`device=` label — the
            # cardinality-safe shape, docs/observability.md): slots
            # placed, rows ingested, per-device collection read time
            report["mesh"] = mesh_info
            flat[f"{p}.mesh.n_devices"] = mesh_info["n_devices"]
            flat[f"{p}.mesh.slots_per_device"] = \
                mesh_info["slots_per_device"]
            fam_help = {
                "slots_placed": "tenants placed on one mesh device",
                "rows_ingested": "rows dispatched to one mesh device",
                "collect_ms": "stats shard-read time for one device",
            }
            for d, entry in mesh_info["per_device"].items():
                for key in ("slots_placed", "rows_ingested",
                            "collect_ms"):
                    self.metrics.labeled_gauge(
                        f"{p}.mesh.{key}", {"device": d},
                        dotted=f"{p}.mesh.device.{d}.{key}",
                        help=fam_help[key]).set(entry[key])
                self.metrics.labeled_gauge(
                    f"{p}.mesh.device_lost", {"device": d},
                    dotted=f"{p}.mesh.device.{d}.lost",
                    help="1 when this mesh device is marked lost"
                ).set(int(entry["lost"]))
            # migration.* / evacuation.* gauge families
            # (docs/observability.md): live-move and device-loss
            # counters for the rebalance/evacuation loops
            flat[f"{p}.migration.count"] = mesh_info["migrations"]
            flat[f"{p}.migration.in_flight"] = \
                mesh_info["migrations_in_flight"]
            flat[f"{p}.migration.rows_moved"] = \
                mesh_info["rows_migrated"]
            if mesh_info["migration_pause_ms_last"] is not None:
                flat[f"{p}.migration.pause_ms_last"] = \
                    mesh_info["migration_pause_ms_last"]
            flat[f"{p}.evacuation.count"] = mesh_info["evacuations"]
            flat[f"{p}.evacuation.lost_devices"] = \
                len(mesh_info["lost_devices"])
            flat[f"{p}.evacuation.lost_tenants"] = \
                len(mesh_info["lost_tenants"])
            if mesh_info["evacuation_age_ms"] is not None:
                flat[f"{p}.evacuation.age_ms"] = \
                    mesh_info["evacuation_age_ms"]
        # SLO + saturation (obs/slo.py): host-side windows, labeled
        # p99/burn/state families, machine-readable pressure signals
        report["slo"] = self.slo_engine.evaluate(saturation=saturation)
        self.slo_engine.publish(self.metrics, f"{p}.slo")
        for k in ("pending_rows", "queue_age_ms_max", "drain_lag_ms",
                  "rejections_last_60s"):
            v = saturation.get(k)
            if isinstance(v, (int, float)):
                flat[f"{p}.saturation.{k}"] = v
        for cause, n in saturation["rejections"].items():
            self.metrics.labeled_gauge(
                f"{p}.saturation.rejections", {"cause": cause},
                dotted=f"{p}.saturation.rejections.{cause}",
                help="admission rejections by saturation cause").set(n)
        # QoS: DRR credits + breaker state per tenant as labeled gauge
        # families (the cardinality-safe shape), plus the scheduler /
        # breaker / throttle counters (docs/serving.md "QoS dials")
        report["qos"] = qos_rep if qos_rep is not None \
            else {"enabled": False}
        if qos_rep is not None:
            cred_fam = f"{p}.qos.credits"
            brk_fam = f"{p}.qos.breaker_state"
            keep_cred: set = set()
            keep_brk: set = set()
            state_num = {"CLOSED": 0, "HALF_OPEN": 1, "OPEN": 2}
            for tid, q in qos_rep["tenants"].items():
                dotted = f"{p}.qos.tenant.{tid}.credits"
                self.metrics.labeled_gauge(
                    cred_fam, {"tenant": tid}, dotted=dotted,
                    help="unspent DRR scheduler credits for one tenant"
                ).set(q["credits"])
                keep_cred.add(dotted)
                br = q.get("breaker")
                if br is not None:
                    dotted = f"{p}.qos.tenant.{tid}.breaker_state"
                    self.metrics.labeled_gauge(
                        brk_fam, {"tenant": tid}, dotted=dotted,
                        help="circuit-breaker state for one tenant "
                             "(0 closed, 1 half-open, 2 open)"
                    ).set(state_num.get(br["state"], -1))
                    keep_brk.add(dotted)
            self.metrics.prune_family(cred_fam, keep_cred)
            self.metrics.prune_family(brk_fam, keep_brk)
            flat[f"{p}.qos.throttled_429s"] = qos_rep["throttled_429s"]
            flat[f"{p}.qos.short_circuited"] = \
                qos_rep["short_circuited"]
        if recovery is not None:
            report["recovery"] = recovery
            for k in ("checkpoints", "checkpoint_failures",
                      "checkpoint_age_ms", "recovery_age_ms",
                      "replayed"):
                v = recovery.get(k)
                if isinstance(v, (int, float)):
                    flat[f"{p}.recovery.{k}"] = v
        comp = dict(self.proto.compile_service.summary())
        # ONE compiled program set per template, shared by every tenant
        # — the multi-tenant acceptance invariant (bench.py `tenants`)
        comp["program_sets"] = 1
        report["compile"] = comp
        for k in ("warmups", "programs", "compile_ms", "cache_hits",
                  "cache_misses", "program_sets"):
            flat[f"{p}.pool.compile.{k}"] = comp.get(k, 0)
        flat[f"{p}.pool.ready"] = int(self.ready)
        return flat, report
