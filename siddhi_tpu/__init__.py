"""siddhi_tpu — a TPU-native streaming / Complex Event Processing framework.

A from-scratch re-design of the capabilities of the reference Siddhi engine
(see SURVEY.md): SiddhiQL over unbounded event streams executed as columnar
micro-batches through pure, jitted (state, batch) -> (state', out) step
functions on TPU.
"""
import jax

# Java long/double semantics (bit-parity with the reference) require 64-bit
# types; must be set before any array is created.
jax.config.update("jax_enable_x64", True)

from .core.manager import SiddhiManager  # noqa: E402
from .core.persistence import (  # noqa: E402
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
    PersistenceStore,
)
from .core.stream import Event, QueryCallback, StreamCallback  # noqa: E402
from .core.types import AttrType  # noqa: E402
from .lang import parser as compiler  # noqa: E402
from .lang.parser import (  # noqa: E402
    parse,
    parse_expression,
    parse_on_demand_query,
    parse_query,
)

__all__ = [
    "AttrType",
    "Event",
    "FileSystemPersistenceStore",
    "InMemoryPersistenceStore",
    "PersistenceStore",
    "QueryCallback",
    "SiddhiManager",
    "StreamCallback",
    "compiler",
    "parse",
    "parse_expression",
    "parse_on_demand_query",
    "parse_query",
]

__version__ = "0.1.0"
