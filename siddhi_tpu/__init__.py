"""siddhi_tpu — a TPU-native streaming / Complex Event Processing framework.

A from-scratch re-design of the capabilities of the reference Siddhi engine
(see SURVEY.md): SiddhiQL over unbounded event streams executed as columnar
micro-batches through pure, jitted (state, batch) -> (state', out) step
functions on TPU.
"""
import os

import jax

# Java long/double semantics (bit-parity with the reference) require 64-bit
# types; must be set before any array is created.
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: TPU first-compiles of window/NFA steps
# run 20-60 s; caching makes every later process start in ~2 s (measured).
# Opt out with SIDDHI_TPU_NO_CACHE=1 or point elsewhere with
# SIDDHI_TPU_CACHE_DIR (default: ./.jax_cache, shared with bench.py).
# Every compile persists (min compile time / entry size 0): warm starts
# must hit for the small CPU-compiled steps too, not just the minute-long
# TPU ones — see docs/compile_cache.md for the cache-key stability rules
# that keep the entries reusable across processes.
if not os.environ.get("SIDDHI_TPU_NO_CACHE"):
    _cache = os.environ.get(
        "SIDDHI_TPU_CACHE_DIR",
        os.path.join(os.path.abspath(os.curdir), ".jax_cache"))
    try:
        os.makedirs(_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — cache is best-effort
        pass

from .core.manager import SiddhiManager  # noqa: E402
from .core.persistence import (  # noqa: E402
    FileSystemPersistenceStore,
    InMemoryPersistenceStore,
    PersistenceStore,
)
from .core.stream import Event, QueryCallback, StreamCallback  # noqa: E402
from .core.types import AttrType  # noqa: E402
from .lang import parser as compiler  # noqa: E402
from .obs.explain import ExplainReport, explain_diff  # noqa: E402
from .lang.parser import (  # noqa: E402
    parse,
    parse_expression,
    parse_on_demand_query,
    parse_query,
)
from .resilience import (  # noqa: E402
    CheckpointSupervisor,
    ErroredEvent,
    ErrorStore,
    FaultInjector,
    FileSystemErrorStore,
    InMemoryErrorStore,
    PoolCheckpointSupervisor,
)
from .serving import (  # noqa: E402
    AdmissionError,
    Template,
    TemplateRegistry,
    TenantPool,
)

__all__ = [
    "AdmissionError",
    "AttrType",
    "ExplainReport",
    "explain_diff",
    "CheckpointSupervisor",
    "ErrorStore",
    "ErroredEvent",
    "Event",
    "FaultInjector",
    "FileSystemErrorStore",
    "FileSystemPersistenceStore",
    "InMemoryErrorStore",
    "InMemoryPersistenceStore",
    "PersistenceStore",
    "PoolCheckpointSupervisor",
    "QueryCallback",
    "SiddhiManager",
    "StreamCallback",
    "Template",
    "TemplateRegistry",
    "TenantPool",
    "compiler",
    "parse",
    "parse_expression",
    "parse_on_demand_query",
    "parse_query",
]

__version__ = "0.1.0"
