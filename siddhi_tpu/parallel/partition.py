"""Partitioned query execution: `partition with (attr of S) begin ... end`.

Reference mapping:
- PartitionRuntimeImpl (partition/PartitionRuntimeImpl.java:75) — one
  runtime per partition block                      -> PartitionBlockRuntime
- PartitionStreamReceiver (partition/PartitionStreamReceiver.java:82-146)
  — computes the key per event and routes it to a lazily-created per-key
  clone of every inner query                        -> the key->slot device
  hash table + a vmap over the slot axis
- ValuePartitionExecutor / RangePartitionExecutor
  (partition/executor/*.java)                       -> compiled key/range
  expressions evaluated over the whole batch at once
- PartitionStateHolder (util/snapshot/state/PartitionStateHolder.java:33)
  — per-key State maps                              -> operator states with
  a leading [K] slot axis

TPU-first design. The reference lazily clones the entire query runtime per
distinct key and routes each event through its key's clone — pointer-chasing
over an unbounded HashMap. Here the block compiles to ONE jitted step:

  1. the partition key of every event in the batch is computed in one
     vectorized expression pass;
  2. keys claim stable slots in a bounded open-addressing device hash table
     (ops/keyed.py) — first-seen assignment, overflow counted, never silent;
  3. the whole inner query chain runs under `jax.vmap` over the [K] slot
     axis: slot k sees the batch masked to its own events (plus TIMER
     rows, which every slot observes — each clone has its own scheduler in
     the reference too), so every existing operator works unchanged;
  4. per-query outputs [K, B] are flattened, ts-sorted, and compacted to
     one output batch; inner-stream (`#stream`) outputs never leave the
     vmap — they chain to consuming queries inside the same XLA program,
     keeping the key axis intact (the reference's per-key `#inner`
     junctions collapse into dataflow inside one step).

Multi-chip: the [K] slot axis is the sharding axis. When the app is built
with a `partition_mesh`, the stacked states are placed with a
NamedSharding over the mesh's first axis and XLA partitions the vmap
across devices — each device owns K/n key slots and masks the (replicated)
ingest batch down to the keys it owns. This is the all-gather + key-hash
ownership routing of `__graft_entry__.dryrun_multichip`, expressed through
GSPMD instead of hand-written collectives.

Bounded-state contract: at most K distinct keys are live; rows whose key
cannot claim a slot are dropped AND counted (`overflow`), mirroring the
framework-wide "counted, never silent" rule. Output compaction beyond the
per-trigger capacity is likewise counted (`lost`).

Ordering note: outputs are sorted by timestamp; rows with EQUAL timestamps
order by (slot, emission) rather than strict arrival interleaving across
keys (the reference interleaves per arrival). Within one key the order is
exact.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.event import (CURRENT, EventBatch, StreamSchema, TIMER,
                          rows_from_batch)
from ..core.stream import Event, Receiver
from ..obs.tracing import maybe_span
from ..ops.expr import CompiledExpr, env_from_batch
from ..ops.keyed import hash_columns, lookup_or_insert
from ..ops.windows import POS_INF, WindowOp

from ..ops.sentinels import NO_SLOT

# combined-output compaction bound: several key slots can emit in the same
# step (e.g. a timer flushing every slot's timeBatch window), so the cap
# scales with K instead of a single slot's capacity; beyond it rows are
# dropped AND counted in `lost`
OUT_COMPACT_CAP = 65536


class BlockQueryPlan:
    """One query inside a partition block, compiled to an operator chain."""

    def __init__(self, name: str, input_id: str, in_schema: StreamSchema,
                 operators: list, target: str, inner_target: bool,
                 out_type: str):
        self.name = name
        self.input_id = input_id          # '#I' for inner streams
        self.in_schema = in_schema
        self.operators = operators
        self.target = target              # '#I' when inner_target
        self.inner_target = inner_target
        self.out_type = out_type

    @property
    def out_schema(self) -> StreamSchema:
        return self.operators[-1].out_schema

    def init_state(self):
        return tuple(op.init_state() for op in self.operators)

    def has_timers(self) -> bool:
        return any(isinstance(op, WindowOp) and
                   op.next_due(op.init_state()) is not None
                   for op in self.operators)


class BlockPatternPlan:
    """A pattern/sequence query inside a partition block: the NFA pending
    table gains a leading [K] slot axis under the block's vmap — each key
    instance owns an independent pending table (the reference clones
    whole query runtimes per key: PartitionRuntimeImpl.java:75,
    PartitionStreamReceiver.java:82-146)."""

    is_pattern = True

    def __init__(self, name: str, engine, sel_ops: list,
                 input_ids: set, in_schema: StreamSchema, target: str,
                 inner_target: bool, out_type: str):
        self.name = name
        self.engine = engine
        self.sel_ops = sel_ops
        self.input_ids = input_ids        # outer stream ids consumed
        self.input_id = next(iter(sorted(input_ids)))
        self.in_schema = in_schema
        self.operators = sel_ops          # for sort-heavy/overflow scans
        self.target = target
        self.inner_target = inner_target
        self.out_type = out_type

    @property
    def out_schema(self) -> StreamSchema:
        return self.sel_ops[-1].out_schema if self.sel_ops \
            else self.engine.match_schema

    def init_state(self):
        return (self.engine.init_state(),
                tuple(op.init_state() for op in self.sel_ops))

    def has_timers(self) -> bool:
        return self.engine.has_absent


class PartitionQueryPort:
    """Output surface of one partitioned query: handlers + callbacks
    (what `app.queries[name]` exposes for queries inside a partition)."""

    def __init__(self, block: "PartitionBlockRuntime", name: str,
                 out_schema: StreamSchema):
        from ..core.runtime import QueryCallbackHandler
        self.block = block
        self.name = name
        self.out_schema = out_schema
        self.output_handlers: list = []
        self.callback_handler = QueryCallbackHandler()
        self.batch_callbacks: list[Callable] = []
        self.rate_limiter = None

    def set_rate_limiter(self, rl) -> None:
        rl.emit = self._emit_limited
        rl.start(self.block.app)
        self.rate_limiter = rl

    def _emit_limited(self, timestamp: int, rows) -> None:
        for h in self.output_handlers:
            h.handle(timestamp, rows)
        self.callback_handler.handle(timestamp, rows)

    def stats(self) -> dict:
        return {"emitted": int(jax.device_get(
                    self.block._emitted[self.name])),
                "overflow": self.block.overflow_total()}

    def overflow_total(self) -> int:
        return self.block.overflow_total()


class BlockStreamReceiver(Receiver):
    """Junction subscriber feeding one outer stream into the block
    (= PartitionStreamReceiver)."""

    supports_packed = False

    def __init__(self, block: "PartitionBlockRuntime", stream_id: str):
        self.block = block
        self.stream_id = stream_id

    @property
    def max_step_capacity(self):
        return self.block.max_step_capacity

    def receive(self, events):
        self.block.process_stream_events(self.stream_id, events)

    def process_batch(self, batch, last_ts):
        self.block.process_stream_batch(self.stream_id, batch, last_ts)


def _tree_overflow_sum(tree) -> int:
    """Sum every 'overflow' entry in a host pytree of dicts/tuples."""
    total = 0
    if isinstance(tree, dict):
        for k, v in tree.items():
            if k == "overflow":
                total += int(np.sum(np.asarray(v)))
            else:
                total += _tree_overflow_sum(v)
    elif isinstance(tree, (tuple, list)):
        for v in tree:
            total += _tree_overflow_sum(v)
    return total


def _flatten_compact(out: EventBatch, out_cap: int):
    """[K, B] per-slot outputs -> one ts-sorted [out_cap] batch + lost
    count. Stable sort: equal timestamps keep (slot, row) order."""
    K, B = out.valid.shape

    def flat(x):
        return x.reshape((K * B,) + x.shape[2:])

    valid = flat(out.valid)
    ts = flat(out.ts)
    key = jnp.where(valid, ts, jnp.int64(2 ** 62))
    order = jnp.argsort(key, stable=True)[:out_cap]
    picked = EventBatch(
        ts=ts[order],
        cols=tuple(flat(c)[order] for c in out.cols),
        nulls=tuple(flat(nl)[order] for nl in out.nulls),
        kind=flat(out.kind)[order],
        valid=valid[order],
    )
    lost = (jnp.sum(valid.astype(jnp.int64)) -
            jnp.sum(picked.valid.astype(jnp.int64)))
    return picked, lost


def _concat_batches(a: EventBatch, b: EventBatch) -> EventBatch:
    return EventBatch(
        ts=jnp.concatenate([a.ts, b.ts]),
        cols=tuple(jnp.concatenate([x, y])
                   for x, y in zip(a.cols, b.cols)),
        nulls=tuple(jnp.concatenate([x, y])
                    for x, y in zip(a.nulls, b.nulls)),
        kind=jnp.concatenate([a.kind, b.kind]),
        valid=jnp.concatenate([a.valid, b.valid]),
    )


def _as_current(b: EventBatch) -> EventBatch:
    """EXPIRED rows become CURRENT when inserted into a stream
    (InsertIntoStreamCallback.java:52-55)."""
    return EventBatch(b.ts, b.cols, b.nulls,
                      jnp.where(b.valid, jnp.int32(CURRENT), b.kind),
                      b.valid)


class PartitionBlockRuntime:
    """All queries of one `partition ... begin ... end` block, executed as
    one jitted, slot-vmapped step per triggering input."""

    def __init__(self, app, name: str, n_slots: int,
                 key_specs: dict, plans: list[BlockQueryPlan],
                 mesh=None):
        self.app = app
        self.name = name
        self.K = int(n_slots)
        # key_specs: stream_id -> ("value", CompiledExpr)
        #                        | ("range", [CompiledExpr, ...]) (slot=index)
        self.key_specs = key_specs
        self.plans = plans
        self.mesh = mesh
        self.slot_tbl = {
            "keys": jnp.zeros((self.K,), jnp.int64),
            "used": jnp.zeros((self.K,), jnp.bool_),
            "overflow": jnp.int64(0),
        }
        self.qstates = {p.name: self._stack_state(p.init_state())
                        for p in plans}
        self._emitted = {p.name: jnp.int64(0) for p in plans}
        self._lost = {p.name: jnp.int64(0) for p in plans}
        self.ports = {p.name: PartitionQueryPort(self, p.name, p.out_schema)
                      for p in plans}
        self._steps: dict = {}
        self._lock = threading.Lock()
        self._sched_due: dict[str, Optional[int]] = {p.name: None
                                                     for p in plans}
        self._has_timers = {p.name: p.has_timers() for p in plans}
        # the slot-vmap multiplies every per-step sort by K — cap harder
        # (see runtime.py SORT_HEAVY_CAP)
        from ..core.runtime import PARTITION_SORT_HEAVY_CAP \
            as SORT_HEAVY_CAP
        self.max_step_capacity = SORT_HEAVY_CAP if any(
            getattr(op, "sort_heavy", False)
            for p in plans for op in p.operators) else None
        if mesh is not None:
            from . import sharding as _sharding
            _sharding.check_divisible(self.K, mesh,
                                      f"partition '{name}' slots")
            self._apply_mesh_sharding()

    # -- state layout -----------------------------------------------------
    def _stack_state(self, state):
        K = self.K
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x)[None], (K,) + jnp.asarray(x).shape
            ) + jnp.zeros((K,) + (1,) * jnp.asarray(x).ndim,
                          dtype=jnp.asarray(x).dtype),
            state)

    def _apply_mesh_sharding(self, qstates=None, slot_tbl=None):
        """Place the block state per the regex rule table
        (parallel/sharding.py PARTITION_STATE_RULES): [K]-leading
        qstates shard over the mesh's first axis — XLA then partitions
        the slot-vmap across devices (each device owns K/n key slots —
        GSPMD routing, see module docstring) — and the key-slot table
        replicates. Accepts HOST pytrees (restore): a sharded
        `device_put` of a numpy leaf is ONE placement that never
        aliases the payload, so restore re-places shards directly
        instead of a fresh-copy-then-re-place double transfer
        (`shard_pytree` also skips already-placed leaves — redundant
        calls transfer nothing; tests/test_mesh.py counts both)."""
        from . import sharding
        placed = sharding.shard_pytree(
            {"qstates": qstates if qstates is not None else self.qstates,
             "slot_tbl": slot_tbl if slot_tbl is not None
             else self.slot_tbl},
            self.mesh, sharding.PARTITION_STATE_RULES)
        self.qstates = placed["qstates"]
        self.slot_tbl = placed["slot_tbl"]

    # -- key computation --------------------------------------------------
    def _slots_for(self, spec, batch: EventBatch, now, slot_tbl):
        kind = spec[0]
        if kind == "value":
            cexpr: CompiledExpr = spec[1]
            env = env_from_batch(batch)
            env["__now__"] = now
            c = cexpr.fn(env)
            codes = hash_columns([c.values], [c.nulls])
            active = batch.valid & (batch.kind != TIMER)
            slots, keys, used, ovf = lookup_or_insert(
                slot_tbl["keys"], slot_tbl["used"], codes, active)
            slot_tbl = {"keys": keys, "used": used,
                        "overflow": slot_tbl["overflow"] + ovf}
            return slots, slot_tbl
        # range partition: slot = label's slot for the first matching range
        # condition (labels shared across streams identify the instance);
        # events matching no range are dropped (RangePartitionExecutor
        # returns null -> no instance)
        conds = spec[1]  # [(CompiledExpr, slot_index), ...]
        env = env_from_batch(batch)
        env["__now__"] = now
        B = batch.valid.shape[0]
        slots = jnp.full((B,), NO_SLOT, dtype=jnp.int32)
        for cexpr, si in conds:
            c = cexpr.fn(env)
            hit = c.values & ~c.nulls & (slots == NO_SLOT)
            slots = jnp.where(hit, jnp.int32(si), slots)
        return slots, slot_tbl

    # -- step compilation -------------------------------------------------
    def _step_for(self, trigger: tuple, capacity: int):
        fn = self._steps.get((trigger, capacity))
        if fn is None:
            fn = jax.jit(self._make_step(trigger))
            self._steps[(trigger, capacity)] = fn
        return fn

    def _make_step(self, trigger: tuple):
        kind, tid = trigger
        plans = self.plans
        K = self.K
        key_specs = self.key_specs

        # pattern plans: the engine's per-stream/timer step fns are
        # trigger-specific — built once per compiled step
        nfa_steps = {}
        for p in plans:
            if getattr(p, "is_pattern", False):
                if kind == "stream" and tid in p.input_ids:
                    nfa_steps[p.name] = p.engine.make_stream_step(tid)
                elif kind == "timer" and p.name == tid:
                    nfa_steps[p.name] = p.engine.make_timer_step()

        def step(slot_tbl, qstates, emitted, lost, batch, now):
            if kind == "stream":
                slots, slot_tbl = self._slots_for(
                    key_specs[tid], batch, now, slot_tbl)
            else:
                slots = None  # TIMER trigger: every slot observes it
            is_timer_row = batch.kind == TIMER

            def run_block(per_slot, k):
                inner_k: dict = {}
                outs_k: dict = {}
                dues_k: dict = {}
                new_k: dict = {}
                for p in plans:
                    if getattr(p, "is_pattern", False):
                        nstep = nfa_steps.get(p.name)
                        if nstep is None:
                            new_k[p.name] = per_slot[p.name]
                            continue
                        nfa_state, sel_states = per_slot[p.name]
                        if kind == "timer":
                            nfa_state, b = nstep(nfa_state, now)
                        else:
                            bk = batch.mask((slots == k) | is_timer_row)
                            nfa_state, b = nstep(nfa_state, bk, now)
                        sts = []
                        for op, st in zip(p.sel_ops, sel_states):
                            st, b = op.step(st, b, now)
                            sts.append(st)
                        new_k[p.name] = (nfa_state, tuple(sts))
                        if p.engine.has_absent:
                            dues_k[p.name] = p.engine.next_due(nfa_state)
                        outs_k[p.name] = b
                        continue
                    if kind == "timer" and p.name == tid:
                        b = batch
                    elif kind == "stream" and p.input_id == tid:
                        b = batch.mask((slots == k) | is_timer_row)
                    elif p.input_id in inner_k:
                        b = inner_k[p.input_id]
                    else:
                        new_k[p.name] = per_slot[p.name]
                        continue
                    sts = []
                    for op, st in zip(p.operators, per_slot[p.name]):
                        st, b = op.step(st, b, now)
                        sts.append(st)
                    new_k[p.name] = tuple(sts)
                    ds = [op.next_due(s) for op, s in
                          zip(p.operators, sts) if isinstance(op, WindowOp)]
                    ds = [d for d in ds if d is not None]
                    if ds:
                        due = ds[0]
                        for d in ds[1:]:
                            due = jnp.minimum(due, d)
                        dues_k[p.name] = due
                    if p.inner_target:
                        cur = _as_current(b)
                        if p.target in inner_k:
                            inner_k[p.target] = _concat_batches(
                                inner_k[p.target], cur)
                        else:
                            inner_k[p.target] = cur
                    else:
                        outs_k[p.name] = b
                return new_k, outs_k, dues_k

            ks = jnp.arange(K, dtype=jnp.int32)
            new_states, outs, dues = jax.vmap(run_block)(qstates, ks)
            flat_outs = {}
            for qn, ob in outs.items():
                out_cap = min(K * ob.valid.shape[1], OUT_COMPACT_CAP)
                flat, l = _flatten_compact(ob, out_cap)
                flat_outs[qn] = flat
                emitted = dict(emitted)
                emitted[qn] = emitted[qn] + flat.count().astype(jnp.int64)
                lost = dict(lost)
                lost[qn] = lost[qn] + l
            dues = {qn: jnp.min(d) for qn, d in dues.items()}
            return slot_tbl, new_states, emitted, lost, flat_outs, dues

        return step

    # -- runtime ----------------------------------------------------------
    def process_stream_events(self, stream_id: str, events: list[Event]):
        from ..core.runtime import QueryRuntime
        schema = self.app.schemas[stream_id]
        for batch, last_ts in QueryRuntime.encode_chunks(
                schema, events, self.max_step_capacity):
            self.process_stream_batch(stream_id, batch, last_ts)

    def process_stream_batch(self, stream_id: str, batch: EventBatch,
                             timestamp: int, now: Optional[int] = None):
        cap = self.max_step_capacity
        if cap is not None and batch.capacity > cap:
            from ..core.runtime import QueryRuntime
            for sub in QueryRuntime.split_batch(batch, cap):
                self._run(("stream", stream_id), sub, timestamp, now)
            return
        self._run(("stream", stream_id), batch, timestamp, now)

    def _run(self, trigger, batch, timestamp, now=None):
        cost = getattr(self.app, "cost", None)
        probe = cost.probe("partition", self.name) \
            if cost is not None and cost.enabled else None
        with maybe_span(self.app, "partition", self.name,
                        trigger=str(trigger)):
            if now is None:
                now = self.app.current_time()
            now_dev = jnp.asarray(now, dtype=jnp.int64)
            with self._lock:
                step = self._step_for(trigger, batch.capacity)
                (self.slot_tbl, self.qstates, self._emitted, self._lost,
                 flat_outs, dues) = step(self.slot_tbl, self.qstates,
                                         self._emitted, self._lost, batch,
                                         now_dev)
            if probe is not None:
                # sampled branch only: the sync serializes the pipeline
                jax.block_until_ready(flat_outs)
                probe.done(rows=int(batch.capacity))
            for qn, out in flat_outs.items():
                self._dispatch(qn, out, timestamp)
            if dues:
                # one pytree transfer for every query's due, not one sync
                # per query (docs/tpu_hygiene.md host-sync-in-loop)
                for qn, due in jax.device_get(dues).items():
                    self._schedule(qn, int(due))

    def _dispatch(self, qname: str, out: EventBatch, timestamp: int):
        port = self.ports[qname]
        for cb in port.batch_callbacks:
            cb(out)
        if port.rate_limiter is not None:
            out_host = jax.device_get(out)
            rows = rows_from_batch(port.out_schema.types, out_host)
            if rows:
                port.rate_limiter.process(timestamp, rows)
            return
        row_handlers = [h for h in port.output_handlers
                        if not h.handle_device_batch(out, timestamp)]
        if not (row_handlers or port.callback_handler.callbacks):
            return
        out_host = jax.device_get(out)
        rows = rows_from_batch(port.out_schema.types, out_host)
        if not rows:
            return
        for h in row_handlers:
            h.handle(timestamp, rows)
        port.callback_handler.handle(timestamp, rows)

    # -- timers -----------------------------------------------------------
    def _schedule(self, qname: str, due: int):
        if due >= int(POS_INF):
            return
        cur = self._sched_due.get(qname)
        if cur is not None and cur <= due:
            return
        self._sched_due[qname] = due
        self.app.scheduler.notify_at(due, lambda d, q=qname:
                                     self._on_timer(q, d))

    def _on_timer(self, qname: str, due: int):
        self._sched_due[qname] = None
        if not self.app.running:
            return
        plan = next(p for p in self.plans if p.name == qname)
        from ..core.runtime import _timer_batch
        now = max(due, self.app.current_time())
        # TIMER rows carry the advanced clock (see QueryRuntime._on_timer)
        batch = _timer_batch(plan.in_schema, now)
        self._run(("timer", qname), batch, due, now=now)

    # -- snapshot ---------------------------------------------------------
    def snapshot_state(self) -> dict:
        with self._lock:
            snap = jax.device_get({"slot_tbl": self.slot_tbl,
                                   "qstates": self.qstates,
                                   "emitted": self._emitted,
                                   "lost": self._lost})
            snap["rate"] = {qn: p.rate_limiter.snapshot_state()
                            for qn, p in self.ports.items()
                            if p.rate_limiter is not None}
            return snap

    def restore_state(self, snap: dict) -> None:
        from ..core.runtime import _fresh_device
        with self._lock:
            if self.mesh is not None:
                # restore RE-PLACES shards straight from the host
                # snapshot: ONE sharded device_put per leaf (fresh
                # buffers by construction — a sharded put never aliases
                # the numpy payload, so the _fresh_device donation
                # guard is subsumed), never a fresh single-device copy
                # that a second pass then re-places
                self._apply_mesh_sharding(qstates=snap["qstates"],
                                          slot_tbl=snap["slot_tbl"])
            else:
                # snapshot payloads are host numpy; device_put may
                # alias them zero-copy, so single-device restores route
                # through _fresh_device before the state re-enters a
                # donated step (core/runtime.py)
                self.slot_tbl = _fresh_device(snap["slot_tbl"])
                self.qstates = _fresh_device(snap["qstates"])
            self._emitted = {k: jnp.array(v, copy=True)
                             for k, v in snap["emitted"].items()}
            self._lost = {k: jnp.array(v, copy=True)
                          for k, v in snap["lost"].items()}
            for qn in self._sched_due:
                self._sched_due[qn] = None
            for qn, rsnap in snap.get("rate", {}).items():
                port = self.ports.get(qn)
                if port is not None and port.rate_limiter is not None:
                    port.rate_limiter.restore_state(rsnap)

    def reschedule(self) -> None:
        """Re-arm per-query timers from restored [K]-stacked states."""
        per_plan: dict[str, list] = {}
        for p in self.plans:
            if not self._has_timers[p.name]:
                continue
            with self._lock:  # restore rebinds the stacked states
                qstates = self.qstates[p.name]
            for op, st in zip(p.operators, qstates):
                if isinstance(op, WindowOp):
                    d = jax.vmap(op.next_due)(st)
                    if d is not None:
                        per_plan.setdefault(p.name, []).append(jnp.min(d))
        if per_plan:
            # reductions stay on device; ONE pytree transfer re-arms every
            # query instead of a per-window sync
            for qn, ds in jax.device_get(per_plan).items():
                self._schedule(qn, min(int(d) for d in ds))

    # -- introspection ----------------------------------------------------
    def overflow_total(self) -> int:
        with self._lock:  # vs restore/process rebinding mid-read
            host = jax.device_get((self.slot_tbl, self.qstates,
                                   self._lost))
        tbl, qstates, losts = host
        total = int(tbl["overflow"])
        total += _tree_overflow_sum(qstates)
        total += sum(int(v) for v in losts.values())
        return total

    def stats(self) -> dict:
        with self._lock:  # vs the step path rebinding counters
            emitted = jax.device_get(self._emitted)
        return {"emitted": {qn: int(v) for qn, v in emitted.items()},
                "overflow": self.overflow_total()}
