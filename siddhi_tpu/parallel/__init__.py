"""Multi-chip execution: partitioned key-slot blocks
(parallel/partition.py), regex partition-rule sharding tables
(parallel/sharding.py), and measured data-parallel mesh execution
(parallel/mesh.py)."""
from .sharding import (  # noqa: F401
    DATA_PARALLEL_RULES,
    PARTITION_STATE_RULES,
    POOL_STATE_RULES,
    REPLICATE,
    SHARD,
    build_mesh,
    match_partition_rules,
    placement_stats,
    shard_pytree,
)
