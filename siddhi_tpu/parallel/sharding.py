"""Regex partition-rule tables: state pytree -> PartitionSpec -> placed
shards (the `match_partition_rules` pattern from large-model training
codebases, applied to streaming state).

Every multi-chip surface in this framework shards STATE along one
leading axis — the partition key-slot axis ([K]-leading, see
parallel/partition.py), the tenant-pool slot axis ([slots]-leading,
serving/pool.py), or the data-parallel shard axis ([n_devices]-leading,
parallel/mesh.py). Instead of each consumer hand-rolling `device_put`
calls, a rule table maps state paths (``qstates/q1/0/buf/ts``) to
actions by regex, first match wins:

- ``SHARD``      -> ``PartitionSpec(axis, None, ...)``: split the
                    leading axis over the mesh; the rest stays local.
- ``REPLICATE``  -> ``PartitionSpec()``: every device holds a copy
                    (overflow counters, small lookup tables).

Scalars and single-element leaves always replicate regardless of rules
(they cannot be split, and XLA would just broadcast them anyway).

Placement is DEDUPLICATED: `shard_pytree` checks each leaf's current
sharding and skips the `jax.device_put` when the leaf is already placed
as requested — so re-placement only ever transfers on the events that
actually change layout (slot-axis growth, snapshot restore), never on
steady-state rebuilds. `placement_stats` counts real puts vs skips;
tests/test_mesh.py pins the counts.

Restore contract: a host (numpy) snapshot passed through `shard_pytree`
lands directly as device shards — ONE `device_put` per leaf, already
fresh buffers (donation-safe: a sharded put never aliases the numpy
payload), and never a gather-then-scatter round trip.
"""
from __future__ import annotations

import re
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# rule actions
SHARD = "shard"           # split the leading axis over the mesh axis
REPLICATE = "replicate"   # full copy on every device

# -- default rule tables ----------------------------------------------------
# Partition blocks (parallel/partition.py): every operator state under
# qstates/ carries the [K] slot axis first and shards with it. The
# open-addressing key-slot table REPLICATES: the batch->slot map is
# computed over the whole ingest batch BEFORE the slot-vmap, on every
# device (its overflow counter is a scalar and auto-replicates).
PARTITION_STATE_RULES = (
    (r"(^|/)slot_tbl(/|$)", REPLICATE),
    (r"(^|/)qstates(/|$)", SHARD),
    (r"", SHARD),
)

# Tenant pools (serving/pool.py): stacked per-query operator states and
# the per-slot emitted counters all lead with the tenant-slot axis.
POOL_STATE_RULES = (
    (r"(^|/)(states|emitted)(/|$)", SHARD),
    (r"", SHARD),
)

# Data-parallel shard-axis stacking (parallel/mesh.py): everything leads
# with the shard axis — window pools, NFA pending tables, group-by
# tables, and the banded-join sorted pools (ops/join.py keeps the sorted
# key view per shard; see JOIN_STATE_RULES there for the key-axis view).
DATA_PARALLEL_RULES = (
    (r"", SHARD),
)


class PlacementStats:
    """Host-side counters of real vs skipped placements (the dedupe
    regression guard: tests monkeypatch nothing, they just read this)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.device_puts = 0
        self.skipped = 0

    def note(self, placed: bool) -> None:
        with self._lock:
            if placed:
                self.device_puts += 1
            else:
                self.skipped += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"device_puts": self.device_puts,
                    "skipped": self.skipped}

    def reset(self) -> None:
        with self._lock:
            self.device_puts = 0
            self.skipped = 0


placement_stats = PlacementStats()


def _path_str(path) -> str:
    """jax key path -> '/'-joined readable name (dict keys, tuple
    indices, attribute names)."""
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(str(p.name))
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            parts.append(str(p.key))
        else:  # pragma: no cover - future key kinds degrade readably
            parts.append(str(p).strip(".[]'\""))
    return "/".join(parts)


def _leaf_shape(leaf) -> tuple:
    return tuple(getattr(leaf, "shape", np.shape(leaf)))


def spec_for_path(name: str, leaf, rules, axis: str) -> PartitionSpec:
    """The PartitionSpec one state leaf gets under a rule table: scalars
    replicate unconditionally; otherwise the first rule whose regex
    ``search``es the path decides. No match is an ERROR — silent
    replication of a big state array is exactly the bug class this
    table exists to prevent."""
    shape = _leaf_shape(leaf)
    if len(shape) == 0 or int(np.prod(shape)) == 1:
        return PartitionSpec()
    for rule, action in rules:
        if re.search(rule, name) is None:
            continue
        if isinstance(action, PartitionSpec):
            return action
        if action == REPLICATE:
            return PartitionSpec()
        return PartitionSpec(axis, *([None] * (len(shape) - 1)))
    raise ValueError(f"no partition rule matched state path '{name}'")


def match_partition_rules(rules, tree, axis: str):
    """Pytree of PartitionSpec mirroring ``tree``, by regex rule table
    (SNIPPETS.md [1] `match_partition_rules`, state-path flavored)."""
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = [spec_for_path(_path_str(path), leaf, rules, axis)
             for path, leaf in leaves]
    return jax.tree_util.tree_unflatten(treedef, specs)


def describe_placement(tree, rules, axis: str) -> dict:
    """Human/machine-readable placement per state leaf: ``{path:
    "shard(<axis>)" | "replicate"}`` from the rule table — the mesh
    section of the explain report (obs/explain.py). Pure path + shape
    metadata: no device reads, no placement side effects; paths are
    stable across slot-axis growth so the plan hash never moves on
    churn."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in leaves:
        spec = spec_for_path(_path_str(path), leaf, rules, axis)
        out[_path_str(path)] = (f"shard({axis})" if len(spec) and
                                spec[0] is not None else "replicate")
    return out


def check_divisible(n: int, mesh: Mesh, what: str) -> None:
    axis = mesh.axis_names[0]
    nd = int(mesh.shape[axis])
    if n % nd:
        raise ValueError(
            f"{what} ({n}) must divide evenly over mesh axis "
            f"'{axis}' ({nd} devices)")


def device_of_index(index: int, n: int, mesh: Mesh, axis=None) -> int:
    """Mesh position that owns row ``index`` of a length-``n`` leading
    axis sharded over ``axis`` (first axis by default) — the host-side
    twin of the ``PartitionSpec(axis)`` block layout. The tenant pool's
    slot->device math (placement budgets, migration and evacuation
    targets) routes through here so it can never drift from the rule-
    table placement that `shard_pytree` actually applies."""
    axis = axis or mesh.axis_names[0]
    nd = int(mesh.shape[axis])
    if not 0 <= index < n:
        raise ValueError(f"index {index} out of range for axis of "
                         f"length {n}")
    return index // (n // nd)


def _already_placed(leaf, sharding: NamedSharding) -> bool:
    cur = getattr(leaf, "sharding", None)
    if cur is None:          # host numpy: never placed
        return False
    try:
        return cur.is_equivalent_to(sharding, leaf.ndim)
    except Exception:  # noqa: BLE001 — conservative: re-place
        return cur == sharding


def shard_pytree(tree, mesh: Mesh, rules, axis=None, stats=None):
    """Place every leaf of a state pytree per the rule table: ONE
    ``jax.device_put`` per leaf that is not already laid out as
    requested, zero for leaves that are (the dedupe contract — see
    module docstring). Host (numpy) leaves land directly as device
    shards without an intermediate single-device copy."""
    axis = axis or mesh.axis_names[0]
    stats = stats or placement_stats
    specs = match_partition_rules(rules, tree, axis)

    def place(x, spec):
        ns = NamedSharding(mesh, spec)
        if _already_placed(x, ns):
            stats.note(False)
            return x
        stats.note(True)
        return jax.device_put(x, ns)

    return jax.tree_util.tree_map(place, tree, specs)


def place_leading(arr, mesh: Mesh, axis=None):
    """ONE sharded ``jax.device_put`` of a host array (or array
    pytree) split over ``axis`` on the LEADING dimension — each device
    receives only its rows, and the transfer is still a single put
    call. The tenant pool's round inputs route through here: the
    packed (slots, total) ingest buffer and the stacked EventBatch
    both place with the identical slot-axis layout the POOL_STATE_RULES
    give the states they meet inside the vmapped step."""
    axis = axis or mesh.axis_names[0]
    return jax.device_put(
        arr, NamedSharding(mesh, PartitionSpec(axis)))


def build_mesh(n_devices=None, axis: str = "shards",
               devices=None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices (default:
    all of them). The CPU shim (`XLA_FLAGS=--xla_force_host_platform_
    device_count=N`) makes this testable without hardware."""
    devs = list(devices if devices is not None else jax.devices())
    n = int(n_devices) if n_devices else len(devs)
    if len(devs) < n:
        raise ValueError(
            f"mesh wants {n} devices but only {len(devs)} are visible "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{n} for the CPU shim)")
    return Mesh(np.array(devs[:n]), (axis,))


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """Version-portable shard_map: jax >= 0.8 exports it at the top
    level (check_vma), older versions under jax.experimental
    (check_rep). Replication checking is off either way — the local
    steps intentionally mix sharded state with replicated clocks."""
    try:
        from jax import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sme

        return _sme(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, check_rep=False)
