"""Data-parallel mesh execution: the planner's own compiled query steps
run under ``shard_map`` over a 1-D device mesh, with per-shard
device-local state and collectives ONLY at the aggregate boundary.

Execution model (ROADMAP item 1, SURVEY §2.6):

- the ingest chunk batch axis splits over the mesh: shard d receives its
  own sub-stream slice (a ``(n_devices, B)``-stacked ``EventBatch``
  placed with ``NamedSharding(P(axis))`` — ONE transfer, each device
  gets only its rows);
- window pools, NFA pending tables, group-by tables and banded-join
  sorted pools stay DEVICE-LOCAL: shard d's state never crosses the
  interconnect (rule table: ``sharding.DATA_PARALLEL_RULES``);
- optional key routing (``route_cols``): every shard's ingest is
  all-gathered, each shard keeps the events whose key hash it owns
  (owner = hash(key) % n), restoring event-time order before
  order-sensitive steps — a key's keyed state then lives on exactly one
  shard while being reachable from every shard's input;
- ``psum`` crosses shards ONLY for aggregate outputs: the per-step
  emitted-row count is all-reduced so callers read ONE replicated
  number instead of gathering per-shard outputs.

This module is the measured multi-chip layer behind
``bench.py multichip`` and ``__graft_entry__.dryrun_multichip``; the
bit-equivalence sweep against single-chip replays lives in
tests/test_mesh.py.
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from . import sharding
from ..core.event import batch_from_columns

# Knuth multiplicative hash — the one host/device-mirrored owner
# function (also the routing hash of __graft_entry__.dryrun_multichip)
_OWNER_MULT = 2654435761


def owner_of(codes, n_devices: int):
    """Device-side shard owner of each key code ([B] int -> [B] int32)."""
    h = (codes.astype(jnp.uint32) * jnp.uint32(_OWNER_MULT)) \
        >> jnp.uint32(8)
    return (h % jnp.uint32(n_devices)).astype(jnp.int32)


def owner_of_host(code: int, n_devices: int) -> int:
    """Host mirror of owner_of() for assertions/tests."""
    return (((code * _OWNER_MULT) & 0xFFFFFFFF) >> 8) % n_devices


def _peel(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _expand(tree):
    return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), tree)


class DataParallelRunner:
    """ONE query of ONE app executed data-parallel over a mesh.

    Supports the three step families the planner compiles:

    - plain row queries (filter / window / group-by chains):
      ``QueryRuntime._make_step``;
    - pattern/sequence queries (NFA): ``_step_for_stream``;
    - two-stream joins: ``_step_for_side`` per trigger side.

    ``route_cols`` maps a trigger stream id to the index of its key
    column; routed streams all-gather + owner-mask (keyed state — group
    -by tables, NFA pending per key, join pools banded along the key
    axis — lands on the owning shard). Streams not in the map run pure
    data-parallel: each shard processes its own sub-stream.
    """

    def __init__(self, ql: str, query: str, mesh=None, n_devices=None,
                 route_cols: Optional[dict] = None):
        from ..core.manager import SiddhiManager
        from ..core.runtime import (JoinQueryRuntime, PatternQueryRuntime,
                                    QueryRuntime)
        self.mesh = mesh if mesh is not None \
            else sharding.build_mesh(n_devices)
        self.axis = self.mesh.axis_names[0]
        self.n = int(self.mesh.shape[self.axis])
        self.mgr = SiddhiManager()
        self.rt = self.mgr.create_siddhi_app_runtime(ql)
        q = self.rt.queries[query]
        self.q = q
        if route_cols == "auto":
            # joins carry their own routing key: the banded equi
            # conjunct's bare columns (ops/join.py equi_route_columns)
            rc = None
            for cross in getattr(q, "crosses", {}).values():
                rc = getattr(cross, "route_cols", None) or rc
            if rc is None:
                raise ValueError(
                    f"query '{query}' has no bare-column equi key to "
                    "route by (route_cols='auto' needs one)")
            route_cols = {q.in_schemas[s].stream_id: idx
                          for s, idx in rc.items()}
        self.route_cols = dict(route_cols or {})
        if getattr(q, "table_deps", ()):
            raise ValueError(
                f"query '{query}' reads tables — table state is not "
                "data-parallel (route it through a keyed partition)")
        if isinstance(q, JoinQueryRuntime):
            self.kind = "join"
            self._state = {
                "sides": self._stack({s: q.side_states[s]
                                      for s in ("L", "R")}),
                "sel": self._stack(q.states),
            }
        elif isinstance(q, PatternQueryRuntime):
            self.kind = "pattern"
            self._state = {"nfa": self._stack(q.nfa_state),
                           "sel": self._stack(q.states)}
        elif type(q) is QueryRuntime:
            self.kind = "row"
            self._state = {"states": self._stack(q.states)}
        else:
            raise ValueError(
                f"unsupported runtime {type(q).__name__} for "
                "data-parallel execution")
        self._emitted = self._place(
            np.zeros((self.n,), np.int64))
        self._fns: dict = {}
        self.rows_in = 0

    # -- state / batch placement ------------------------------------------

    def _place(self, tree):
        return sharding.shard_pytree(
            tree, self.mesh, sharding.DATA_PARALLEL_RULES, axis=self.axis)

    def _stack(self, tree):
        """Replicate an init-state pytree onto the leading shard axis and
        place it sharded: each device holds exactly its own copy (one
        batched pytree transfer to host, one placement per leaf)."""
        n = self.n
        host = jax.device_get(tree)
        stacked = jax.tree_util.tree_map(
            lambda x: np.broadcast_to(
                np.asarray(x)[None], (n,) + tuple(np.shape(x))).copy(),
            host)
        return self._place(stacked)

    def stack_shards(self, stream_id: str, shards):
        """Per-shard ``(ts, cols)`` host chunks -> ONE sharded
        ``(n, B)``-stacked EventBatch (device d gets row d only)."""
        schema = self.rt.schemas[stream_id]
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shard chunks, got "
                             f"{len(shards)}")
        cap = max(len(np.asarray(ts)) for ts, _ in shards)
        batches = [batch_from_columns(schema, ts, cols, capacity=cap)
                   for ts, cols in shards]
        stacked = jax.tree_util.tree_map(
            lambda *xs: np.stack(xs), *batches)
        self.rows_in += sum(len(np.asarray(ts)) for ts, _ in shards)
        return jax.device_put(
            stacked, NamedSharding(self.mesh, P(self.axis)))

    # -- routing ----------------------------------------------------------

    def _router(self, stream_id: str, order_sensitive: bool):
        col = self.route_cols.get(stream_id)
        if col is None:
            return None
        axis, n = self.axis, self.n

        def route(b):
            me = jax.lax.axis_index(axis)
            g = jax.tree_util.tree_map(
                lambda x: jax.lax.all_gather(x, axis).reshape(
                    (-1,) + x.shape[1:]), b)
            routed = g.mask(owner_of(g.cols[col], n) == me)
            if order_sensitive:
                # the all-gather concatenates shard-major; restore
                # event-time order (stable: ties keep shard-major order,
                # the single-chip union replay's exact tie-break)
                key = jnp.where(routed.valid, routed.ts,
                                jnp.int64(2 ** 62))
                perm = jnp.argsort(key, stable=True)
                routed = jax.tree_util.tree_map(lambda x: x[perm], routed)
            return routed

        return route

    # -- compiled steps (cached per trigger+capacity: zero steady-state
    # retraces, the _step_for contract) -----------------------------------

    def _fn_for(self, trigger, cap: int):
        key = (trigger, cap)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        axis = self.axis
        if self.kind == "row":
            step = self.q._make_step()
            route = self._router(trigger, order_sensitive=False)

            def local(state, emitted, batch, now):
                s, b, e = _peel(state["states"]), _peel(batch), emitted[0]
                if route is not None:
                    b = route(b)
                s2, _t, e2, out, _due = step(s, {}, e, b, now)
                agg = jax.lax.psum(out.count().astype(jnp.int64), axis)
                return ({"states": _expand(s2)},
                        jnp.expand_dims(e2, 0), _expand(out), agg)

        elif self.kind == "pattern":
            step = self.q._step_for_stream(trigger)
            route = self._router(trigger, order_sensitive=True)

            def local(state, emitted, batch, now):
                nfa, sel = _peel(state["nfa"]), _peel(state["sel"])
                b, e = _peel(batch), emitted[0]
                if route is not None:
                    b = route(b)
                nfa2, sel2, _t, e2, out = step(nfa, sel, {}, e, b, now)
                agg = jax.lax.psum(out.count().astype(jnp.int64), axis)
                return ({"nfa": _expand(nfa2), "sel": _expand(sel2)},
                        jnp.expand_dims(e2, 0), _expand(out), agg)

        else:  # join: trigger is the side tag "L" | "R"
            side = trigger
            opp = "R" if side == "L" else "L"
            step = self.q._step_for_side(side)
            sid = self.q.in_schemas[side].stream_id
            route = self._router(sid, order_sensitive=False)

            def local(state, emitted, batch, now):
                sides = {s: _peel(state["sides"][s]) for s in ("L", "R")}
                sel = _peel(state["sel"])
                b, e = _peel(batch), emitted[0]
                if route is not None:
                    b = route(b)
                my, sel2, _t, e2, out, _lost, _due = step(
                    sides[side], sides[opp], sel, {}, e, b, now)
                new_sides = dict(state["sides"])
                new_sides[side] = _expand(my)
                agg = jax.lax.psum(out.count().astype(jnp.int64), axis)
                return ({"sides": new_sides, "sel": _expand(sel2)},
                        jnp.expand_dims(e2, 0), _expand(out), agg)

        fn = jax.jit(sharding.shard_map(
            local, self.mesh,
            (P(axis), P(axis), P(axis), P()),
            (P(axis), P(axis), P(axis), P())))
        self._fns[key] = fn
        return fn

    # -- dispatch ---------------------------------------------------------

    def step(self, trigger, stacked_batch, now: int):
        """Advance every shard one step; returns the per-shard stacked
        output batch (device-resident, sharded) and the psum'd aggregate
        emitted-row count (replicated scalar)."""
        fn = self._fn_for(trigger, int(stacked_batch.ts.shape[-1]))
        now_dev = jnp.asarray(int(now), dtype=jnp.int64)
        self._state, self._emitted, out, agg = fn(
            self._state, self._emitted, stacked_batch, now_dev)
        return out, agg

    def send_shards(self, stream_id: str, shards, now: int):
        """stack + step for the common single-trigger case."""
        trigger = stream_id if self.kind != "join" else next(
            s for s in ("L", "R")
            if self.q.in_schemas[s].stream_id == stream_id)
        return self.step(trigger, self.stack_shards(stream_id, shards),
                         now)

    @property
    def emitted_total(self) -> int:
        """Aggregate emitted rows across shards (one reduction, one
        scalar read — never a per-shard gather)."""
        return int(jax.device_get(jnp.sum(self._emitted)))

    def explain(self) -> dict:
        """Data-parallel placement decisions for the explain surface
        (obs/explain.py): step family, mesh geometry, which streams
        route by key (and on which column), and the rule-table
        placement per state leaf. Host-side metadata only — no device
        reads, no new programs."""
        return {
            "kind": self.kind,
            "query": self.q.name,
            "axis": self.axis,
            "n_devices": self.n,
            "route_cols": {sid: int(col) for sid, col
                           in sorted(self.route_cols.items())},
            "psum_boundary": "aggregate-emitted-count",
            "placement": sharding.describe_placement(
                self._state, sharding.DATA_PARALLEL_RULES, self.axis),
        }


# -- measured scaling arms (bench.py `multichip`, __graft_entry__) ----------

FILTER_QL = """
    @app:playback
    define stream S (sym int, price float, volume long);
    @info(name = 'q')
    from S[price > 100.0] select sym, price insert into Out;
"""

SEQ5_QL = """
    @app:playback
    define stream T (sym int, stage int, v int);
    @info(name = 'p')
    from every e1=T[stage == 1] -> e2=T[stage == 2] -> e3=T[stage == 3]
      -> e4=T[stage == 4] -> e5=T[stage == 5]
    within 60 sec
    select e1.sym as sym, e5.v as v insert into POut;
"""

TENANT_QL = """
define stream In (v double, k long);
@info(name='q')
from In[v > ${lo:double} and v < ${hi:double}]#window.lengthBatch(256)
select v, k
insert into Out;
"""

TS0 = 1_700_000_000_000


def _filter_shard(b: int, seed: int):
    rng = np.random.default_rng(seed)
    ts = TS0 + np.arange(b, dtype=np.int64)
    return ts, [rng.integers(0, 64, b).astype(np.int32),
                rng.uniform(0, 200, b).astype(np.float32),
                rng.integers(1, 100, b, dtype=np.int64)]


def _seq5_shard(b: int, seed: int):
    rng = np.random.default_rng(1000 + seed)
    ts = TS0 + np.arange(b, dtype=np.int64)
    return ts, [rng.integers(0, 64, b).astype(np.int32),
                rng.integers(1, 6, b).astype(np.int32),
                rng.integers(0, 1000, b).astype(np.int32)]


def _arm_entry(events: int, seconds: float, n: int,
               eps_1dev: Optional[float]) -> dict:
    eps = events / seconds
    entry = {"n_devices": n,
             "eps_aggregate": round(eps, 1),
             "eps_per_device": round(eps / n, 1),
             "seconds": round(seconds, 3)}
    if eps_1dev:
        entry["eps_1dev"] = round(eps_1dev, 1)
        entry["scaling"] = round(eps / eps_1dev, 2)
        entry["scaling_efficiency"] = round(eps / (n * eps_1dev), 3)
    return entry


def _measure_runner(ql, query, n: int, chunk: int, iters: int,
                    reps: int, mk_shard) -> float:
    """Best-of-reps wall seconds for `iters` stacked rounds of `chunk`
    rows per shard (weak scaling: per-device load is constant)."""
    runner = DataParallelRunner(ql, query, n_devices=n)
    sid = next(iter(runner.rt.schemas))
    batches = [runner.stack_shards(
        sid, [mk_shard(chunk, d + i * n) for d in range(n)])
        for i in range(2)]
    now = TS0 + chunk
    out, _ = runner.step(sid, batches[0], now)   # compile off the clock
    jax.block_until_ready(out.valid)
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        for i in range(iters):
            out, _ = runner.step(sid, batches[i % 2], now + i)
        # ONE sync per timed rep closes the async-dispatch pipeline —
        # the measurement IS the sync (bench.py _drain pattern)
        jax.block_until_ready(out.valid)  # lint: disable=host-sync-in-loop
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _measure_pool(n_mesh: int, tenants: int, rows: int, batch_max: int,
                  reps: int) -> float:
    """Best-of-reps wall seconds for one full pooled pass: every tenant
    sends `rows` rows, fair rounds drain them. n_mesh > 1 shards the
    slot axis (1/n of the tenants per device)."""
    from ..serving import TemplateRegistry
    from ..core.manager import SiddhiManager
    mesh = sharding.build_mesh(n_mesh) if n_mesh > 1 else None
    reg = TemplateRegistry(SiddhiManager())
    pool = reg.pool(TENANT_QL, warm=False, slots=tenants,
                    max_tenants=tenants, batch_max=batch_max,
                    mesh=mesh, name=f"mc{n_mesh}")
    pool.warmup([batch_max])
    for i in range(tenants):
        pool.add_tenant(f"t{i}", {"lo": 20.0 + (i % 16),
                                  "hi": 180.0 - (i % 16)})
    rng = np.random.default_rng(11)
    ts = TS0 + np.arange(rows, dtype=np.int64)
    cols = [rng.uniform(0, 200, rows), rng.integers(
        0, 1 << 20, rows, dtype=np.int64)]
    last = {}
    # terminal maps sid -> LIST of device batches; keep the newest
    pool.batch_callbacks.append(
        lambda terminal: last.update(out=next(
            iter(terminal.values()))[-1] if terminal else None))

    def one_pass():
        for i in range(tenants):
            pool.send(f"t{i}", ts, cols)
        pool.flush()
        if last.get("out") is not None:
            jax.block_until_ready(last["out"].valid)

    one_pass()   # dispatch caches settle off the clock
    best = min(_timed(one_pass) for _ in range(reps))
    pool.shutdown()
    return best


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def measure_scaling(n_devices: int = 8, chunk: int = 16384,
                    seq_chunk: Optional[int] = None,
                    iters: int = 4, reps: int = 2,
                    tenants: Optional[int] = None,
                    tenant_rows: int = 1024,
                    arms=("filter", "seq5", "tenants")) -> dict:
    """The MULTICHIP acceptance measurement: aggregate events/s for each
    arm at n_devices vs 1 device (weak scaling — per-device load held
    constant), with per-arm scaling efficiency. Returns the JSON-ready
    dict bench.py `multichip` and the __graft_entry__ child both emit.

    `platform` makes the artifact honest about WHERE it ran: on the
    forced-host-device CPU shim every "device" shares the host's cores
    (one core: no scaling is physically possible — the numbers guard
    plumbing, not parallelism); on real multi-chip hardware the
    efficiency number is the ROADMAP item 1 acceptance signal."""
    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"measure_scaling wants {n_devices} devices, "
            f"{len(jax.devices())} visible")
    if tenants is None:
        tenants = 64 * n_devices
    if seq_chunk is None:
        seq_chunk = max(256, chunk // 4)
    out: dict = {
        "n_devices": n_devices,
        "platform": jax.devices()[0].platform,
        "host_device_shim": jax.devices()[0].platform == "cpu",
        "arms": {},
    }
    if "filter" in arms:
        dt1 = _measure_runner(FILTER_QL, "q", 1, chunk, iters, reps,
                              _filter_shard)
        dtn = _measure_runner(FILTER_QL, "q", n_devices, chunk, iters,
                              reps, _filter_shard)
        out["arms"]["filter"] = _arm_entry(
            n_devices * chunk * iters, dtn, n_devices,
            chunk * iters / dt1)
    if "seq5" in arms:
        dt1 = _measure_runner(SEQ5_QL, "p", 1, seq_chunk, iters, reps,
                              _seq5_shard)
        dtn = _measure_runner(SEQ5_QL, "p", n_devices, seq_chunk, iters,
                              reps, _seq5_shard)
        out["arms"]["seq5"] = _arm_entry(
            n_devices * seq_chunk * iters, dtn, n_devices,
            seq_chunk * iters / dt1)
    if "tenants" in arms:
        batch_max = min(1024, tenant_rows)
        t_small = max(n_devices, tenants // n_devices)
        dt1 = _measure_pool(1, t_small, tenant_rows, batch_max, reps)
        dtn = _measure_pool(n_devices, tenants, tenant_rows, batch_max,
                            reps)
        entry = _arm_entry(tenants * tenant_rows, dtn, n_devices,
                           t_small * tenant_rows / dt1)
        entry["tenants"] = tenants
        entry["tenants_1dev"] = t_small
        out["arms"]["tenants"] = entry
    return out
