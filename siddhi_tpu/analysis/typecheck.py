"""App-wide schema & dtype inference: a static type checker over the
query dataflow graph.

``plan_rules.py`` answers "does this stream exist / does this window
take two parameters"; this pass answers everything *type-shaped*. It

1. builds the stream dataflow graph of a parsed app — queries,
   partitions, joins, patterns, insert-into edges;
2. topologically propagates schemas so implicitly-defined streams
   (insert-into targets) get inferred ``(name, AttrType)`` schemas; and
3. statically types every expression by mirroring the rules
   ``ops/expr.py`` / ``ops/selector.py`` / ``ops/aggregators.py`` apply
   at compile time: Java numeric promotion in arithmetic, comparability
   in comparisons (STRING vs numeric is an error — device strings are
   int32 dictionary codes), BOOL-typed filter/having conditions,
   aggregator result types (``avg -> DOUBLE``, ``count -> LONG``, …),
   and alias-scoped resolution for join sides and pattern ``e1=``
   references (subsuming the single-stream-only attribute check PR 1's
   ``plan_rules.check_attributes`` shipped with).

Error-severity issues are definite compile-time rejections (the runtime
planner or the expression compiler would raise the same way later, or
worse, an XLA shape error would) and make ``check_app`` raise
``CompileError`` from inside ``lang.parser.parse``. Warning-severity
issues (dead dataflow, float64-in-hot-path, coercible insert widths)
flow through the PR 1 ``Finding``/baseline machinery via
``tools/lint.py --plan`` so they are suppressible and baselined.

The checker *never guesses*: anything it cannot type statically
(extension stream processors, aggregation references, UDF results
without declared types) becomes an unknown that propagates and
suppresses dependent diagnostics. A clean pass is a claim, a silent
pass is not.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from ..core.types import AttrType, NUMERIC_TYPES, comparable, promote
from ..lang import ast as A
from .findings import Finding
from .schema import (AGGREGATOR_NAMES, COERCE, INFERRED, MISMATCH, Schema,
                     aggregator_accepts, aggregator_result_type,
                     insert_compat, schema_from_attribute_defs)

ERROR = "error"
WARNING = "warning"

_BOOL = AttrType.BOOL
_STRING = AttrType.STRING
_DOUBLE = AttrType.DOUBLE
_LONG = AttrType.LONG


@dataclasses.dataclass(frozen=True)
class TypeIssue:
    code: str
    severity: str
    where: str            # query name / stream id anchor
    message: str
    line: Optional[int] = None

    def render(self) -> str:
        return f"{self.where}: {self.severity} [{self.code}] {self.message}"


@dataclasses.dataclass
class TypeReport:
    issues: list[TypeIssue]
    schemas: dict[str, Schema]       # every known stream-like schema,
                                     # inferred implicit streams included

    @property
    def errors(self) -> list[TypeIssue]:
        return [i for i in self.issues if i.severity == ERROR]

    @property
    def warnings(self) -> list[TypeIssue]:
        return [i for i in self.issues if i.severity == WARNING]


class _Unresolved(Exception):
    """Definite resolution failure inside a scope (code + message)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _output_attribute_name(oa: A.OutputAttribute, i: int) -> str:
    # = ops/selector.py output_attribute_name (kept import-light here)
    if oa.rename:
        return oa.rename
    if isinstance(oa.expression, A.Variable):
        return oa.expression.attribute
    return f"_{i}"


# ---------------------------------------------------------------------------
# scopes: the static twins of ops/expr.py SingleStreamScope,
# ops/join.py JoinSideScope and ops/nfa.py PatternScope
# ---------------------------------------------------------------------------


def _skippable(var: A.Variable) -> bool:
    """Variables the static pass never types: compiler placeholders,
    aggregation references (StockAgg.avgPrice#...), fault/inner columns."""
    if var.attribute is None:            # bare stream ref (IS NULL forms)
        return True
    if var.function_ref is not None:
        return True
    if var.attribute.startswith("__"):
        return True
    if var.is_fault or var.is_inner:
        return True
    return False


class _SingleScope:
    """One input stream; accepts the stream id and its alias (an alias
    does not shadow the id for single streams — SingleStreamScope)."""

    def __init__(self, checker: "TypeChecker", schema: Optional[Schema],
                 refs: set):
        self.checker = checker
        self.schema = schema
        self.refs = refs

    def resolve(self, var: A.Variable) -> Optional[AttrType]:
        if _skippable(var):
            return None
        ref = var.stream_ref
        if ref is not None and ref not in self.refs:
            if ref in self.checker.table_ids:
                return None     # table-scoped: planner territory
            raise _Unresolved(
                "unresolved-reference",
                f"unknown stream reference '{ref}' (expected "
                f"{sorted(self.refs)})")
        if var.index is not None:
            return None         # indexed refs only exist in patterns
        if self.schema is None:
            return None
        if not self.schema.has(var.attribute):
            raise _Unresolved(
                "undefined-attribute",
                f"'{var.attribute}' is not an attribute of stream "
                f"'{self.schema.stream_id}' {self.schema.render()}")
        return self.schema.get(var.attribute)


class _JoinScope:
    """Two sides; an alias REPLACES the side's stream id (JoinSideScope:
    the reference rejects the original id once `as x` is used)."""

    def __init__(self, checker: "TypeChecker",
                 left: Optional[Schema], left_name: str,
                 right: Optional[Schema], right_name: str):
        self.checker = checker
        self.sides = ((left, left_name), (right, right_name))
        self.incomplete = left is None or right is None

    def resolve(self, var: A.Variable) -> Optional[AttrType]:
        if _skippable(var) or var.index is not None:
            return None
        ref = var.stream_ref
        if ref is not None:
            for schema, name in self.sides:
                if ref == name:
                    if schema is None:
                        return None
                    if not schema.has(var.attribute):
                        raise _Unresolved(
                            "undefined-attribute",
                            f"'{ref}' has no attribute '{var.attribute}'")
                    return schema.get(var.attribute)
            if ref in self.checker.table_ids:
                return None
            if self.incomplete:
                return None
            raise _Unresolved("unresolved-reference",
                              f"unknown stream reference '{ref}' in join")
        if self.incomplete:
            return None
        hits = [s for s, _ in self.sides if s.has(var.attribute)]
        if len(hits) == 1:
            return hits[0].get(var.attribute)
        if hits:
            raise _Unresolved(
                "unresolved-reference",
                f"attribute '{var.attribute}' is ambiguous across join "
                "sides (qualify it)")
        raise _Unresolved(
            "undefined-attribute",
            f"attribute '{var.attribute}' is unknown across join sides")


@dataclasses.dataclass
class _Slot:
    ref: Optional[str]          # e1= event reference
    stream_id: str
    schema: Optional[Schema]
    stream: A.SingleInputStream


class _PatternScope:
    """Match-slot resolution, mirroring ops/nfa.py PatternScope: event
    refs first, then unique stream-id matches; bare attributes bind to
    the state's own stream first, else must be unique across slots."""

    def __init__(self, checker: "TypeChecker", slots: list[_Slot],
                 own_slot: Optional[int] = None):
        self.checker = checker
        self.slots = slots
        self.own_slot = own_slot
        self.incomplete = any(s.schema is None for s in slots)

    def _find(self, var: A.Variable) -> Optional[int]:
        ref = var.stream_ref
        if ref is not None:
            for j, s in enumerate(self.slots):
                if s.ref == ref:
                    return j
            matches = [j for j, s in enumerate(self.slots)
                       if s.stream_id == ref]
            if len(matches) == 1:
                return matches[0]
            if len(matches) > 1:
                raise _Unresolved(
                    "unresolved-reference",
                    f"ambiguous stream reference '{ref}' in pattern")
            if ref in self.checker.table_ids:
                return None
            raise _Unresolved("unresolved-reference",
                              f"unknown event reference '{ref}'")
        own = self.own_slot
        if own is not None and self.slots[own].schema is not None \
                and self.slots[own].schema.has(var.attribute):
            return own
        if self.incomplete:
            return None
        matches = [j for j, s in enumerate(self.slots)
                   if s.schema.has(var.attribute)]
        if len(matches) == 1:
            return matches[0]
        if matches:
            raise _Unresolved(
                "unresolved-reference",
                f"attribute '{var.attribute}' is ambiguous in pattern "
                "scope (qualify it with an event reference)")
        raise _Unresolved(
            "undefined-attribute",
            f"attribute '{var.attribute}' is unknown in pattern scope")

    def resolve(self, var: A.Variable) -> Optional[AttrType]:
        if _skippable(var):
            return None
        j = self._find(var)
        if j is None:
            return None
        spec = self.slots[j]
        if spec.schema is None:
            return None
        if not spec.schema.has(var.attribute):
            raise _Unresolved(
                "undefined-attribute",
                f"'{spec.ref or spec.stream_id}' has no attribute "
                f"'{var.attribute}'")
        # indexed (e1[2].x / e1[last].x) refs share the attribute's type
        return spec.schema.get(var.attribute)


class _OutputChainScope:
    """HAVING scope: the selector's own output attributes first, the
    input scope second (ops/selector.py OutputScope + ChainScope)."""

    def __init__(self, out_schema: Optional[Schema], inner):
        self.out_schema = out_schema
        self.inner = inner

    def resolve(self, var: A.Variable) -> Optional[AttrType]:
        if _skippable(var):
            return None
        if self.out_schema is not None and var.stream_ref is None \
                and var.index is None and self.out_schema.has(var.attribute):
            return self.out_schema.get(var.attribute)
        return self.inner.resolve(var)


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _QueryInfo:
    query: A.Query
    name: str
    partition_index: Optional[int]   # index into app.execution_elements


class TypeChecker:
    def __init__(self, app: A.SiddhiApp):
        self.app = app
        self.issues: list[TypeIssue] = []
        self.table_ids = set(app.table_definitions)
        # id -> Schema | None (known id, statically unknown schema)
        self.sources: dict[str, Optional[Schema]] = {}
        for sid, sd in app.stream_definitions.items():
            self.sources[sid] = schema_from_attribute_defs(
                sid, sd.attributes, line=sd.line)
            for ann in sd.annotations:
                if ann.name.lower() == "onerror" and \
                        (ann.element("action") or "").upper() == "STREAM":
                    # shadow fault stream: attrs + _error STRING
                    self.sources["!" + sid] = Schema(
                        "!" + sid,
                        self.sources[sid].attrs + (("_error", _STRING),),
                        source="builtin")
        for tid, td in app.table_definitions.items():
            self.sources[tid] = schema_from_attribute_defs(
                tid, td.attributes)
        for wid, wd in app.window_definitions.items():
            self.sources[wid] = schema_from_attribute_defs(
                wid, wd.attributes)
        for tid in app.trigger_definitions:
            self.sources[tid] = Schema(
                tid, (("triggered_time", _LONG),), source="builtin")
        for aid in app.aggregation_definitions:
            self.sources[aid] = None   # duration-bucketed: planner types it
        self.infos = list(self._collect())
        # per-query settled output schema (index into self.infos)
        self.out_schemas: list[Optional[Schema]] = [None] * len(self.infos)
        # app-scope implicit insert targets -> producer info indices
        self.producers: dict[str, list[int]] = {}
        for i, qi in enumerate(self.infos):
            t = self._stream_target(qi.query)
            if t is not None:
                self.producers.setdefault(t, []).append(i)

    # -- graph collection ----------------------------------------------
    def _collect(self):
        qn = 0
        for ei, el in enumerate(self.app.execution_elements):
            if isinstance(el, A.Query):
                qn += 1
                yield _QueryInfo(el, el.name or f"query{qn}", None)
            elif isinstance(el, A.Partition):
                pname = f"partition{qn + 1}"
                for i, q in enumerate(el.queries):
                    yield _QueryInfo(q, q.name or f"{pname}.query{i + 1}",
                                     ei)
                qn += len(el.queries)

    @staticmethod
    def _stream_target(q: A.Query) -> Optional[str]:
        out = q.output
        if isinstance(out, A.InsertIntoStream) and not out.is_inner \
                and not out.is_fault:
            return out.target
        return None

    # -- issue emission -------------------------------------------------
    def _emitter(self, qi: Optional[_QueryInfo],
                 where: Optional[str] = None) -> Callable:
        anchor = where or (qi.name if qi else "app")
        line = qi.query.line if qi else None

        def emit(code: str, message: str, severity: str = ERROR):
            issue = TypeIssue(code=code, severity=severity, where=anchor,
                              message=message, line=line)
            if issue not in self.issues:
                self.issues.append(issue)
        return emit

    @staticmethod
    def _no_emit(code: str, message: str, severity: str = ERROR):
        pass

    # -- expression typing ---------------------------------------------
    def type_expr(self, e: A.Expression, scope, emit,
                  agg: bool = False) -> Optional[AttrType]:
        te = lambda x: self.type_expr(x, scope, emit, agg)  # noqa: E731

        if isinstance(e, A.Constant):
            if e.value is None:
                return e.type if isinstance(e.type, AttrType) else _DOUBLE
            return e.type

        if isinstance(e, A.Variable):
            try:
                return scope.resolve(e)
            except _Unresolved as u:
                emit(u.code, u.message)
                return None

        if isinstance(e, A.TemplateParam):
            # tenant-template placeholder: types as its declared
            # `${name:type}` type, so a binding position that contradicts
            # the surrounding expression (e.g. `price > ${t:string}`)
            # fails right here through the shared comparability tables.
            # Untyped placeholders type as unknown; the template-binding
            # plan rule rejects them with a dedicated message.
            return e.type if isinstance(e.type, AttrType) else None

        if isinstance(e, A.MathOp):
            l, r = te(e.left), te(e.right)
            bad = False
            for t in (l, r):
                if t is not None and t not in NUMERIC_TYPES:
                    emit("non-numeric-math",
                         f"'{e.op}' requires numeric operands, got "
                         f"{t.value.upper()}")
                    bad = True
            if bad or l is None or r is None:
                return None
            return promote(l, r)

        if isinstance(e, A.Compare):
            l, r = te(e.left), te(e.right)
            if l is not None and r is not None:
                if not comparable(l, r):
                    if (l is _STRING) != (r is _STRING):
                        other = r if l is _STRING else l
                        emit("string-numeric-compare",
                             f"cannot compare STRING with "
                             f"{other.value.upper()}: device strings are "
                             "int32 dictionary codes — the comparison "
                             "would relate codes, not text")
                    else:
                        emit("incomparable-types",
                             f"cannot compare {l.value.upper()} with "
                             f"{r.value.upper()}")
                elif l is _STRING and e.op not in ("==", "!="):
                    emit("string-ordering",
                         f"ordering comparison '{e.op}' on STRING is not "
                         "supported on device (dictionary codes are not "
                         "lexicographic)")
            return _BOOL

        if isinstance(e, (A.And, A.Or)):
            for t, side in ((te(e.left), "left"), (te(e.right), "right")):
                if t is not None and t is not _BOOL:
                    word = "AND" if isinstance(e, A.And) else "OR"
                    emit("non-bool-logical",
                         f"{word} requires BOOL operands, {side} side is "
                         f"{t.value.upper()}")
            return _BOOL

        if isinstance(e, A.Not):
            t = te(e.expr)
            if t is not None and t is not _BOOL:
                emit("non-bool-logical",
                     f"NOT requires a BOOL operand, got {t.value.upper()}")
            return _BOOL

        if isinstance(e, A.IsNull):
            if e.expr is not None:
                te(e.expr)
            return _BOOL

        if isinstance(e, A.InTable):
            # inner expression may be table-scoped; table vars resolve
            # to unknown in every scope, so this stays silent for them
            te(e.expr)
            return _BOOL

        if isinstance(e, A.AttributeFunction):
            return self._type_function(e, scope, emit, agg)

        return None

    def _type_function(self, e: A.AttributeFunction, scope, emit,
                       agg: bool) -> Optional[AttrType]:
        params = [self.type_expr(p, scope, emit, agg) for p in e.parameters]
        key = e.name.lower()

        if e.namespace is not None:
            if e.namespace.lower() == "math":
                return self._type_math(key, params, emit)
            return None            # extension function: planner resolves

        if agg and key in AGGREGATOR_NAMES:
            arg = params[0] if params else None
            if not e.star and not aggregator_accepts(key, arg):
                emit("aggregator-input",
                     f"aggregator '{e.name}' cannot take a "
                     f"{arg.value.upper()} argument")
                return aggregator_result_type(key, None)
            return aggregator_result_type(key, arg)

        if key in ("convert", "cast"):
            if len(e.parameters) == 2 and \
                    isinstance(e.parameters[1], A.Constant):
                try:
                    return AttrType.from_name(str(e.parameters[1].value))
                except ValueError:
                    return None
            return None
        if key == "coalesce":
            return self._fold_shared_type(params)
        if key == "ifthenelse":
            if len(params) != 3:
                return None
            cond, a, b = params
            if cond is not None and cond is not _BOOL:
                emit("non-bool-logical",
                     "ifThenElse() condition must be BOOL, got "
                     f"{cond.value.upper()}")
            return self._fold_shared_type([a, b])
        if key in ("maximum", "minimum"):
            for t in params:
                if t is not None and t not in NUMERIC_TYPES:
                    emit("non-numeric-math",
                         f"{e.name}() requires numeric arguments, got "
                         f"{t.value.upper()}")
                    return None
            return self._fold_shared_type(params)
        if key == "default":
            return self._fold_shared_type(params)
        if key == "uuid":
            return _STRING
        if key in ("eventtimestamp", "currenttimemillis"):
            return _LONG
        if key.startswith("instanceof"):
            return _BOOL
        if key == "createset":
            return AttrType.OBJECT
        if key == "sizeofset":
            return AttrType.INT
        fd = self.app.function_definitions.get(e.name)
        if fd is not None:
            return fd.return_type
        return None                # unknown/extension: planner's call

    @staticmethod
    def _type_math(key: str, params, emit) -> Optional[AttrType]:
        unary = ("abs", "ceil", "floor", "sqrt", "exp", "ln", "log10",
                 "sin", "cos", "tan", "asin", "acos", "atan", "signum",
                 "round")
        if key in unary and len(params) == 1:
            t = params[0]
            if t is not None and t not in NUMERIC_TYPES:
                emit("non-numeric-math",
                     f"math:{key}() requires a numeric argument, got "
                     f"{t.value.upper()}")
                return None
            return t if key == "abs" else _DOUBLE
        if key == "power" and len(params) == 2:
            return _DOUBLE
        return None

    @staticmethod
    def _fold_shared_type(params) -> Optional[AttrType]:
        """coalesce/default/ifThenElse branch typing: numeric operands
        promote, otherwise all must share a type; unknown poisons."""
        t: Optional[AttrType] = None
        for p in params:
            if p is None:
                return None
            if t is None:
                t = p
            elif p in NUMERIC_TYPES and t in NUMERIC_TYPES:
                t = promote(t, p)
            elif p is not t:
                return None       # runtime raises; arity rules cover it
        return t

    # -- input contexts -------------------------------------------------
    def _chain_schema(self, sin: A.SingleInputStream,
                      base: Optional[Schema]) -> Optional[Schema]:
        """Schema after a stream's handler chain (filters/windows keep
        it; stream functions may rewrite it — log keeps, pol2Cart
        appends, extensions are unknown)."""
        schema = base
        for h in sin.handlers:
            if not isinstance(h, A.StreamFunction):
                continue
            fname = (f"{h.namespace}:{h.name}"
                     if h.namespace else h.name).lower()
            if fname == "log":
                continue
            if fname == "pol2cart" and schema is not None:
                extra = [("cartX", _DOUBLE), ("cartY", _DOUBLE)]
                if len(h.parameters) == 3:
                    extra.append(("cartZ", _DOUBLE))
                schema = Schema(schema.stream_id,
                                schema.attrs + tuple(extra), INFERRED)
            else:
                return None
        return schema

    def _input_schema_for(self, sin: A.SingleInputStream,
                          qi: _QueryInfo) -> Optional[Schema]:
        if sin.is_fault:
            return self.sources.get("!" + sin.stream_id)
        if sin.is_inner:
            if qi.partition_index is None:
                return None
            inner = self._inner_schemas.get(qi.partition_index, {})
            return inner.get("#" + sin.stream_id)
        return self.sources.get(sin.stream_id) \
            or self._implicit.get(sin.stream_id)

    def _pattern_slots(self, st: A.StateInputStream,
                       qi: _QueryInfo) -> list[_Slot]:
        slots = []
        for el in A.iter_state_elements(st.state):
            if isinstance(el, A.StreamStateElement) and el.stream is not None:
                base = self._input_schema_for(el.stream, qi)
                slots.append(_Slot(ref=el.event_ref,
                                   stream_id=el.stream.stream_id,
                                   schema=self._chain_schema(el.stream, base),
                                   stream=el.stream))
        return slots

    # -- per-query output schema (pure: no emission) --------------------
    def _query_out_schema(self, qi: _QueryInfo) -> Optional[Schema]:
        q = qi.query
        target = getattr(q.output, "target", None) or "::return"
        sel = q.selector
        inp = q.input

        if isinstance(inp, A.SingleInputStream):
            schema = self._chain_schema(
                inp, self._input_schema_for(inp, qi))
            if sel.select_all:
                if schema is None:
                    return None
                return Schema(target, schema.attrs, INFERRED, qi.query.line)
            refs = {inp.stream_id}
            if inp.alias:
                refs.add(inp.alias)
            scope = _SingleScope(self, schema, refs)
        elif isinstance(inp, A.JoinInputStream):
            l = self._chain_schema(inp.left,
                                   self._input_schema_for(inp.left, qi))
            r = self._chain_schema(inp.right,
                                   self._input_schema_for(inp.right, qi))
            if sel.select_all:
                if l is None or r is None:
                    return None
                return Schema(target, l.attrs + r.attrs, INFERRED,
                              qi.query.line)
            scope = _JoinScope(
                self, l, inp.left.alias or inp.left.stream_id,
                r, inp.right.alias or inp.right.stream_id)
        elif isinstance(inp, A.StateInputStream):
            slots = self._pattern_slots(inp, qi)
            if sel.select_all:
                # select * flattens (slot, attr, copy); copies only
                # exceed 1 under counting states, which we do not model
                # — mirror the cap==1 flattening (ops/nfa.py NfaEngine)
                if any(isinstance(el, A.CountStateElement)
                       for el in A.iter_state_elements(inp.state)) \
                        or any(s.schema is None for s in slots):
                    return None
                attrs = []
                for s in slots:
                    for n, t in s.schema.attrs:
                        attrs.append((f"{s.ref or s.stream_id}_{n}", t))
                return Schema(target, tuple(attrs), INFERRED,
                              qi.query.line)
            scope = _PatternScope(self, slots)
        else:
            return None            # anonymous inputs: planner rejects

        attrs = []
        for i, oa in enumerate(sel.attributes):
            t = self.type_expr(oa.expression, scope, self._no_emit,
                               agg=True)
            attrs.append((_output_attribute_name(oa, i), t))
        return Schema(target, tuple(attrs), INFERRED, qi.query.line)

    # -- schema fixpoint ------------------------------------------------
    def infer(self) -> None:
        self._implicit: dict[str, Schema] = {}
        self._inner_schemas: dict[int, dict[str, Schema]] = {}
        for _ in range(len(self.infos) + 2):
            changed = False
            inner_next: dict[int, dict[str, Schema]] = {}
            for i, qi in enumerate(self.infos):
                out = self._query_out_schema(qi)
                if out != self.out_schemas[i]:
                    self.out_schemas[i] = out
                    changed = True
                # inner (#) insert targets live per partition, first
                # producer wins (mirrors the planner's ordered map)
                o = qi.query.output
                if qi.partition_index is not None and \
                        isinstance(o, A.InsertIntoStream) and o.is_inner \
                        and out is not None:
                    inner_next.setdefault(qi.partition_index, {}) \
                        .setdefault("#" + o.target, out)
            # app-scope implicit streams: first producer in query order
            implicit_next: dict[str, Schema] = {}
            for target, idxs in self.producers.items():
                if target in self.sources:
                    continue       # explicitly defined: not implicit
                for i in idxs:
                    if self.out_schemas[i] is not None:
                        implicit_next[target] = Schema(
                            target, self.out_schemas[i].attrs, INFERRED,
                            self.infos[i].query.line)
                        break
            if implicit_next != self._implicit or \
                    inner_next != self._inner_schemas:
                changed = True
            self._implicit = implicit_next
            self._inner_schemas = inner_next
            if not changed:
                break

    # -- check pass ------------------------------------------------------
    def check(self) -> None:
        for ei, el in enumerate(self.app.execution_elements):
            if isinstance(el, A.Partition):
                self._check_partition_keys(el, ei)
        for i, qi in enumerate(self.infos):
            self._check_query(qi, self.out_schemas[i])
        self._check_insert_edges()
        self._check_dataflow()
        self._check_float64()

    def _check_partition_keys(self, part: A.Partition, ei: int) -> None:
        emit = self._emitter(None, f"partition{ei + 1}")
        for pt in part.partition_types:
            schema = self.sources.get(pt.stream_id)
            scope = _SingleScope(self, schema, {pt.stream_id})
            if isinstance(pt, A.ValuePartitionType) and \
                    pt.expression is not None:
                self.type_expr(pt.expression, scope, emit)
            elif isinstance(pt, A.RangePartitionType):
                for cond, _label in pt.ranges:
                    t = self.type_expr(cond, scope, emit)
                    if t is not None and t is not _BOOL:
                        emit("non-bool-filter",
                             "partition range condition must be BOOL, "
                             f"got {t.value.upper()}")

    def _check_query(self, qi: _QueryInfo,
                     out_schema: Optional[Schema]) -> None:
        q = qi.query
        emit = self._emitter(qi)
        sel = q.selector
        inp = q.input
        scope = None

        def check_filters(sin: A.SingleInputStream, base: Optional[Schema],
                          fscope, label: str):
            schema = base
            for h in sin.handlers:
                if isinstance(h, A.Filter):
                    t = self.type_expr(h.expression, fscope, emit)
                    if t is not None and t is not _BOOL:
                        emit("non-bool-filter",
                             f"{label} filter condition must be BOOL, "
                             f"got {t.value.upper()}")
                elif isinstance(h, A.StreamFunction):
                    schema = self._chain_schema(
                        A.SingleInputStream(sin.stream_id,
                                            handlers=[h]), schema)
                    if isinstance(fscope, _SingleScope):
                        fscope = _SingleScope(self, schema, fscope.refs)
            return fscope

        if isinstance(inp, A.SingleInputStream):
            base = self._input_schema_for(inp, qi)
            refs = {inp.stream_id}
            if inp.alias:
                refs.add(inp.alias)
            scope = check_filters(
                inp, base, _SingleScope(self, base, refs), "stream")
            scope = _SingleScope(self, self._chain_schema(inp, base),
                                 scope.refs)
        elif isinstance(inp, A.JoinInputStream):
            for sin, label in ((inp.left, "left"), (inp.right, "right")):
                base = self._input_schema_for(sin, qi)
                refs = {sin.stream_id}
                if sin.alias:
                    refs.add(sin.alias)
                check_filters(sin, base, _SingleScope(self, base, refs),
                              label)
            scope = _JoinScope(
                self,
                self._chain_schema(inp.left,
                                   self._input_schema_for(inp.left, qi)),
                inp.left.alias or inp.left.stream_id,
                self._chain_schema(inp.right,
                                   self._input_schema_for(inp.right, qi)),
                inp.right.alias or inp.right.stream_id)
            if inp.on is not None:
                t = self.type_expr(inp.on, scope, emit)
                if t is not None and t is not _BOOL:
                    emit("non-bool-filter",
                         "join ON condition must be BOOL, got "
                         f"{t.value.upper()}")
        elif isinstance(inp, A.StateInputStream):
            slots = self._pattern_slots(inp, qi)
            for j, slot in enumerate(slots):
                sscope = _PatternScope(self, slots, own_slot=j)
                for h in slot.stream.handlers:
                    if isinstance(h, A.Filter):
                        t = self.type_expr(h.expression, sscope, emit)
                        if t is not None and t is not _BOOL:
                            emit("non-bool-filter",
                                 f"pattern condition on "
                                 f"'{slot.ref or slot.stream_id}' must "
                                 f"be BOOL, got {t.value.upper()}")
            scope = _PatternScope(self, slots)
        else:
            return

        if not sel.select_all:
            for oa in sel.attributes:
                self.type_expr(oa.expression, scope, emit, agg=True)
        for g in sel.group_by:
            self.type_expr(g, scope, emit)
        if sel.having is not None:
            hscope = _OutputChainScope(out_schema, scope)
            t = self.type_expr(sel.having, hscope, emit, agg=True)
            if t is not None and t is not _BOOL:
                emit("non-bool-having",
                     f"HAVING must be BOOL, got {t.value.upper()}")
        if out_schema is not None:
            for ob in sel.order_by:
                v = ob.variable
                if v is not None and v.attribute is not None \
                        and not _skippable(v) \
                        and not out_schema.has(v.attribute):
                    emit("undefined-attribute",
                         f"order by '{v.attribute}' is not an output "
                         "attribute")

    # -- insert-into edges ----------------------------------------------
    def _check_insert_edges(self) -> None:
        for target, idxs in self.producers.items():
            decl = self.app.stream_definitions.get(target) \
                or self.app.window_definitions.get(target)
            if target in self.table_ids:
                continue           # store semantics: name-matched upsert
            if decl is not None:
                dschema = schema_from_attribute_defs(
                    target, decl.attributes)
                for i in idxs:
                    self._check_insert_against(self.infos[i],
                                               self.out_schemas[i],
                                               dschema, "stream"
                                               if target in
                                               self.app.stream_definitions
                                               else "window")
            elif target in self.sources:
                # trigger / other builtin-schema target
                dschema = self.sources[target]
                if dschema is not None:
                    for i in idxs:
                        self._check_insert_against(
                            self.infos[i], self.out_schemas[i], dschema,
                            "stream")
            else:
                self._check_implicit_conflicts(target, idxs)
        # inner streams: conflicting producers inside one partition
        for ei, el in enumerate(self.app.execution_elements):
            if not isinstance(el, A.Partition):
                continue
            seen: dict[str, tuple] = {}
            for i, qi in enumerate(self.infos):
                if qi.partition_index != ei:
                    continue
                o = qi.query.output
                if not (isinstance(o, A.InsertIntoStream) and o.is_inner):
                    continue
                out = self.out_schemas[i]
                if out is None or not out.fully_known:
                    continue
                prev = seen.get(o.target)
                if prev is not None and prev != out.types:
                    self._emitter(qi)(
                        "implicit-schema-conflict",
                        f"inner stream '#{o.target}' schema mismatch "
                        "between producers")
                seen.setdefault(o.target, out.types)

    def _check_insert_against(self, qi: _QueryInfo,
                              out: Optional[Schema], decl: Schema,
                              kind: str) -> None:
        if out is None:
            return
        emit = self._emitter(qi)
        if len(out.attrs) != len(decl.attrs):
            emit("insert-arity",
                 f"inserts {len(out.attrs)} attribute(s) into {kind} "
                 f"'{decl.stream_id}' defined with {len(decl.attrs)} "
                 f"{decl.render()}")
            return
        for (name, src), (dname, dst) in zip(out.attrs, decl.attrs):
            compat = insert_compat(src, dst)
            if compat == MISMATCH:
                emit("insert-type",
                     f"output '{name}' is {src.value.upper()} but "
                     f"{kind} '{decl.stream_id}' declares '{dname}' as "
                     f"{dst.value.upper()} (not coercible)")
            elif compat == COERCE:
                emit("insert-coerce",
                     f"output '{name}' is {src.value.upper()}, widened "
                     f"into '{dname}' {dst.value.upper()} of {kind} "
                     f"'{decl.stream_id}' — the runtime rejects "
                     "mismatched insert-into; align the types",
                     WARNING)

    def _check_implicit_conflicts(self, target: str, idxs: list[int]):
        first: Optional[tuple] = None
        first_qi: Optional[_QueryInfo] = None
        for i in idxs:
            out = self.out_schemas[i]
            if out is None or not out.fully_known:
                continue
            if first is None:
                first, first_qi = out.types, self.infos[i]
            elif out.types != first:
                self._emitter(self.infos[i])(
                    "implicit-schema-conflict",
                    f"insert into implicit stream '{target}' with schema "
                    f"{out.render()} conflicts with the schema inferred "
                    f"from query '{first_qi.name}' "
                    f"{self._implicit[target].render()}")

    # -- dead dataflow ---------------------------------------------------
    def _consumed_ids(self) -> set:
        consumed: set = set()
        for qi in self.infos:
            for sin in A.iter_query_inputs(qi.query):
                consumed.add(sin.stream_id)   # fault input implies base
        for el in self.app.execution_elements:
            if isinstance(el, A.Partition):
                for pt in el.partition_types:
                    consumed.add(pt.stream_id)
        for ad in self.app.aggregation_definitions.values():
            if ad.input is not None:
                consumed.add(ad.input.stream_id)
        for sid, sd in self.app.stream_definitions.items():
            if any(a.name.lower() == "sink" for a in sd.annotations):
                consumed.add(sid)
        return consumed

    def _check_dataflow(self) -> None:
        consumed = self._consumed_ids()
        produced = set(self.producers)
        for sid, sd in self.app.stream_definitions.items():
            if sd.is_inner or sd.is_fault:
                continue
            has_source = any(a.name.lower() == "source"
                             for a in sd.annotations)
            if sid not in consumed and sid not in produced \
                    and not has_source:
                self._emitter(None, f"stream {sid}")(
                    "dead-stream",
                    f"defined stream '{sid}' is never consumed or "
                    "produced by any query, partition, aggregation, "
                    "source or sink", WARNING)
        for target, idxs in self.producers.items():
            if target in consumed:
                continue
            decl = self.app.stream_definitions.get(target)
            if decl is not None and any(
                    a.name.lower() == "sink" for a in decl.annotations):
                continue
            if target in self.table_ids or \
                    target in self.app.window_definitions:
                continue           # tables/named windows are stores
            self._emitter(self.infos[idxs[0]])(
                "dead-output",
                f"output stream '{target}' feeds no sink or downstream "
                "query (only host callbacks could observe it)", WARNING)

    # -- float64 hot-path ------------------------------------------------
    def _check_float64(self) -> None:
        consumed = self._consumed_ids()
        for sid, sd in self.app.stream_definitions.items():
            if sid not in consumed:
                continue
            dbl = [a.name for a in sd.attributes
                   if a.type is AttrType.DOUBLE]
            if dbl:
                self._emitter(None, f"stream {sid}")(
                    "float64-hot-path",
                    f"DOUBLE attribute(s) {', '.join(dbl)} of stream "
                    f"'{sid}' enter the jitted hot path as float64 — "
                    "half throughput on TPU; prefer float/long unless "
                    "Java-double parity is required "
                    "(docs/tpu_hygiene.md)", WARNING)
        for target, schema in sorted(self._implicit.items()):
            dbl = [n for n, t in schema.attrs if t is AttrType.DOUBLE]
            if dbl:
                self._emitter(None, f"stream {target}")(
                    "float64-hot-path",
                    f"inferred attribute(s) {', '.join(dbl)} of implicit "
                    f"stream '{target}' are DOUBLE — downstream "
                    "consumers inherit float64 on the hot path "
                    "(docs/tpu_hygiene.md)", WARNING)

    # -- driver ----------------------------------------------------------
    def run(self) -> TypeReport:
        self.infer()
        self.check()
        schemas = {k: v for k, v in self.sources.items() if v is not None}
        schemas.update(self._implicit)
        for ei, inner in self._inner_schemas.items():
            for k, v in inner.items():
                schemas[f"partition{ei}:{k}"] = v
        return TypeReport(issues=self.issues, schemas=schemas)


# ---------------------------------------------------------------------------
# public facade
# ---------------------------------------------------------------------------


def analyze_app(app: A.SiddhiApp) -> TypeReport:
    """Full static type analysis: inferred schemas + all issues."""
    return TypeChecker(app).run()


def check_app(app: A.SiddhiApp) -> None:
    """Parser hook: raise CompileError on error-severity type issues."""
    errors = analyze_app(app).errors
    if errors:
        from ..ops.expr import CompileError
        raise CompileError("; ".join(i.render() for i in errors))


def findings_from_issues(issues, path: str) -> list[Finding]:
    """Adapt TypeIssues (and plan_rules PlanIssues) to the Finding model
    so `tools/lint.py --plan` reuses the baseline/suppression machinery.
    Identity stays line-independent (rule::path::message)."""
    out = []
    for i in issues:
        out.append(Finding(rule=i.code, severity=i.severity, path=path,
                           line=getattr(i, "line", None) or 1, col=0,
                           message=f"{i.where}: {i.message}"))
    return out
