"""Checked-in lint baseline: grandfathered findings.

The baseline maps ``rule::path::message`` -> occurrence count. Keys skip
line numbers on purpose — unrelated edits must not resurrect a
grandfathered finding — so a file can carry N known instances of a
pattern and the linter only fails when an N+1th appears (or a new file
grows one). ``--update-baseline`` rewrites the file from the current
findings; shrinking it over time is the point.
"""
from __future__ import annotations

import collections
import json
from typing import Iterable

from .findings import Finding

VERSION = 1


def load(path: str) -> dict[str, int]:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return {}
    if data.get("version") != VERSION:
        raise ValueError(f"unsupported baseline version in {path}")
    return {k: int(v) for k, v in data.get("findings", {}).items()}


def save(path: str, findings: Iterable[Finding]) -> None:
    counts = collections.Counter(f.baseline_key() for f in findings)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": VERSION,
                   "findings": dict(sorted(counts.items()))},
                  fh, indent=1, sort_keys=False)
        fh.write("\n")


def filter_new(findings: list[Finding],
               baseline: dict[str, int]) -> tuple[list[Finding], int]:
    """Split findings into (new, n_baselined). Within one key, the first
    `count` occurrences (in line order) are considered grandfathered."""
    seen: collections.Counter = collections.Counter()
    fresh: list[Finding] = []
    baselined = 0
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col)):
        key = f.baseline_key()
        if seen[key] < baseline.get(key, 0):
            seen[key] += 1
            baselined += 1
        else:
            fresh.append(f)
    return fresh, baselined


def stale_keys(findings: list[Finding],
               baseline: dict[str, int]) -> list[str]:
    """Baseline entries no longer matched by any finding (prune these)."""
    counts = collections.Counter(f.baseline_key() for f in findings)
    return sorted(k for k, n in baseline.items() if counts[k] < n)
