"""Finding/severity model for the static analyzers.

A Finding anchors to ``path:line:col`` (1-based line, 0-based col, the
Python ``ast`` convention) so editors and CI logs can jump straight to
the offending source. The baseline key deliberately excludes the line
number: grandfathered findings must survive unrelated edits above them,
so identity is (rule, path, message) with an occurrence count.
"""
from __future__ import annotations

import dataclasses

ERROR = "error"
WARNING = "warning"

SEVERITIES = (ERROR, WARNING)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str        # repo-relative where possible
    line: int        # 1-based
    col: int         # 0-based
    message: str

    @property
    def anchor(self) -> str:
        return f"{self.path}:{self.line}"

    def baseline_key(self) -> str:
        return f"{self.rule}::{self.path}::{self.message}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity} [{self.rule}] {self.message}")
