"""Static analysis for the siddhi_tpu codebase and its query plans.

Two independent analyzers live here:

- the **TPU-hygiene linter** (`lint_paths` / `tools/lint.py`): pure
  Python-AST rules enforcing the JAX dispatch/tracing invariants the
  runtime depends on (see docs/tpu_hygiene.md) — no target code is ever
  imported;
- the **query-plan validator** (`validate_app` / `check_app`): semantic
  checks over `lang/ast.py` SiddhiApp plans, invoked by
  `lang.parser.parse` so bad plans fail at compile time.
"""
from .findings import ERROR, WARNING, Finding
from .linter import ModuleContext, lint_file, lint_paths, lint_source
from .registry import all_rules, get_rule, rule_names
from . import jax_rules  # noqa: F401  (registers the TPU/JAX rules)

__all__ = [
    "ERROR", "WARNING", "Finding", "ModuleContext",
    "lint_file", "lint_paths", "lint_source",
    "all_rules", "get_rule", "rule_names",
]
