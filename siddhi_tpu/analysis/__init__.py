"""Static analysis for the siddhi_tpu codebase and its query plans.

Three independent analyzers live here:

- the **TPU-hygiene linter** (`lint_paths` / `tools/lint.py`): pure
  Python-AST rules enforcing the JAX dispatch/tracing invariants the
  runtime depends on (see docs/tpu_hygiene.md) — no target code is ever
  imported. On top of the per-module rules, `lint_project` runs the
  whole-repo **semantic passes**: an approximate call graph with a
  thread-entry map (`callgraph`), lock-discipline + lock-order-cycle
  checks (`concurrency`), use-after-donate dataflow (`donation`), and
  a stale-suppression audit;
- the **query-plan validator** (`plan_rules.validate_app` /
  `check_app`): structural checks over `lang/ast.py` SiddhiApp plans
  (undefined streams, window/aggregator arity, dead states), invoked by
  `lang.parser.parse` so bad plans fail at compile time;
- the **static type checker** (`typecheck.analyze_app` / `check_app`):
  app-wide schema & dtype inference over the query dataflow graph —
  inferred schemas for implicit insert-into streams, expression typing
  mirroring ops/expr.py, insert-into schema compatibility, dead-dataflow
  and float64-hot-path warnings (see docs/typecheck.md). Also invoked
  by `lang.parser.parse`; query `.siddhi` files are checkable from the
  CLI via `tools/lint.py --plan`.
"""
from .findings import ERROR, WARNING, Finding
from .linter import ModuleContext, lint_file, lint_paths, lint_source
from .registry import (all_rules, get_rule, register_meta, rule_names)
from .schema import Schema, aggregator_result_type
from . import jax_rules  # noqa: F401  (registers the TPU/JAX rules)
from .callgraph import ProjectContext, build_project, lint_project
from . import concurrency  # noqa: F401  (registers the project rules)
from . import donation  # noqa: F401  (registers use-after-donate)

# driver-synthesized finding ids — no check function, but SARIF output
# and --list-rules still need their metadata
register_meta(
    "parse-error", ERROR,
    "the source failed to parse; nothing else can be checked")
register_meta(
    "stale-pragma", WARNING,
    "a `# lint: disable=` pragma or baseline entry no longer suppresses "
    "anything — prune it so dead suppressions cannot mask future bugs")

__all__ = [
    "ERROR", "WARNING", "Finding", "ModuleContext",
    "lint_file", "lint_paths", "lint_source",
    "all_rules", "get_rule", "rule_names",
    "Schema", "aggregator_result_type",
    "ProjectContext", "build_project", "lint_project",
]
