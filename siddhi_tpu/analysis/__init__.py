"""Static analysis for the siddhi_tpu codebase and its query plans.

Three independent analyzers live here:

- the **TPU-hygiene linter** (`lint_paths` / `tools/lint.py`): pure
  Python-AST rules enforcing the JAX dispatch/tracing invariants the
  runtime depends on (see docs/tpu_hygiene.md) — no target code is ever
  imported. On top of the per-module rules, `lint_project` runs the
  whole-repo **semantic passes**: an approximate call graph with a
  thread-entry map (`callgraph`), lock-discipline + lock-order-cycle
  checks (`concurrency`), use-after-donate dataflow (`donation`), and
  a stale-suppression audit;
- the **query-plan validator** (`plan_rules.validate_app` /
  `check_app`): structural checks over `lang/ast.py` SiddhiApp plans
  (undefined streams, window/aggregator arity, dead states), invoked by
  `lang.parser.parse` so bad plans fail at compile time;
- the **static type checker** (`typecheck.analyze_app` / `check_app`):
  app-wide schema & dtype inference over the query dataflow graph —
  inferred schemas for implicit insert-into streams, expression typing
  mirroring ops/expr.py, insert-into schema compatibility, dead-dataflow
  and float64-hot-path warnings (see docs/typecheck.md). Also invoked
  by `lang.parser.parse`; query `.siddhi` files are checkable from the
  CLI via `tools/lint.py --plan`.
"""
from .findings import ERROR, WARNING, Finding
from .linter import ModuleContext, lint_file, lint_paths, lint_source
from .registry import (all_rules, get_rule, register_meta, rule_names)
from .schema import Schema, aggregator_result_type
from . import jax_rules  # noqa: F401  (registers the TPU/JAX rules)
from .callgraph import ProjectContext, build_project, lint_project
from . import concurrency  # noqa: F401  (registers the project rules)
from . import donation  # noqa: F401  (registers use-after-donate)

# driver-synthesized finding ids — no check function, but SARIF output
# and --list-rules still need their metadata
register_meta(
    "parse-error", ERROR,
    "the source failed to parse; nothing else can be checked")
register_meta(
    "stale-pragma", WARNING,
    "a `# lint: disable=` pragma or baseline entry no longer suppresses "
    "anything — prune it so dead suppressions cannot mask future bugs")

# compiled-program audit rules (programs.py / tools/audit.py): findings
# are synthesized from the lowered artifact, not a source AST, so they
# register as metadata like the driver ids above
register_meta(
    "program-donation-aliasing", ERROR,
    "a donate_argnums buffer is missing from the lowered program's "
    "input-output alias table — the 'in-place' state update silently "
    "copies on every dispatch")
register_meta(
    "program-host-boundary", ERROR,
    "a pure_callback/io_callback/debug_callback op is baked into a "
    "jitted hot-path program — every chunk round-trips to Python")
register_meta(
    "program-dtype-drift", WARNING,
    "a compiled program emits weak-typed outputs from strongly-typed "
    "inputs — Python-scalar promotion destabilizes jit cache keys and "
    "widens dtypes downstream (docs/compile_cache.md)")
register_meta(
    "program-memory-budget", ERROR,
    "the program set's static live-buffer estimate exceeds the app's "
    "@app:cap(program.mb=) dial")

from .programs import (AuditReport, ProgramAudit, audit_pool,  # noqa: E402
                       audit_runtime, audit_spec, audit_specs)

__all__ = [
    "ERROR", "WARNING", "Finding", "ModuleContext",
    "lint_file", "lint_paths", "lint_source",
    "all_rules", "get_rule", "rule_names",
    "Schema", "aggregator_result_type",
    "ProjectContext", "build_project", "lint_project",
    "AuditReport", "ProgramAudit",
    "audit_spec", "audit_specs", "audit_runtime", "audit_pool",
]
