"""Static analysis for the siddhi_tpu codebase and its query plans.

Three independent analyzers live here:

- the **TPU-hygiene linter** (`lint_paths` / `tools/lint.py`): pure
  Python-AST rules enforcing the JAX dispatch/tracing invariants the
  runtime depends on (see docs/tpu_hygiene.md) — no target code is ever
  imported;
- the **query-plan validator** (`plan_rules.validate_app` /
  `check_app`): structural checks over `lang/ast.py` SiddhiApp plans
  (undefined streams, window/aggregator arity, dead states), invoked by
  `lang.parser.parse` so bad plans fail at compile time;
- the **static type checker** (`typecheck.analyze_app` / `check_app`):
  app-wide schema & dtype inference over the query dataflow graph —
  inferred schemas for implicit insert-into streams, expression typing
  mirroring ops/expr.py, insert-into schema compatibility, dead-dataflow
  and float64-hot-path warnings (see docs/typecheck.md). Also invoked
  by `lang.parser.parse`; query `.siddhi` files are checkable from the
  CLI via `tools/lint.py --plan`.
"""
from .findings import ERROR, WARNING, Finding
from .linter import ModuleContext, lint_file, lint_paths, lint_source
from .registry import all_rules, get_rule, rule_names
from .schema import Schema, aggregator_result_type
from . import jax_rules  # noqa: F401  (registers the TPU/JAX rules)

__all__ = [
    "ERROR", "WARNING", "Finding", "ModuleContext",
    "lint_file", "lint_paths", "lint_source",
    "all_rules", "get_rule", "rule_names",
    "Schema", "aggregator_result_type",
]
