"""Command-line driver behind tools/audit.py — the compiled-program
auditor (analysis/programs.py, docs/tpu_hygiene.md "Compiled-program
audit").

Where tools/lint.py verifies Python *source* and ``--plan`` verifies
the query AST, this driver verifies what XLA would actually *compile*:
it parses each SiddhiQL app, enumerates every step program the runtime
can dispatch, lowers each with abstract arguments (zero executions,
zero device work, zero new compiles) and checks donation aliasing,
host-boundary callbacks, dtype drift and the ``@app:cap(program.mb=)``
memory budget.

Inputs:

- default (no paths): the curated repo suite ``tools/audit_suite/``;
- explicit ``.siddhi`` files or directories (``--app f.siddhi`` is an
  alias for a single positional path); template sources (``${...}``)
  audit through a real TenantPool — bind structural parameters with
  repeatable ``--bind name=value``;
- explicit ``.py`` fixture modules exposing ``specs() -> list`` (and
  optionally ``BUDGET_MB``) — how tests/lint_fixtures seed the four
  hazard shapes;
- ``--corpus``: sweep the reference corpus (tests/ref_corpus/*.json),
  deduplicated by structural app class so the ~400 extracted cases
  audit as ~200 distinct plans;
- ``--changed``: only git-modified/untracked ``.siddhi`` files under
  ``--root`` (renames followed, like the linter).

File-scope suppression inside ``.siddhi`` sources uses the linter's
pragma: ``-- lint: disable=program-dtype-drift``. Findings flow through
the shared baseline machinery (``tools/audit_baseline.json`` ships
EMPTY and must stay empty) and ``--sarif`` emits SARIF 2.1.0 for
code-scanning UIs. Exit codes: 0 clean (or baselined), 1 any fresh
finding or stale baseline entry, 2 usage/configuration error.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import pathlib
import re
import subprocess
import sys
from typing import Optional

from . import baseline as baseline_mod
from .cli import _SIDDHI_PRAGMA, iter_siddhi_files
from .findings import Finding, ERROR
from .programs import PROGRAM_RULES, audit_specs

# one app text per structural class: literals collapse so the corpus's
# hundreds of near-identical extracted cases audit once per distinct
# plan shape (the PR 16 sweep discipline)
_LITERAL_RE = re.compile(r"('[^']*'|\b\d+(\.\d+)?\b)")


def struct_class(app_text: str) -> str:
    return _LITERAL_RE.sub("#", app_text)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="audit.py",
        description="static compiled-program auditor for SiddhiQL apps")
    p.add_argument("paths", nargs="*", default=None,
                   help=".siddhi files/directories or .py fixture "
                        "modules (default: the tools/audit_suite/ repo "
                        "program set)")
    p.add_argument("--app", default=None, metavar="FILE",
                   help="audit one .siddhi app (alias for a positional "
                        "path)")
    p.add_argument("--corpus", action="store_true",
                   help="sweep the reference corpus "
                        "(tests/ref_corpus/*.json), struct-deduplicated")
    p.add_argument("--changed", action="store_true",
                   help="audit only git-modified/untracked .siddhi "
                        "files under --root")
    p.add_argument("--bind", action="append", default=None,
                   metavar="NAME=VALUE",
                   help="bind a template's structural ${NAME} "
                        "placeholder (repeatable); template sources "
                        "audit through a TenantPool")
    p.add_argument("--buckets", default=None,
                   help="comma-separated ingest buckets to enumerate "
                        "programs for (default: SIDDHI_TPU_WARM_BUCKETS "
                        "else 1024)")
    p.add_argument("--budget-mb", type=float, default=None,
                   help="memory budget override (else the app's "
                        "@app:cap(program.mb=) dial)")
    p.add_argument("--root", default=None,
                   help="directory findings paths are made relative to "
                        "(default: cwd)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON of grandfathered findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline; report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write the new findings as SARIF 2.1.0")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="write per-app audit summaries as JSON ('-' for "
                        "stdout)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the program-audit rule table and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def _pragma_disabled(text: str) -> set:
    disabled: set = set()
    for m in _SIDDHI_PRAGMA.finditer(text):
        disabled |= {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
    return disabled


def audit_app_text(text: str, rel: str, *, buckets=None,
                   budget_mb=None, bind=None):
    """Audit one SiddhiQL source: plain apps through an (unstarted)
    SiddhiAppRuntime, templates through a real TenantPool so the
    vmapped tenant-axis programs are the audited artifact. Returns an
    AuditReport, or a parse/instantiation failure as a synthesized
    ERROR finding inside one."""
    from .programs import AuditReport, audit_pool, audit_runtime
    disabled = _pragma_disabled(text)
    try:
        if "${" in text:
            from ..serving.pool import TenantPool
            from ..serving.template import Template
            tpl = Template(text, name=f"audit_{abs(hash(rel)) & 0xffff}")
            pool = TenantPool(tpl, shared=dict(bind or {}))
            return audit_pool(pool, path=rel, budget_mb=budget_mb,
                              disabled=disabled, store=False)
        from ..core.manager import SiddhiManager
        rt = SiddhiManager().create_siddhi_app_runtime(text)
        return audit_runtime(rt, buckets=buckets, path=rel,
                             budget_mb=budget_mb, disabled=disabled,
                             store=False)
    except Exception as e:  # noqa: BLE001 — an unbuildable app is the
        # audit verdict for that file, not a driver crash
        rep = AuditReport(rel, [], disabled=disabled)
        rep.findings.append(Finding(
            rule="parse-error", severity=ERROR, path=rel, line=1, col=0,
            message=f"{type(e).__name__}: {e}"))
        return rep


def audit_fixture_module(path: str, rel: str, *, budget_mb=None):
    """Audit a .py fixture exposing ``specs() -> list[CompileSpec]``
    (and optionally ``BUDGET_MB``) — the hook tests/lint_fixtures uses
    to seed doctored programs without a SiddhiQL surface for them."""
    name = f"_audit_fixture_{pathlib.Path(path).stem}"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if budget_mb is None:
        budget_mb = getattr(mod, "BUDGET_MB", None)
    return audit_specs(mod.specs(), path=rel, budget_mb=budget_mb)


def corpus_reports(corpus_dir: str, *, buckets=None, budget_mb=None,
                   progress=None) -> list:
    """Struct-deduplicated audit of every compilable corpus app."""
    from ..lang.tokens import SiddhiParserException
    from ..ops.expr import CompileError
    reports, seen = [], set()
    for f in sorted(pathlib.Path(corpus_dir).glob("*.json")):
        for i, case in enumerate(json.loads(f.read_text())["cases"]):
            if case.get("expect_error"):
                continue
            text = "@app:playback " + case["app"]
            cls = struct_class(text)
            if cls in seen:
                continue
            seen.add(cls)
            rel = f"{f.stem}#{i}"
            try:
                rep = audit_app_text(text, rel, buckets=buckets,
                                     budget_mb=budget_mb)
            except (CompileError, SiddhiParserException):
                continue
            # apps the runtime itself refuses are out of audit scope
            # (the sweep contract: every COMPILABLE app audits clean)
            rep.findings = [x for x in rep.findings
                            if x.rule != "parse-error"]
            reports.append(rep)
            if progress:
                progress(len(reports), rel)
    return reports


def changed_siddhi_files(root: str) -> Optional[list[str]]:
    """Git-modified (vs HEAD, renames followed) + untracked .siddhi
    files under `root`; None when git is unavailable."""
    files: set[str] = set()
    try:
        res = subprocess.run(
            ["git", "-C", root, "diff", "--name-status", "-M",
             "HEAD", "--"],
            capture_output=True, text=True, check=True)
    except (OSError, subprocess.CalledProcessError):
        return None
    for line in res.stdout.splitlines():
        parts = line.split("\t")
        if len(parts) < 2 or not parts[0] or parts[0][0] == "D":
            continue
        files.add(parts[2] if parts[0][0] in "RC" and len(parts) > 2
                  else parts[1])
    try:
        res = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True)
    except (OSError, subprocess.CalledProcessError):
        return None
    files.update(x.strip() for x in res.stdout.splitlines()
                 if x.strip())
    return [os.path.join(root, f) for f in sorted(files)
            if f.endswith(".siddhi") and "lint_fixtures" not in f
            and os.path.exists(os.path.join(root, f))]


def main(argv: Optional[list[str]] = None, stdout=None) -> int:
    out = stdout or sys.stdout
    args = build_parser().parse_args(argv)

    if args.list_rules:
        from .registry import get_rule
        for name in PROGRAM_RULES:
            r = get_rule(name)
            print(f"{r.name:28} {r.severity:8} {r.rationale}", file=out)
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    buckets = None
    if args.buckets:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    bind = {}
    for b in args.bind or ():
        if "=" not in b:
            print(f"--bind expects NAME=VALUE, got {b!r}", file=out)
            return 2
        k, _, v = b.partition("=")
        bind[k.strip()] = v.strip()

    paths = list(args.paths or ())
    if args.app:
        paths.append(args.app)
    if args.changed:
        changed = changed_siddhi_files(root)
        if changed is None:
            print("--changed requires a git checkout at --root",
                  file=out)
            return 2
        if not changed and not paths and not args.corpus:
            if not args.quiet:
                print("no changed .siddhi files; nothing to audit",
                      file=out)
            return 0
        paths += changed
    if not paths and not args.corpus:
        suite = os.path.join(root, "tools", "audit_suite")
        if not os.path.isdir(suite):
            print(f"no default program suite at {suite} — pass paths, "
                  f"--app, --corpus or --changed", file=out)
            return 2
        paths = [suite]

    reports = []
    for p in paths:
        if p.endswith(".py"):
            rel = os.path.relpath(os.path.abspath(p), root) \
                .replace(os.sep, "/")
            reports.append(audit_fixture_module(
                p, rel, budget_mb=args.budget_mb))
            continue
        for f in iter_siddhi_files([p]):
            rel = os.path.relpath(os.path.abspath(f), root) \
                .replace(os.sep, "/")
            with open(f, encoding="utf-8") as fh:
                text = fh.read()
            reports.append(audit_app_text(
                text, rel, buckets=buckets, budget_mb=args.budget_mb,
                bind=bind))
    if args.corpus:
        corpus = os.path.join(root, "tests", "ref_corpus")
        if not os.path.isdir(corpus):
            print(f"no reference corpus at {corpus}", file=out)
            return 2
        reports += corpus_reports(corpus, buckets=buckets,
                                  budget_mb=args.budget_mb)

    findings = [f for rep in reports for f in rep.findings]

    if args.update_baseline:
        if not args.baseline:
            print("--update-baseline requires --baseline PATH", file=out)
            return 2
        baseline_mod.save(args.baseline, findings)
        if not args.quiet:
            print(f"baseline updated: {len(findings)} finding(s) -> "
                  f"{args.baseline}", file=out)
        return 0

    bl = {}
    if args.baseline and not args.no_baseline:
        try:
            bl = baseline_mod.load(args.baseline)
        except ValueError as e:
            print(str(e), file=out)
            return 2
    fresh, n_baselined = baseline_mod.filter_new(findings, bl)
    stale = baseline_mod.stale_keys(findings, bl)
    if stale:
        bl_rel = os.path.relpath(os.path.abspath(args.baseline), root) \
            .replace(os.sep, "/")
        for k in stale:
            from .findings import WARNING
            fresh.append(Finding(
                rule="stale-pragma", severity=WARNING, path=bl_rel,
                line=1, col=0,
                message=("baseline entry no longer matches any finding "
                         f"— prune it: {k}")))

    for f in fresh:
        print(f.render(), file=out)
    if args.sarif:
        from .sarif import write_sarif
        write_sarif(args.sarif, fresh, root_uri=root)
        if not args.quiet:
            print(f"sarif written: {args.sarif} "
                  f"({len(fresh)} result(s))", file=out)
    if args.json:
        doc = {
            "programs": sum(len(r.programs) for r in reports),
            "apps": [{"path": r.path, **r.summary()} for r in reports],
            "findings": len(fresh),
        }
        if args.json == "-":
            json.dump(doc, out, indent=1, sort_keys=True)
            out.write("\n")
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")
    if not args.quiet:
        n_prog = sum(len(r.programs) for r in reports)
        print(f"{len(reports)} app(s), {n_prog} program(s) audited: "
              f"{len(fresh)} new finding(s), {n_baselined} baselined, "
              f"{len(stale)} stale baseline entr(ies)", file=out)
    return 1 if fresh else 0
