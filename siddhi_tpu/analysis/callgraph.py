"""Whole-repo approximate call graph + thread-entry map.

The semantic passes (concurrency.py lock-discipline / lock-order,
donation.py) need to know two things the per-module AST rules cannot
see: *who calls whom across modules*, and *which functions run on a
thread other than the caller's* (drain workers, checkpoint supervisors,
scheduler callbacks, metric reporters, HTTP handlers, AOT warmers).

``ProjectContext`` parses every target module once (reusing the
linter's ``ModuleContext``) and builds:

- a function index keyed by qualified name
  (``siddhi_tpu/core/stats.py`` -> ``siddhi_tpu.core.stats`` ->
  ``siddhi_tpu.core.stats.LatencyTracker.mark_out``);
- an **approximate** call graph. Resolution is deliberately
  conservative — precision over recall, because findings built on it
  gate CI: ``self.m()`` / ``cls.m()`` resolve through the class (and
  name-matched project bases), bare names resolve to module/nested
  functions and imports (relative imports included), and attribute
  calls resolve only when the receiver's type is knowable from a
  constructor assignment (``self.x = ClassName(...)``), a parameter
  annotation (``def f(t: "Tracker")``), or a local ``v = ClassName()``;
- a **thread-entry map**: functions handed to ``threading.Thread
  (target=...)``, ``executor.submit``, ``atexit.register``, scheduler
  ``notify_at`` callbacks, metrics ``register_collector`` /
  ``set_fn`` collectors (they run on reporter/scrape threads),
  ``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses
  (``ThreadingHTTPServer`` spawns a thread per request), plus anything
  carrying a ``# thread-entry`` comment on its ``def`` line;
- the transitive closure ``reachable``: every function reachable from a
  thread entry over the call graph — the set on which lock-free reads
  of guarded state become findings.
"""
from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Optional

from .findings import ERROR, WARNING, Finding
from .linter import ModuleContext, iter_python_files

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# attribute-call names whose callable argument runs on another thread
# (argument index -> reason). Kept small and explicit: this is the
# "registry of known entry points" — extend it when a new callback
# surface appears, don't guess.
CALLBACK_REGISTRARS = {
    "submit": (0, "executor.submit target"),
    "register_collector": (0, "metrics collector (reporter/scrape thread)"),
    "set_fn": (0, "gauge callable (evaluated at collection time)"),
    "add_done_callback": (0, "future callback"),
    "notify_at": (1, "scheduler timer callback"),
}

HTTP_HANDLER_BASES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler"}

# extra dotted qnames (exact match) forced to be thread entries; the
# annotation form (`# thread-entry: <why>` on the def line) is
# preferred because it lives next to the code it describes.
KNOWN_ENTRY_QNAMES: set[str] = set()

THREAD_ENTRY_MARK = "# thread-entry"


@dataclasses.dataclass
class FunctionInfo:
    qname: str
    name: str
    path: str                      # repo-relative module path
    ctx: ModuleContext
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    cls: Optional["ClassInfo"]     # owning class, if a method
    parent_fn: Optional[str] = None  # enclosing function qname (nested defs)


@dataclasses.dataclass
class ClassInfo:
    qname: str
    name: str
    path: str
    ctx: ModuleContext
    node: ast.ClassDef
    bases: list[str]                          # last dotted component
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    attr_types: dict[str, set[str]] = dataclasses.field(default_factory=dict)


def _last_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.rsplit(".", 1)[-1]
    if isinstance(node, ast.Subscript):        # Optional["X"] and friends
        return None
    return None


def _ann_class_name(ann: Optional[ast.AST]) -> Optional[str]:
    """Class name out of a parameter/attribute annotation: ``Tracker``,
    ``"Tracker"``, ``mod.Tracker``; generics/Optional are skipped."""
    if ann is None:
        return None
    return _last_name(ann)


def walk_body(node: ast.AST):
    """ast.walk over a function body that does NOT descend into nested
    function/class definitions (they are separate graph nodes); lambdas
    ARE descended (they execute as part of the enclosing expression
    flow often enough — sort keys — and when they don't, the
    thread-entry scan handles them explicitly)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, _FUNC_NODES + (ast.ClassDef,)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class ProjectContext:
    """Cross-module facts for the semantic passes."""

    def __init__(self, modules: dict[str, ModuleContext],
                 errors: Optional[list[Finding]] = None):
        self.modules = modules
        self.errors = errors or []
        self.functions: dict[str, FunctionInfo] = {}
        self._fn_by_node: dict[tuple[str, int], FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.class_by_name: dict[str, list[ClassInfo]] = {}
        self._ext_alias: dict[str, dict[str, tuple[str, ...]]] = {}
        self._mod_name: dict[str, tuple[str, ...]] = {}
        self._mod_by_name: dict[tuple[str, ...], str] = {}
        self._local_types: dict[str, dict[str, set[str]]] = {}
        self.call_edges: dict[str, set[str]] = {}
        self.thread_entries: dict[str, str] = {}
        self.reachable: set[str] = set()
        self._index()
        self._infer_attr_types()
        self._build_call_edges()
        self._find_thread_entries()
        self._compute_reachable()

    # -- indexing -----------------------------------------------------
    @staticmethod
    def module_name(rel_path: str) -> tuple[str, ...]:
        parts = rel_path.replace(os.sep, "/").split("/")
        parts[-1] = parts[-1][:-3] if parts[-1].endswith(".py") \
            else parts[-1]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return tuple(parts)

    def _index(self) -> None:
        for path, ctx in self.modules.items():
            mod = self.module_name(path)
            self._mod_name[path] = mod
            self._mod_by_name[mod] = path
            self._ext_alias[path] = self._extend_aliases(ctx, mod)
            self._index_scope(ctx, path, ctx.tree.body,
                              ".".join(mod), cls=None, parent_fn=None)

    def _index_scope(self, ctx, path, body, prefix, cls, parent_fn):
        for node in body:
            if isinstance(node, _FUNC_NODES):
                q = f"{prefix}.{node.name}"
                info = FunctionInfo(qname=q, name=node.name, path=path,
                                    ctx=ctx, node=node, cls=cls,
                                    parent_fn=parent_fn)
                self.functions[q] = info
                self._fn_by_node[(path, id(node))] = info
                if cls is not None and parent_fn is None:
                    cls.methods.setdefault(node.name, info)
                self._index_scope(ctx, path, node.body, q, cls=cls,
                                  parent_fn=q)
            elif isinstance(node, ast.ClassDef):
                q = f"{prefix}.{node.name}"
                ci = ClassInfo(
                    qname=q, name=node.name, path=path, ctx=ctx,
                    node=node,
                    bases=[b for b in (_last_name(x) for x in node.bases)
                           if b])
                self.classes[q] = ci
                self.class_by_name.setdefault(node.name, []).append(ci)
                self._index_scope(ctx, path, node.body, q, cls=ci,
                                  parent_fn=None)
            elif isinstance(node, (ast.If, ast.Try, ast.With)):
                # module-level guards (if TYPE_CHECKING, try/except import)
                inner = []
                for field in ("body", "orelse", "finalbody"):
                    inner.extend(getattr(node, field, []) or [])
                for h in getattr(node, "handlers", []) or []:
                    inner.extend(h.body)
                self._index_scope(ctx, path, inner, prefix, cls, parent_fn)

    def _extend_aliases(self, ctx: ModuleContext,
                        mod: tuple[str, ...]) -> dict[str, tuple[str, ...]]:
        """ctx.alias_map plus *relative* imports resolved against this
        module's package (the linter skips them; cross-module
        resolution needs them — they are the repo's normal idiom)."""
        amap = dict(ctx.alias_map)
        pkg = mod[:-1] if mod else ()
        for node in ctx.nodes:
            if isinstance(node, ast.ImportFrom) and node.level > 0:
                base = pkg[: len(pkg) - (node.level - 1)] \
                    if node.level <= len(pkg) + 1 else ()
                if node.module:
                    base = base + tuple(node.module.split("."))
                for a in node.names:
                    amap[a.asname or a.name] = base + (a.name,)
        return amap

    def canon(self, path: str, node: ast.AST) -> Optional[tuple[str, ...]]:
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        d = tuple(reversed(parts))
        head = self._ext_alias.get(path, {}).get(d[0])
        return head + d[1:] if head else d

    # -- type inference ------------------------------------------------
    def _classes_named(self, name: Optional[str]) -> list[ClassInfo]:
        return self.class_by_name.get(name, []) if name else []

    def _value_class(self, path: str, fn: Optional[FunctionInfo],
                     value: ast.AST) -> set[str]:
        """Class qnames a RHS expression constructs/carries."""
        out: set[str] = set()
        if isinstance(value, ast.Call):
            nm = _last_name(value.func)
            for ci in self._classes_named(nm):
                out.add(ci.qname)
        elif isinstance(value, ast.Name) and fn is not None:
            ptypes = self._param_types(fn)
            out |= ptypes.get(value.id, set())
        elif isinstance(value, (ast.IfExp, ast.BoolOp)):
            for sub in ast.iter_child_nodes(value):
                out |= self._value_class(path, fn, sub)
        return out

    def _param_types(self, fn: FunctionInfo) -> dict[str, set[str]]:
        args = fn.node.args
        out: dict[str, set[str]] = {}
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            nm = _ann_class_name(a.annotation)
            cands = {ci.qname for ci in self._classes_named(nm)}
            if cands:
                out[a.arg] = cands
        return out

    def _infer_attr_types(self) -> None:
        for fn in self.functions.values():
            if fn.cls is None:
                continue
            for node in walk_body(fn.node):
                tgt = None
                val = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    tgt, val = node.target, node.value
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                cands: set[str] = set()
                if isinstance(node, ast.AnnAssign):
                    nm = _ann_class_name(node.annotation)
                    cands |= {ci.qname for ci in self._classes_named(nm)}
                if val is not None:
                    cands |= self._value_class(fn.path, fn, val)
                if cands:
                    fn.cls.attr_types.setdefault(tgt.attr, set()) \
                        .update(cands)

    def _fn_local_types(self, fn: FunctionInfo) -> dict[str, set[str]]:
        cached = self._local_types.get(fn.qname)
        if cached is not None:
            return cached
        out = dict(self._param_types(fn))
        for node in walk_body(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                cands = self._value_class(fn.path, fn, node.value)
                if cands:
                    out.setdefault(node.targets[0].id, set()).update(cands)
        self._local_types[fn.qname] = out
        return out

    # -- method lookup -------------------------------------------------
    def method_in_class(self, ci: ClassInfo, name: str,
                        _seen: Optional[set] = None) -> list[FunctionInfo]:
        _seen = _seen if _seen is not None else set()
        if ci.qname in _seen:
            return []
        _seen.add(ci.qname)
        m = ci.methods.get(name)
        if m is not None:
            return [m]
        out: list[FunctionInfo] = []
        for b in ci.bases:
            for base_ci in self._classes_named(b):
                out.extend(self.method_in_class(base_ci, name, _seen))
        return out

    # -- call resolution -----------------------------------------------
    def resolve_callable_ref(self, fn: Optional[FunctionInfo], path: str,
                             expr: ast.AST) -> list[str]:
        """Resolve an expression used as a *callable value* (a Thread
        target, a registered callback) to function qnames."""
        if isinstance(expr, ast.Lambda):
            out: list[str] = []
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Call):
                    out.extend(self.resolve_call(fn, path, sub))
            return out
        if isinstance(expr, ast.Call):          # functools.partial(f, ...)
            nm = _last_name(expr.func)
            if nm == "partial" and expr.args:
                return self.resolve_callable_ref(fn, path, expr.args[0])
            return []
        return self._resolve_func_expr(fn, path, expr)

    def _resolve_func_expr(self, fn: Optional[FunctionInfo], path: str,
                           expr: ast.AST) -> list[str]:
        mod = ".".join(self._mod_name.get(path, ()))
        if isinstance(expr, ast.Name):
            # nested function of an enclosing def
            if fn is not None:
                scope: Optional[str] = fn.qname
                while scope:
                    q = f"{scope}.{expr.id}"
                    if q in self.functions:
                        return [q]
                    info = self.functions.get(scope)
                    scope = info.parent_fn if info else None
            q = f"{mod}.{expr.id}"
            if q in self.functions:
                return [q]
            # constructor: Class() -> Class.__init__ (or the class itself
            # as a callable unit when no __init__ is defined)
            for ci in self._classes_named(expr.id):
                init = self.method_in_class(ci, "__init__")
                if init:
                    return [init[0].qname]
            c = self.canon(path, expr)
            if c:
                return self._resolve_canon(c)
            return []
        if isinstance(expr, ast.Attribute):
            base = expr.value
            meth = expr.attr
            # self.m / cls.m
            if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and fn is not None and fn.cls is not None:
                return [m.qname
                        for m in self.method_in_class(fn.cls, meth)]
            # local var / param with a known class
            if isinstance(base, ast.Name) and fn is not None:
                cands = self._fn_local_types(fn).get(base.id, set())
                out = []
                for cq in cands:
                    ci = self.classes.get(cq)
                    if ci:
                        out.extend(m.qname
                                   for m in self.method_in_class(ci, meth))
                if out:
                    return out
                # ClassName.method
                for ci in self._classes_named(base.id):
                    out.extend(m.qname
                               for m in self.method_in_class(ci, meth))
                if out:
                    return out
            # self.attr.m through an inferred attribute type
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id in ("self", "cls") \
                    and fn is not None and fn.cls is not None:
                out = []
                for cq in fn.cls.attr_types.get(base.attr, set()):
                    ci = self.classes.get(cq)
                    if ci:
                        out.extend(m.qname
                                   for m in self.method_in_class(ci, meth))
                return out
            # module.func through (possibly relative) imports
            c = self.canon(path, expr)
            if c:
                return self._resolve_canon(c)
        return []

    def _resolve_canon(self, c: tuple[str, ...]) -> list[str]:
        # longest module prefix match, remainder resolves inside it
        for cut in range(len(c) - 1, 0, -1):
            if c[:cut] in self._mod_by_name:
                q = ".".join(c)
                if q in self.functions:
                    return [q]
                # module.Class -> constructor
                for ci in self._classes_named(c[-1]):
                    if ci.qname == q:
                        init = self.method_in_class(ci, "__init__")
                        return [init[0].qname] if init else []
                return []
        return []

    def resolve_call(self, fn: Optional[FunctionInfo], path: str,
                     call: ast.Call) -> list[str]:
        return self._resolve_func_expr(fn, path, call.func)

    # -- call graph ------------------------------------------------------
    def _build_call_edges(self) -> None:
        for fn in self.functions.values():
            edges: set[str] = set()
            for node in walk_body(fn.node):
                if isinstance(node, ast.Call):
                    edges.update(self.resolve_call(fn, fn.path, node))
            self.call_edges[fn.qname] = edges

    # -- thread entries --------------------------------------------------
    def _mark_entry(self, qnames: Iterable[str], reason: str) -> None:
        for q in qnames:
            self.thread_entries.setdefault(q, reason)

    def _find_thread_entries(self) -> None:
        for fn in self.functions.values():
            # `# thread-entry` annotation on the def line
            line = fn.ctx.lines[fn.node.lineno - 1] \
                if fn.node.lineno - 1 < len(fn.ctx.lines) else ""
            if THREAD_ENTRY_MARK in line:
                self._mark_entry([fn.qname], "thread-entry annotation")
            if fn.qname in KNOWN_ENTRY_QNAMES:
                self._mark_entry([fn.qname], "known entry registry")
        # http.server handlers: one thread per request
        for ci in self.classes.values():
            if self._is_http_handler(ci):
                self._mark_entry(
                    (m.qname for name, m in ci.methods.items()
                     if name.startswith("do_")),
                    "HTTP request handler")
        # call-shaped registrations
        for fn in list(self.functions.values()) + [None]:
            if fn is None:
                scopes = [(path, None, ctx.tree)
                          for path, ctx in self.modules.items()]
            else:
                scopes = [(fn.path, fn, fn.node)]
            for path, owner, root in scopes:
                it = walk_body(root) if owner is not None else (
                    n for n in ast.walk(root)
                    if not isinstance(n, _FUNC_NODES))
                for node in it:
                    if isinstance(node, ast.Call):
                        self._scan_entry_call(owner, path, node)

    def _is_http_handler(self, ci: ClassInfo,
                         _seen: Optional[set] = None) -> bool:
        _seen = _seen if _seen is not None else set()
        if ci.qname in _seen:
            return False
        _seen.add(ci.qname)
        for b in ci.bases:
            if b in HTTP_HANDLER_BASES:
                return True
            for base_ci in self._classes_named(b):
                if self._is_http_handler(base_ci, _seen):
                    return True
        return False

    def _scan_entry_call(self, fn: Optional[FunctionInfo], path: str,
                         call: ast.Call) -> None:
        c = self.canon(path, call.func)
        if c and c[0] == "threading" and c[-1] == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    self._mark_entry(
                        self.resolve_callable_ref(fn, path, kw.value),
                        "threading.Thread target")
            return
        if c == ("atexit", "register") and call.args:
            self._mark_entry(self.resolve_callable_ref(fn, path,
                                                       call.args[0]),
                             "atexit callback")
            return
        if isinstance(call.func, ast.Attribute):
            spec = CALLBACK_REGISTRARS.get(call.func.attr)
            if spec is not None:
                idx, reason = spec
                if len(call.args) > idx:
                    self._mark_entry(
                        self.resolve_callable_ref(fn, path, call.args[idx]),
                        reason)

    # -- reachability ----------------------------------------------------
    def _compute_reachable(self) -> None:
        seen = set(self.thread_entries)
        stack = list(seen)
        while stack:
            q = stack.pop()
            for callee in self.call_edges.get(q, ()):
                if callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
        self.reachable = seen

    def function_of_node(self, path: str, node: ast.AST) \
            -> Optional[FunctionInfo]:
        ctx = self.modules.get(path)
        if ctx is None:
            return None
        fn_node = ctx.enclosing_function(node)
        if fn_node is None:
            return None
        return self._fn_by_node.get((path, id(fn_node)))


def stale_pragma_findings(pctx: ProjectContext) -> list[Finding]:
    """`# lint: disable=` pragmas that suppressed nothing across ALL
    passes (module rules + semantic passes) — dead suppressions rot
    into false confidence; prune them. A pragma naming `stale-pragma`
    itself is exempt (explicit keep)."""
    out: list[Finding] = []
    for path in sorted(pctx.modules):
        ctx = pctx.modules[path]
        for line in sorted(ctx.line_disables):
            rules = ctx.line_disables[line]
            if "stale-pragma" in rules:
                continue
            for r in sorted(rules):
                used = (any(ln == line for ln, _ in ctx.used_line)
                        if r == "*" else (line, r) in ctx.used_line)
                if not used:
                    out.append(Finding(
                        rule="stale-pragma", severity=WARNING, path=path,
                        line=line, col=0,
                        message=(f"pragma 'lint: disable={r}' no longer "
                                 f"suppresses any finding — prune it")))
        if "stale-pragma" not in ctx.file_disables:
            for r in sorted(ctx.file_disables):
                used = (bool(ctx.used_file)
                        if r == "*" else r in ctx.used_file)
                if not used:
                    out.append(Finding(
                        rule="stale-pragma", severity=WARNING, path=path,
                        line=1, col=0,
                        message=(f"pragma 'lint: disable-file={r}' no "
                                 f"longer suppresses any finding — "
                                 f"prune it")))
    return [f for f in out
            if not pctx.modules[f.path].suppressed(f)]


def lint_project(paths: Iterable[str], root: Optional[str] = None,
                 rules: Optional[Iterable[str]] = None,
                 semantic: bool = True,
                 audit_suppressions: bool = True) -> list[Finding]:
    """Whole-repo lint: per-module TPU-hygiene rules + the semantic
    passes (lock-discipline, lock-order, use-after-donate reachability)
    over one shared parse, plus the stale-pragma audit (only on full
    runs — a `--rule`-filtered run can't tell a stale pragma from a
    not-yet-checked one, and a `--changed` subset lacks the cross-module
    evidence that makes a pragma earn its keep)."""
    from .registry import module_rules, project_rules
    from . import concurrency, donation  # noqa: F401 — register rules

    pctx = build_project(paths, root)
    wanted = set(rules) if rules is not None else None
    out: list[Finding] = list(pctx.errors)
    for rel in sorted(pctx.modules):
        ctx = pctx.modules[rel]
        for rule in module_rules():
            if wanted is not None and rule.name not in wanted:
                continue
            for f in rule.check(ctx):
                if not ctx.suppressed(f):
                    out.append(f)
    if semantic:
        for rule in project_rules():
            if wanted is not None and rule.name not in wanted:
                continue
            for f in rule.check(pctx):
                mctx = pctx.modules.get(f.path)
                if mctx is None or not mctx.suppressed(f):
                    out.append(f)
        if wanted is None and audit_suppressions:
            out.extend(stale_pragma_findings(pctx))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def build_project(paths: Iterable[str],
                  root: Optional[str] = None) -> ProjectContext:
    """Parse every .py file under `paths` into one ProjectContext.
    Unparseable files become parse-error findings (ERROR) and are
    excluded from the graph."""
    base = os.path.abspath(root or os.getcwd())
    modules: dict[str, ModuleContext] = {}
    errors: list[Finding] = []
    for p in iter_python_files(paths):
        ap = os.path.abspath(p)
        rel = os.path.relpath(ap, base).replace(os.sep, "/")
        with open(ap, encoding="utf-8") as fh:
            src = fh.read()
        try:
            modules[rel] = ModuleContext(ap, src, rel_path=rel)
        except SyntaxError as e:
            errors.append(Finding(
                rule="parse-error", severity=ERROR, path=rel,
                line=e.lineno or 1, col=e.offset or 0,
                message=f"syntax error: {e.msg}"))
    return ProjectContext(modules, errors)
