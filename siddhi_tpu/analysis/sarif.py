"""SARIF 2.1.0 serialization of lint findings.

One run, one tool (``siddhi-tpu-lint``), rule metadata pulled from the
registry (rationale as the short description, default severity as the
configuration level). Findings anchor as ``physicalLocation`` with a
repo-root-relative URI so CI viewers (GitHub code scanning et al.) can
jump to the line. Severity maps 1:1 — the linter's ``error``/``warning``
are already SARIF levels.
"""
from __future__ import annotations

import json
from typing import Iterable, Optional

from .findings import Finding
from .registry import rule_names, get_rule

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
TOOL_NAME = "siddhi-tpu-lint"


def _rule_meta(rule_id: str) -> dict:
    if rule_id in rule_names():
        r = get_rule(rule_id)
        return {
            "id": r.name,
            "shortDescription": {"text": r.rationale},
            "defaultConfiguration": {"level": r.severity},
        }
    # driver-synthesized ids that escaped registration
    return {"id": rule_id,
            "shortDescription": {"text": rule_id},
            "defaultConfiguration": {"level": "warning"}}


def to_sarif(findings: Iterable[Finding],
             root_uri: Optional[str] = None) -> dict:
    findings = list(findings)
    ids = sorted({f.rule for f in findings} | rule_names())
    rules = [_rule_meta(i) for i in ids]
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": index[f.rule],
            "level": f.severity,
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "REPOROOT",
                    },
                    "region": {
                        "startLine": max(1, f.line),
                        # SARIF columns are 1-based; ast cols are 0-based
                        "startColumn": f.col + 1,
                    },
                },
            }],
        })
    run = {
        "tool": {
            "driver": {
                "name": TOOL_NAME,
                "informationUri":
                    "https://example.invalid/siddhi-tpu/docs/tpu_hygiene",
                "rules": rules,
            },
        },
        "columnKind": "utf16CodeUnits",
        "results": results,
    }
    if root_uri:
        uri = root_uri if root_uri.endswith("/") else root_uri + "/"
        if not uri.startswith("file:"):
            uri = "file://" + uri
        run["originalUriBaseIds"] = {"REPOROOT": {"uri": uri}}
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [run],
    }


def write_sarif(path: str, findings: Iterable[Finding],
                root_uri: Optional[str] = None) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(to_sarif(findings, root_uri=root_uri), fh, indent=1)
        fh.write("\n")
