"""Compiled-program auditor: static jaxpr/StableHLO verification of the
step programs `CompileService.specs` enumerates (docs/tpu_hygiene.md
"Compiled-program audit").

PR 16's semantic lint verifies the Python *source*; nothing verified
what XLA actually *compiled*. This module walks every program an app
can dispatch — row/packed steps, fused chains, fan-out groups,
pattern/timer/due steps, join sides, partition triggers, and the
serving pool's vmapped tenant-axis dispatches — lowers each with
abstract `jax.ShapeDtypeStruct` arguments (`core/compile.py
abstract_spec_args()`: ZERO executions, ZERO device allocations, ZERO
new compiles; trace + lower never reach XLA's backend compiler) and
checks the artifact against four rules:

- ``program-donation-aliasing`` (ERROR): every ``donate_argnums``
  buffer must appear in the lowered input-output alias table. XLA
  reports donated-but-unusable buffers at lowering time; a silent
  aliasing failure means the state update COPIES instead of updating
  in place — the perf-bug class ``_fresh_device`` exists to dance
  around (core/runtime.py). Buffers under ``donate_min_bytes``
  (default 64 KiB, ``SIDDHI_TPU_AUDIT_DONATE_MIN``) are counted but
  not findings: tiny scalars fall below XLA's own aliasing floor.
- ``program-host-boundary`` (ERROR): no ``pure_callback`` /
  ``io_callback`` / ``debug_callback`` (``jax.debug.print``) ops may
  appear anywhere in a hot-path program's jaxpr — a host round-trip
  per dispatched chunk is a silent 1000x.
- ``program-dtype-drift`` (WARNING): no weak-typed outputs on programs
  whose inputs are strongly typed (every spec argument is). A weak
  output is a Python-scalar promotion leaking into the artifact: it
  destabilizes jit cache keys and widens dtypes downstream
  (docs/compile_cache.md). Strong float64 outputs from declared
  DOUBLE schema columns are legitimate Siddhi semantics (``avg(int)``
  returns double) and are surfaced as counters, not findings.
- ``program-memory-budget`` (ERROR): the static per-program
  live-buffer estimate (args + outputs + jaxpr constants) rolled up
  per app/pool must fit the ``@app:cap(program.mb=)`` dial when one is
  set; the top-N largest programs ride the summary either way.

Findings flow through the standard analysis machinery — severities,
baselines, pragmas and SARIF come from `findings.py` / `baseline.py` /
`sarif.py`; rule metadata is registered in `analysis/__init__.py`.
The audit summary is stored on the app's `CompileService` so
`statistics()['compile']['audit']` and `ExplainReport.programs` stay
zero-trace views (the PR 15 explain contract: live telemetry, never
hashed).
"""
from __future__ import annotations

import dataclasses
import math
import os
import re
import warnings
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .findings import ERROR, WARNING, Finding

# default ingest bucket the audit enumerates specs for when the app has
# no SIDDHI_TPU_WARM_BUCKETS configured: the bench/default dispatch cap
DEFAULT_AUDIT_BUCKET = 1024

# donated-but-unaliased buffers below this are counted, not findings
DEFAULT_DONATE_MIN_BYTES = 64 * 1024

RULE_DONATION = "program-donation-aliasing"
RULE_HOST = "program-host-boundary"
RULE_DTYPE = "program-dtype-drift"
RULE_BUDGET = "program-memory-budget"

PROGRAM_RULES = (RULE_DONATION, RULE_HOST, RULE_DTYPE, RULE_BUDGET)

# jaxpr primitives that cross the host boundary inside a compiled step
_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed",
})

_UNALIASED_RE = re.compile(r"ShapedArray\((\w+)\[([\d,]*)\]")


def donate_min_bytes_from_env() -> int:
    raw = os.environ.get("SIDDHI_TPU_AUDIT_DONATE_MIN", "")
    return int(raw) if raw else DEFAULT_DONATE_MIN_BYTES


# ---------------------------------------------------------------------------
# per-program audit
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ProgramAudit:
    """Static facts about one lowered step program."""

    key: str
    bytes_in: int = 0
    bytes_out: int = 0
    bytes_const: int = 0
    eqns: int = 0
    donated: int = 0            # donated argument buffers
    donated_bytes: int = 0
    unaliased: int = 0          # donated buffers XLA could not alias
    unaliased_bytes: int = 0
    weak_outputs: int = 0
    f64_outputs: int = 0
    error: Optional[str] = None  # spec failed to build/trace
    issues: list = dataclasses.field(default_factory=list)

    @property
    def bytes_total(self) -> int:
        return self.bytes_in + self.bytes_out + self.bytes_const


def _aval_bytes(aval) -> int:
    shape = tuple(getattr(aval, "shape", ()))
    dtype = np.dtype(getattr(aval, "dtype", np.int64))
    return int(math.prod(shape)) * dtype.itemsize


def _iter_eqns(jaxpr):
    """Every equation in a jaxpr, recursing through control-flow
    sub-jaxprs (scan/while/cond branches, closed calls)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            yield from _iter_param_eqns(v)


def _iter_param_eqns(v):
    if isinstance(v, jax.core.ClosedJaxpr):
        yield from _iter_eqns(v.jaxpr)
    elif isinstance(v, jax.core.Jaxpr):
        yield from _iter_eqns(v)
    elif isinstance(v, (tuple, list)):
        for item in v:
            yield from _iter_param_eqns(item)


def _as_struct(x) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(tuple(jnp.shape(x)), jnp.result_type(x))


def audit_spec(spec, donate_min_bytes: Optional[int] = None) -> ProgramAudit:
    """Trace + lower one CompileSpec abstractly and check the artifact.

    The builder runs inside `abstract_spec_args()` so its argument tree
    is pure `ShapeDtypeStruct`s — no device buffers, no fill programs.
    `fn.trace` gives the closed jaxpr (host-boundary / dtype / memory
    checks); `trace().lower()` runs only when the program donates
    buffers, and the donation-aliasing verdict comes from XLA's own
    "donated buffers were not usable" report captured at lowering.
    Neither step invokes the backend compiler: zero executables are
    built, the persistent-cache counters do not move.
    """
    from ..core.compile import abstract_spec_args
    if donate_min_bytes is None:
        donate_min_bytes = donate_min_bytes_from_env()
    pa = ProgramAudit(key=spec.key)
    try:
        with abstract_spec_args():
            fn, args = spec.build()
        if not hasattr(fn, "trace"):  # plain callable: wrap, no donation
            fn = jax.jit(fn)
        absargs = jax.tree_util.tree_map(_as_struct, args)
        with warnings.catch_warnings(record=True) as wlog:
            warnings.simplefilter("always")
            tr = fn.trace(*absargs)
            donated_flags = [
                bool(getattr(a, "donated", False))
                for a in jax.tree_util.tree_leaves(tr.args_info)]
            if any(donated_flags):
                tr.lower()  # aliasing is decided (and reported) here
    except Exception as e:  # noqa: BLE001 — an unbuildable spec is a
        # fact to report, not a crash: it would also fail to warm
        pa.error = f"{type(e).__name__}: {e}"
        return pa

    jx = tr.jaxpr
    in_avals = list(jx.in_avals)
    out_avals = list(jx.out_avals)
    pa.eqns = sum(1 for _ in _iter_eqns(jx.jaxpr))
    pa.bytes_in = sum(_aval_bytes(a) for a in in_avals)
    pa.bytes_out = sum(_aval_bytes(a) for a in out_avals)
    pa.bytes_const = sum(int(getattr(c, "nbytes", 0)) for c in jx.consts)

    # -- donation-aliasing ------------------------------------------------
    for flag, aval in zip(donated_flags, in_avals):
        if flag:
            pa.donated += 1
            pa.donated_bytes += _aval_bytes(aval)
    for w in wlog:
        msg = str(w.message)
        if "donated buffers were not usable" not in msg:
            continue
        for dt, shp in _UNALIASED_RE.findall(msg):
            shape = tuple(int(s) for s in shp.split(",") if s)
            nbytes = int(math.prod(shape)) * np.dtype(dt).itemsize
            pa.unaliased += 1
            pa.unaliased_bytes += nbytes
            if nbytes >= donate_min_bytes:
                pa.issues.append((RULE_DONATION, ERROR, (
                    f"{spec.key}: donated buffer {dt}[{shp}] "
                    f"({nbytes} bytes) is NOT in the lowered "
                    f"input-output alias table — the 'in-place' state "
                    f"update copies on every dispatch")))

    # -- host-boundary ----------------------------------------------------
    host_ops = sorted({eqn.primitive.name for eqn in _iter_eqns(jx.jaxpr)
                       if eqn.primitive.name in _CALLBACK_PRIMS})
    if host_ops:
        pa.issues.append((RULE_HOST, ERROR, (
            f"{spec.key}: host-boundary op(s) {', '.join(host_ops)} "
            f"baked into a jitted hot-path program — every dispatched "
            f"chunk round-trips to Python")))

    # -- dtype-drift ------------------------------------------------------
    in_f64 = any(np.dtype(getattr(a, "dtype", None)) == np.float64
                 for a in in_avals)
    for i, a in enumerate(out_avals):
        dt = np.dtype(getattr(a, "dtype", np.int64))
        if dt == np.float64:
            pa.f64_outputs += 1
        if getattr(a, "weak_type", False):
            pa.weak_outputs += 1
            extra = "" if in_f64 or dt != np.float64 else \
                " promoted to f64 from non-f64 inputs;"
            pa.issues.append((RULE_DTYPE, WARNING, (
                f"{spec.key}: output {i} is weak-typed {dt.name} —"
                f"{extra} a Python scalar leaked into the artifact; "
                f"jit cache keys and downstream dtypes drift "
                f"(docs/compile_cache.md)")))
    return pa


# ---------------------------------------------------------------------------
# app / pool rollup
# ---------------------------------------------------------------------------


class AuditReport:
    """Audit of one program set (an app runtime or a tenant pool):
    per-program facts, findings adapted to the analysis machinery, and
    a JSON-ready summary for statistics()/explain/bench."""

    def __init__(self, path: str, programs: list[ProgramAudit],
                 budget_mb: Optional[float] = None,
                 attribution: Optional[dict] = None,
                 disabled: Iterable[str] = (), top_n: int = 5):
        self.path = path
        self.programs = programs
        self.budget_mb = budget_mb
        self.attribution = dict(attribution or {})
        self.top_n = top_n
        disabled = set(disabled)
        issues = [iss for p in programs for iss in p.issues]
        total_mb = self.bytes_est_total / 1e6
        if budget_mb is not None and total_mb > float(budget_mb):
            top = ", ".join(f"{p.key}={p.bytes_total / 1e6:.1f}MB"
                            for p in self.top_programs())
            issues.append((RULE_BUDGET, ERROR, (
                f"program set estimates {total_mb:.1f}MB live buffers "
                f"vs @app:cap(program.mb={budget_mb}) — largest: "
                f"{top}")))
        self.findings = [
            Finding(rule=rule, severity=sev, path=path, line=1, col=0,
                    message=msg)
            for rule, sev, msg in issues
            if rule not in disabled and "*" not in disabled]

    @property
    def bytes_est_total(self) -> int:
        return sum(p.bytes_total for p in self.programs)

    def top_programs(self) -> list[ProgramAudit]:
        return sorted(self.programs, key=lambda p: -p.bytes_total)[
            : self.top_n]

    def summary(self) -> dict:
        """The block stored on CompileService.audit: rides
        statistics()['compile']['audit'], ExplainReport.programs and
        each bench config's JSON line. Live view — never hashed."""
        out = {
            "programs": len(self.programs),
            "bytes_est_total": self.bytes_est_total,
            "findings": len(self.findings),
            "donated": sum(p.donated for p in self.programs),
            "unaliased": sum(p.unaliased for p in self.programs),
            "weak_outputs": sum(p.weak_outputs for p in self.programs),
            "f64_outputs": sum(p.f64_outputs for p in self.programs),
            "top": [{"step": self._owned(p.key),
                     "mb": round(p.bytes_total / 1e6, 3)}
                    for p in self.top_programs()],
        }
        if self.budget_mb is not None:
            out["budget_mb"] = float(self.budget_mb)
        errors = [{"step": p.key, "error": p.error}
                  for p in self.programs if p.error]
        if errors:
            out["errors"] = errors
        return out

    def _owned(self, key: str) -> str:
        """Label a program with the member queries it serves (fan-out
        groups and fused chains compile under one key —
        plan/optimizer.py program_attribution)."""
        prefix = key.split("/", 1)[0]
        members = self.attribution.get(prefix)
        if members:
            return f"{key} [{'+'.join(members)}]"
        return key


def audit_specs(specs: list, *, path: str,
                budget_mb: Optional[float] = None,
                donate_min_bytes: Optional[int] = None,
                attribution: Optional[dict] = None,
                disabled: Iterable[str] = (),
                top_n: int = 5) -> AuditReport:
    """Audit an explicit spec list (the engine behind audit_runtime /
    audit_pool / the fixture mode of tools/audit.py)."""
    programs = [audit_spec(s, donate_min_bytes=donate_min_bytes)
                for s in specs]
    return AuditReport(path, programs, budget_mb=budget_mb,
                       attribution=attribution, disabled=disabled,
                       top_n=top_n)


def _budget_from_ast(app_ast) -> Optional[float]:
    """The @app:cap(program.mb=) dial, when the app sets one."""
    from ..lang import ast as A
    try:
        cap = A.find_annotation(app_ast.annotations, "cap")
        if cap is not None:
            raw = cap.element("program.mb")
            if raw is not None:
                return float(raw)
    except Exception:  # noqa: BLE001 — a malformed dial is a plan-rule
        return None    # problem, not an audit crash
    return None


def audit_runtime(rt, buckets=None, samples=None, *,
                  path: Optional[str] = None,
                  budget_mb: Optional[float] = None,
                  donate_min_bytes: Optional[int] = None,
                  disabled: Iterable[str] = (),
                  top_n: int = 5, store: bool = True) -> AuditReport:
    """Audit every program a SiddhiAppRuntime can dispatch for the
    given ingest buckets (default: SIDDHI_TPU_WARM_BUCKETS, else 1024).
    Zero executions, zero compiles, zero device reads; the summary is
    stored on the runtime's CompileService (`store=False` to skip)."""
    from ..core.compile import warm_buckets_from_env
    from ..plan.optimizer import program_attribution
    if not rt.running and rt._opt_decisions is None:
        # segments/groups must exist so the audited programs are the
        # ones traffic will dispatch (the warmup() contract). Skip when
        # a plan is already installed: REBUILDING drops the fused-chain
        # objects and their cached jit wrappers, and a warmed runtime's
        # audit must construct zero new ones
        rt._build_fused_chains()
    if buckets is None:
        buckets = warm_buckets_from_env() or (DEFAULT_AUDIT_BUCKET,)
    specs = rt.compile_service.specs(buckets, samples=samples)
    if budget_mb is None:
        budget_mb = _budget_from_ast(rt.ast)
    rep = audit_specs(
        specs, path=path or f"app/{rt.name}", budget_mb=budget_mb,
        donate_min_bytes=donate_min_bytes,
        attribution=program_attribution(rt), disabled=disabled,
        top_n=top_n)
    if store:
        rt.compile_service.audit = rep.summary()
    return rep


def audit_pool(pool, caps=None, *,
               path: Optional[str] = None,
               budget_mb: Optional[float] = None,
               donate_min_bytes: Optional[int] = None,
               disabled: Iterable[str] = (),
               top_n: int = 5, store: bool = True) -> AuditReport:
    """Audit a TenantPool's vmapped tenant-axis programs (the same
    template-keyed specs warmup() compiles — serving/pool.py). On mesh
    pools the audit sees the single-device twin of each program: slot
    placement needs concrete buffers, and the audit never builds any."""
    if budget_mb is None:
        budget_mb = _budget_from_ast(pool.proto.ast)
    specs = pool._warm_spec_list(caps)
    rep = audit_specs(
        specs, path=path or f"pool/{pool.name}", budget_mb=budget_mb,
        donate_min_bytes=donate_min_bytes, disabled=disabled,
        top_n=top_n)
    if store:
        pool.proto.compile_service.audit = rep.summary()
    return rep
