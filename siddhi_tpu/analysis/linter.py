"""AST lint driver: parse a module once, hand every rule a ModuleContext.

The context pre-computes everything the TPU-hygiene rules keep asking
for — canonical dotted names across import aliases (``jnp.zeros`` /
``jax.numpy.zeros`` / ``from jax import numpy as jnp`` all normalize to
``("jax", "numpy", "zeros")``), a child->parent map, which functions are
jit-compiled, and which source lines carry ``# lint: disable=`` pragmas
— so individual rules stay ~20 lines of pattern matching.

Suppressions (written as ``#``-comments; the marker is elided here so
the examples don't register as real pragmas in this module):
  ``lint: disable=rule-a,rule-b``   suppress those rules on this line
  ``lint: disable=*``               suppress everything on this line
  ``lint: disable-file=rule-a``     suppress a rule for the whole file
"""
from __future__ import annotations

import ast
import os
import re
from collections import deque
from typing import Iterable, Iterator, Optional

from .findings import ERROR, Finding
from .registry import module_rules

_PRAGMA = re.compile(r"#\s*lint:\s*disable(?P<scope>-file)?\s*=\s*"
                     r"(?P<rules>[\w*,\- ]+)")

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_COMPS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _dotted(node: ast.AST) -> Optional[tuple[str, ...]]:
    """Name/Attribute chain -> ("a", "b", "c") for a.b.c, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class ModuleContext:
    def __init__(self, path: str, source: str, rel_path: Optional[str] = None):
        self.path = rel_path or path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        self.parents: dict[int, ast.AST] = {}
        # one BFS (same order as ast.walk) builds both the parent map
        # and the flat node list every rule iterates — re-walking an
        # 80-module tree once per rule is where whole-repo lint time
        # goes
        self.nodes: list[ast.AST] = []
        todo = deque([self.tree])
        while todo:
            parent = todo.popleft()
            self.nodes.append(parent)
            for child in ast.iter_child_nodes(parent):
                self.parents[id(child)] = parent
                todo.append(child)
        self.alias_map = self._build_alias_map()
        self.line_disables, self.file_disables = self._scan_pragmas()
        self._jitted = self._find_jitted_functions()
        # which pragma tokens actually suppressed something — the
        # stale-pragma audit reads these after all passes ran
        self.used_line: set[tuple[int, str]] = set()
        self.used_file: set[str] = set()

    # -- imports / canonical names ------------------------------------
    def _build_alias_map(self) -> dict[str, tuple[str, ...]]:
        amap: dict[str, tuple[str, ...]] = {}
        for node in self.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    parts = tuple(a.name.split("."))
                    if a.asname:
                        amap[a.asname] = parts
                    else:
                        # `import jax.numpy` binds only the root name
                        amap.setdefault(parts[0], (parts[0],))
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                base = tuple(node.module.split("."))
                for a in node.names:
                    amap[a.asname or a.name] = base + (a.name,)
        return amap

    def canon(self, node: ast.AST) -> Optional[tuple[str, ...]]:
        """Canonical dotted name of a Name/Attribute chain, resolving
        import aliases (jnp.x -> ("jax","numpy","x"))."""
        d = _dotted(node)
        if d is None:
            return None
        head = self.alias_map.get(d[0])
        return head + d[1:] if head else d

    # -- pragmas -------------------------------------------------------
    def _scan_pragmas(self):
        line_dis: dict[int, set[str]] = {}
        file_dis: set[str] = set()
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group("rules").split(",")
                     if r.strip()}
            if m.group("scope"):
                file_dis |= rules
            else:
                line_dis.setdefault(i, set()).update(rules)
        return line_dis, file_dis

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_disables:
            self.used_file.add(finding.rule)
            return True
        if "*" in self.file_disables:
            self.used_file.add("*")
            return True
        dis = self.line_disables.get(finding.line, ())
        if finding.rule in dis:
            self.used_line.add((finding.line, finding.rule))
            return True
        if "*" in dis:
            self.used_line.add((finding.line, "*"))
            return True
        return False

    # -- structural helpers -------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, _FUNCS):
                return anc
        return None

    def in_loop(self, node: ast.AST) -> bool:
        """True when `node` re-executes per iteration of an enclosing
        Python loop or comprehension within the same function body."""
        prev = node
        for anc in self.ancestors(node):
            if isinstance(anc, _FUNCS):
                return False
            if isinstance(anc, ast.For) and prev is not anc.iter:
                return True  # the For's own iterable runs once
            if isinstance(anc, (ast.AsyncFor, ast.While)):
                return True
            if isinstance(anc, ast.comprehension):
                comp = self.parent(anc)
                first = getattr(comp, "generators", [None])[0]
                if not (anc is first and prev is anc.iter):
                    return True  # only the first source evaluates once
            elif isinstance(anc, _COMPS):
                if prev not in anc.generators:
                    return True  # elt/key/value runs per iteration
            prev = anc
        return False

    def at_module_scope(self, node: ast.AST) -> bool:
        """Executed at import time (module body, incl. module-level ifs
        and class bodies — anything outside a def/lambda)."""
        return self.enclosing_function(node) is None

    # -- jit detection -------------------------------------------------
    def _is_jit_expr(self, node: ast.AST) -> bool:
        c = self.canon(node)
        if c == ("jax", "jit"):
            return True
        if isinstance(node, ast.Call):
            fc = self.canon(node.func)
            if fc == ("jax", "jit"):
                return True
            if fc == ("functools", "partial") and node.args \
                    and self.canon(node.args[0]) == ("jax", "jit"):
                return True
        return False

    def _find_jitted_functions(self) -> set[int]:
        by_name: dict[str, list[ast.AST]] = {}
        jitted: set[int] = set()
        for node in self.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(node.name, []).append(node)
                if any(self._is_jit_expr(d) for d in node.decorator_list):
                    jitted.add(id(node))
        # `stepf = jax.jit(step)` style wrapping of a local function
        for node in self.nodes:
            if isinstance(node, ast.Call) \
                    and self.canon(node.func) == ("jax", "jit"):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        for fn in by_name.get(arg.id, ()):
                            jitted.add(id(fn))
        return jitted

    def is_jitted(self, fn_node: ast.AST) -> bool:
        return id(fn_node) in self._jitted

    def enclosing_jitted_function(self, node: ast.AST):
        for anc in self.ancestors(node):
            if isinstance(anc, _FUNCS) and self.is_jitted(anc):
                return anc
        return None


# ---------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                rel_path: Optional[str] = None,
                rules: Optional[Iterable[str]] = None) -> list[Finding]:
    try:
        ctx = ModuleContext(path, source, rel_path=rel_path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity=ERROR,
                        path=rel_path or path, line=e.lineno or 1,
                        col=e.offset or 0, message=f"syntax error: {e.msg}")]
    wanted = set(rules) if rules is not None else None
    out: list[Finding] = []
    for rule in module_rules():
        if wanted is not None and rule.name not in wanted:
            continue
        for f in rule.check(ctx):
            if not ctx.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_file(path: str, rel_path: Optional[str] = None,
              rules: Optional[Iterable[str]] = None) -> list[Finding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path=path, rel_path=rel_path,
                           rules=rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               rules: Optional[Iterable[str]] = None) -> list[Finding]:
    """Lint every .py file under `paths`; paths in findings are made
    relative to `root` (default: cwd) for stable baseline keys."""
    base = os.path.abspath(root or os.getcwd())
    out: list[Finding] = []
    for path in iter_python_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, base)
        rel = rel.replace(os.sep, "/")
        out.extend(lint_file(ap, rel_path=rel, rules=rules))
    return out
