"""Static schema model for the query-dataflow type checker.

A ``Schema`` is the static twin of ``core/event.py StreamSchema``: the
``(name, AttrType)`` shape of one stream, table, window or trigger, with
one extension — an attribute's type may be ``None`` ("unknown"), which
is how the checker degrades gracefully around constructs it cannot type
statically (extension stream processors, aggregation references, UDFs
without declared return types). Unknown types propagate and suppress
downstream diagnostics instead of guessing.

This module also centralizes the *operator typing rules* the runtime
applies piecemeal at compile time, so the static pass and the executors
share one table instead of drifting apart:

- numeric promotion / coercion / comparability live in
  ``core/types.py`` (``promote``, ``can_coerce``, ``comparable``);
- aggregator result types (``avg -> DOUBLE``, ``count -> LONG``, …)
  live here in ``aggregator_result_type`` and are consumed by
  ``ops/aggregators.py`` when it builds the real AggSpec executors.

Everything here is import-light (stdlib + core.types, no jax) so the
lint CLI can type-check ``.siddhi`` files without touching a device
runtime.
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

from ..core.types import AttrType, NUMERIC_TYPES

# ---------------------------------------------------------------------------
# Schema
# ---------------------------------------------------------------------------

# how a schema became known — definitions are authoritative, inferred
# schemas come from insert-into propagation
DEFINED = "defined"
INFERRED = "inferred"
BUILTIN = "builtin"


@dataclasses.dataclass(frozen=True)
class Schema:
    """Static shape of one stream-like source. ``types[i] is None``
    means "statically unknown" and suppresses dependent checks."""

    stream_id: str
    attrs: tuple[tuple[str, Optional[AttrType]], ...]
    source: str = DEFINED
    line: Optional[int] = None  # definition/first-producer source line

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(n for n, _ in self.attrs)

    @property
    def types(self) -> tuple[Optional[AttrType], ...]:
        return tuple(t for _, t in self.attrs)

    @property
    def fully_known(self) -> bool:
        return all(t is not None for _, t in self.attrs)

    def get(self, name: str) -> Optional[AttrType]:
        """Type of attribute `name`; KeyError when absent (first match
        wins, like StreamSchema.index_of)."""
        for n, t in self.attrs:
            if n == name:
                return t
        raise KeyError(name)

    def has(self, name: str) -> bool:
        return any(n == name for n, _ in self.attrs)

    def render(self) -> str:
        body = ", ".join(
            f"{n} {t.value if t is not None else '?'}" for n, t in self.attrs)
        return f"({body})"


def schema_from_attribute_defs(stream_id: str, attribute_defs: Iterable,
                               source: str = DEFINED,
                               line: Optional[int] = None) -> Schema:
    """Schema from a definition's list of lang.ast.AttributeDef."""
    return Schema(stream_id,
                  tuple((a.name, a.type) for a in attribute_defs),
                  source=source, line=line)


# ---------------------------------------------------------------------------
# Aggregator typing rules
# ---------------------------------------------------------------------------

# the aggregator names ops/selector.py recognizes in select clauses;
# re-declared here (strings only) so the static pass does not import the
# jax-heavy executor module — ops/selector.py asserts equality in tier-1
AGGREGATOR_NAMES = frozenset({
    "sum", "avg", "count", "distinctcount", "min", "max", "minforever",
    "maxforever", "stddev", "and", "or", "unionset",
})

# input-domain of each aggregator: the static twin of the constructor
# checks in ops/aggregators.py (SumAgg raises on non-numeric, BoolAgg on
# non-BOOL, UnionSetAgg on non-OBJECT). None = any input accepted.
AGGREGATOR_INPUT: dict[str, Optional[tuple[AttrType, ...]]] = {
    "sum": NUMERIC_TYPES, "avg": NUMERIC_TYPES, "stddev": NUMERIC_TYPES,
    "min": NUMERIC_TYPES, "max": NUMERIC_TYPES,
    "minforever": NUMERIC_TYPES, "maxforever": NUMERIC_TYPES,
    "and": (AttrType.BOOL,), "or": (AttrType.BOOL,),
    "unionset": (AttrType.OBJECT,),
    "count": None, "distinctcount": None,
}


def aggregator_result_type(name: str,
                           arg: Optional[AttrType]) -> Optional[AttrType]:
    """Result type of aggregator `name` over an argument of type `arg`.

    The single source of truth for aggregator result typing:
    ``ops/aggregators.py`` AggSpec constructors call this, and the
    static type checker mirrors it at parse time. Returns None when the
    result cannot be determined (unknown arg for an arg-dependent
    aggregator, or an unknown aggregator name).
    """
    key = name.lower()
    if key == "count":
        return AttrType.LONG
    if key == "distinctcount":
        return AttrType.LONG
    if key in ("avg", "stddev"):
        return AttrType.DOUBLE
    if key == "sum":
        if arg in (AttrType.INT, AttrType.LONG):
            return AttrType.LONG
        if arg in (AttrType.FLOAT, AttrType.DOUBLE):
            return AttrType.DOUBLE
        return None
    if key in ("min", "max", "minforever", "maxforever"):
        return arg if arg in NUMERIC_TYPES else None
    if key in ("and", "or"):
        return AttrType.BOOL
    if key == "unionset":
        return AttrType.OBJECT
    return None


def aggregator_accepts(name: str, arg: Optional[AttrType]) -> bool:
    """Whether `arg` is in the aggregator's input domain (unknown args
    are always accepted — the checker never guesses)."""
    if arg is None:
        return True
    domain = AGGREGATOR_INPUT.get(name.lower())
    return domain is None or arg in domain


# ---------------------------------------------------------------------------
# Insert-into compatibility
# ---------------------------------------------------------------------------

OK = "ok"
COERCE = "coerce"      # numeric widening the runtime still rejects today,
                       # but is semantically sound — warning severity
MISMATCH = "mismatch"  # non-coercible dtype pair — definite error
UNKNOWN = "unknown"    # one side statically unknown — no diagnosis


def insert_compat(src: Optional[AttrType],
                  dst: Optional[AttrType]) -> str:
    """Classify one (produced, declared) attribute-type pair of an
    insert-into edge."""
    from ..core.types import can_coerce
    if src is None or dst is None:
        return UNKNOWN
    if src is dst:
        return OK
    if can_coerce(src, dst):
        return COERCE
    return MISMATCH
